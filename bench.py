"""Benchmark: electron wall-clock + dispatch overhead (BASELINE.json metric).

Runs the north-star workloads end-to-end through the REAL framework path —
workflow dispatch -> TPUExecutor -> staged harness subprocess -> result
fetch — on whatever accelerator is present (the driver runs this on TPU).

Output protocol: one JSON line **per phase as it completes** (so a timeout
preserves partial progress in the driver's output tail), then ONE final
combined JSON line with ``{"metric", "value", "unit", "vs_baseline"}`` last.
``value`` is the median per-electron dispatch overhead in seconds; the
reference's own defaults bound its per-electron overhead at >= its 15 s poll
interval + ~10 sequential SSH round-trips (BASELINE.md; reference ssh.py:87
poll_freq=15, SURVEY §3.1), and the north star demands < 2 s, so
``vs_baseline`` is target/actual: 2.0 / value (> 1 beats the target).

Structure (fixes the round-1 rc-124 empty bench):
  * the bench parent process NEVER imports jax — only harness subprocesses
    touch the accelerator, so a hanging backend init can't take down the
    whole script;
  * all accelerator work runs in ONE combined electron, paying TPU backend
    init exactly once; the electron streams per-subphase JSON lines to a
    progress file which the parent tails and re-emits live;
  * every phase runs under its own wall-clock budget and is skipped (with
    an error line) on overrun, never aborting the phases after it.
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from covalent_tpu_plugin import TPUExecutor  # noqa: E402

OVERHEAD_PROBES = 5
#: Phase selection (CI smoke runs pick a subset: the full TPU phase needs
#: an accelerator + minutes of budget, the dispatch phases need neither).
BENCH_PHASES = {
    phase.strip()
    for phase in os.environ.get(
        "BENCH_PHASES",
        "overhead,obs_tax,fanout,cached_fanout,bundled_fanout,"
        "rpc_overhead,serve_traffic,serve_scale,serve_disagg,serve_spec,"
        "serve_multilora,"
        "gray_failure,chaos_fanout,preemption_chaos,dispatcher_crash,"
        "sched_fanout,"
        "traffic_ramp,tpu",
    ).split(",")
    if phase.strip()
}
# Per-phase wall budgets (s).  The accelerator phase dominates: it absorbs
# one cold TPU backend init (minutes on some PJRT plugins) plus the compute
# sub-phases, each of which self-skips as the electron's deadline nears.
OVERHEAD_BUDGET_S = float(os.environ.get("BENCH_OVERHEAD_BUDGET_S", "60"))
FANOUT_BUDGET_S = float(os.environ.get("BENCH_FANOUT_BUDGET_S", "45"))
#: SLO asserted on the overhead phase: p95 of per-electron wall overhead
#: (elapsed minus execute) must stay under the north-star dispatch budget.
WALL_OVERHEAD_BUDGET_S = float(
    os.environ.get("BENCH_WALL_OVERHEAD_BUDGET_S", "2.0")
)
#: SLO asserted on the obs_tax phase: full telemetry (events stream +
#: heartbeats + ops endpoint) may cost at most this fraction of obs-off
#: wall time per electron (plus a small absolute floor for timer noise).
OBS_TAX_BUDGET_PCT = float(os.environ.get("BENCH_OBS_TAX_BUDGET_PCT", "3.0"))
#: SLO asserted on the rpc_overhead phase: median per-electron wall
#: overhead in RPC mode (warm resident runtime, execute-by-digest) must
#: stay under this many seconds — the ROADMAP item-3 sub-100ms target.
RPC_OVERHEAD_BUDGET_S = float(
    os.environ.get("BENCH_RPC_OVERHEAD_BUDGET_S", "0.1")
)
#: serve_traffic phase knobs: request count, the simulated model
#: load+compile each per-electron call pays (the cost a resident session
#: amortizes), per-decode-chunk latency, tokens per request, and the SLO —
#: the resident arm's p50 request latency must beat the per-electron arm's
#: by at least this factor (and its aggregate tokens/s must be higher).
SERVE_REQUESTS = int(os.environ.get("BENCH_SERVE_REQUESTS", "16"))
SERVE_LOAD_S = float(os.environ.get("BENCH_SERVE_LOAD_S", "0.25"))
SERVE_STEP_S = float(os.environ.get("BENCH_SERVE_STEP_S", "0.01"))
SERVE_TOKENS = int(os.environ.get("BENCH_SERVE_TOKENS", "8"))
SERVE_SPEEDUP_MIN = float(os.environ.get("BENCH_SERVE_SPEEDUP_MIN", "1.5"))
SERVE_BUDGET_S = float(os.environ.get("BENCH_SERVE_BUDGET_S", "90"))
#: serve_scale phase knobs: replica count for the scaled arm, offered
#: load (held constant across arms), per-decode-chunk step time, and the
#: SLOs — aggregate tokens/s must scale by >= SERVE_SCALE_MIN from 1 to
#: SERVE_SCALE_REPLICAS replicas, p99 at N must not regress vs N=1 under
#: the same offered load, and the router's median per-request decision
#: must stay under ROUTER_DECISION_BUDGET_S.
SERVE_SCALE_REPLICAS = int(os.environ.get("BENCH_SERVE_SCALE_REPLICAS", "4"))
SERVE_SCALE_REQUESTS = int(os.environ.get("BENCH_SERVE_SCALE_REQUESTS", "32"))
SERVE_SCALE_TOKENS = int(os.environ.get("BENCH_SERVE_SCALE_TOKENS", "12"))
SERVE_SCALE_STEP_S = float(
    os.environ.get("BENCH_SERVE_SCALE_STEP_S", "0.08")
)
SERVE_SCALE_MIN = float(os.environ.get("BENCH_SERVE_SCALE_MIN", "3.0"))
SERVE_SCALE_BUDGET_S = float(
    os.environ.get("BENCH_SERVE_SCALE_BUDGET_S", "150")
)
ROUTER_DECISION_BUDGET_S = float(
    os.environ.get("BENCH_ROUTER_DECISION_BUDGET_S", "0.001")
)
#: serve_disagg phase knobs: mixed short/long-prompt traffic through the
#: SAME decode tier with and without a prefill tier in front.  Long
#: prompts cost prefill_s_per_tok * len of ENGINE-LOOP time at admission
#: (the compute disaggregation moves off the decode tier); arrivals are
#: open-loop so prefill work genuinely overlaps decode.  SLOs: decode
#: tokens/s with the prefill tier must not be lower (no_slower, CI) —
#: and is expected to beat the fused arm — with every stream byte-equal
#: across arms and KV transfer bytes + p50 latency accounted.
SERVE_DISAGG_DECODE = int(os.environ.get("BENCH_SERVE_DISAGG_DECODE", "2"))
SERVE_DISAGG_REQUESTS = int(
    os.environ.get("BENCH_SERVE_DISAGG_REQUESTS", "18")
)
SERVE_DISAGG_TOKENS = int(os.environ.get("BENCH_SERVE_DISAGG_TOKENS", "16"))
SERVE_DISAGG_STEP_S = float(
    os.environ.get("BENCH_SERVE_DISAGG_STEP_S", "0.04")
)
SERVE_DISAGG_LONG_PROMPT = int(
    os.environ.get("BENCH_SERVE_DISAGG_LONG_PROMPT", "32")
)
SERVE_DISAGG_PREFILL_S_PER_TOK = float(
    os.environ.get("BENCH_SERVE_DISAGG_PREFILL_S_PER_TOK", "0.01")
)
SERVE_DISAGG_ARRIVAL_S = float(
    os.environ.get("BENCH_SERVE_DISAGG_ARRIVAL_S", "0.08")
)
SERVE_DISAGG_BUDGET_S = float(
    os.environ.get("BENCH_SERVE_DISAGG_BUDGET_S", "150")
)
#: serve_spec phase knobs: open-loop load through three REAL
#: ContinuousEngine arms inside one worker (the bench parent never
#: imports jax) — fp, fp+draft (speculative), and a kv_quant lane group
#: driven by the per-request ``quality`` knob, all greedy.  The draft is
#: a 1-layer model sharing the target's embed/unembed/layer-0 weights
#: while the target's upper layers have their residual contributions
#: zeroed, so draft and target argmax agree by construction (accept rate
#: 1.0) and the measured speedup isolates the verify-slab amortization
#: (draft_len+1 tokens per fused target pass vs 1 per plain step).
#: SLOs: the spec arm's greedy streams byte-equal the fp arm's, and its
#: aggregate tokens/s beats fp by >= SERVE_SPEC_SPEEDUP_MIN.
SERVE_SPEC_REQUESTS = int(os.environ.get("BENCH_SERVE_SPEC_REQUESTS", "8"))
SERVE_SPEC_TOKENS = int(os.environ.get("BENCH_SERVE_SPEC_TOKENS", "48"))
SERVE_SPEC_DRAFT_LEN = int(os.environ.get("BENCH_SERVE_SPEC_DRAFT_LEN", "6"))
SERVE_SPEC_LAYERS = int(os.environ.get("BENCH_SERVE_SPEC_LAYERS", "6"))
SERVE_SPEC_SPEEDUP_MIN = float(
    os.environ.get("BENCH_SERVE_SPEC_SPEEDUP_MIN", "1.5")
)
SERVE_SPEC_BUDGET_S = float(
    os.environ.get("BENCH_SERVE_SPEC_BUDGET_S", "240")
)
#: serve_multilora phase knobs: the SAME mixed multi-tenant load (a
#: round-robin of MULTILORA_ADAPTERS distinct LoRA adapters over one
#: shared base model) offered to ONE multiplexed engine (the adapter
#: bank: every wave gathers each lane's adapter inside the compiled
#: step, so all tenants co-batch) and to per-adapter single-tenant
#: engines time-sharing the same device (each sees only its adapter's
#: quarter of the traffic, so its batches run 1/N full and the device
#: serializes N engines' decode waves).  SLOs: every stream byte-equal
#: across arms (slot-0 identity / bank-gather exactness), aggregate
#: multiplexed tokens/s >= MULTILORA_SPEEDUP_MIN x the single-tenant
#: aggregate, and a mid-phase hot swap of one adapter finishes every
#: in-flight stream on the OLD generation while new admissions decode
#: the new one — zero drops, zero sheds.
MULTILORA_ADAPTERS = int(os.environ.get("BENCH_MULTILORA_ADAPTERS", "4"))
MULTILORA_REQUESTS = int(os.environ.get("BENCH_MULTILORA_REQUESTS", "32"))
MULTILORA_TOKENS = int(os.environ.get("BENCH_MULTILORA_TOKENS", "32"))
MULTILORA_RANK = int(os.environ.get("BENCH_MULTILORA_RANK", "4"))
MULTILORA_LAYERS = int(os.environ.get("BENCH_MULTILORA_LAYERS", "4"))
MULTILORA_SPEEDUP_MIN = float(
    os.environ.get("BENCH_MULTILORA_SPEEDUP_MIN", "1.3")
)
MULTILORA_BUDGET_S = float(
    os.environ.get("BENCH_MULTILORA_BUDGET_S", "240")
)
#: gray_failure phase knobs: three replica-set arms under the SAME
#: open-loop load — healthy (3 good replicas), brownout-unhedged (one
#: replica slowed GRAY_SLOW_S per engine step via worker-side chaos,
#: health scoring + hedging OFF: the pre-defense baseline), and
#: brownout-hedged (same brownout, full gray-failure defense ON).
#: SLOs: the hedged arm's measured p99 stays within GRAY_HEDGED_MAX of
#: the healthy arm's (floored at GRAY_P99_FLOOR_S against timer noise)
#: while the unhedged arm degrades by at least GRAY_UNHEDGED_MIN; every
#: stream byte-equal across all arms; zero requests shed; hedges fired.
GRAY_REQUESTS = int(os.environ.get("BENCH_GRAY_REQUESTS", "16"))
GRAY_WARMUP = int(os.environ.get("BENCH_GRAY_WARMUP", "12"))
GRAY_TOKENS = int(os.environ.get("BENCH_GRAY_TOKENS", "12"))
GRAY_STEP_S = float(os.environ.get("BENCH_GRAY_STEP_S", "0.04"))
GRAY_SLOW_S = float(os.environ.get("BENCH_GRAY_SLOW_S", "2.0"))
GRAY_ARRIVAL_S = float(os.environ.get("BENCH_GRAY_ARRIVAL_S", "0.03"))
GRAY_HEDGED_MAX = float(os.environ.get("BENCH_GRAY_HEDGED_MAX", "1.5"))
GRAY_UNHEDGED_MIN = float(os.environ.get("BENCH_GRAY_UNHEDGED_MIN", "2.0"))
GRAY_P99_FLOOR_S = float(os.environ.get("BENCH_GRAY_P99_FLOOR_S", "0.3"))
GRAY_BUDGET_S = float(os.environ.get("BENCH_GRAY_BUDGET_S", "240"))
#: traffic_ramp phase knobs: the SAME ramping open-loop load (a light
#: warm phase, a surge past one replica's throughput, a cool tail)
#: offered to a statically over-provisioned replica set and to a
#: 1-replica set under the closed-loop AutoscaleController with a
#: deliberately tight injected latency SLO.  Asserted: the injected burn
#: fires on the autoscaled arm and CLEARS after the controller's
#: scale-up, the autoscaled arm consumes measurably fewer warm
#: gang-seconds (live replicas integrated over the run) than the static
#: arm, and its p95 holds within RAMP_P95_MARGIN_S of the static arm's
#: (one decode chunk of queueing during the reaction window).
RAMP_REPLICAS_MAX = int(os.environ.get("BENCH_RAMP_REPLICAS_MAX", "3"))
RAMP_TOKENS = int(os.environ.get("BENCH_RAMP_TOKENS", "8"))
RAMP_STEP_S = float(os.environ.get("BENCH_RAMP_STEP_S", "0.05"))
RAMP_WARM_REQUESTS = int(os.environ.get("BENCH_RAMP_WARM_REQUESTS", "16"))
RAMP_WARM_INTERVAL_S = float(
    os.environ.get("BENCH_RAMP_WARM_INTERVAL_S", "0.4")
)
#: The surge is a STEP (start == end), not a gradual ramp: a gradual
#: acceleration gives the in-flight trend enough warning that the
#: controller scales before a single request queues (measured: max
#: latency 0.234s vs the 0.45s threshold — no burn to clear).  The step
#: is the injection: ~14 req/s against one replica's ~10 req/s ceiling
#: with zero trend warning, so the tight SLO below provably burns, the
#: burn hook drives the scale-up, and the cool tail clears it.
RAMP_SURGE_REQUESTS = int(os.environ.get("BENCH_RAMP_SURGE_REQUESTS", "24"))
RAMP_SURGE_START_S = float(
    os.environ.get("BENCH_RAMP_SURGE_START_S", "0.085")
)
RAMP_SURGE_END_S = float(os.environ.get("BENCH_RAMP_SURGE_END_S", "0.085"))
RAMP_COOL_REQUESTS = int(os.environ.get("BENCH_RAMP_COOL_REQUESTS", "14"))
RAMP_COOL_INTERVAL_S = float(
    os.environ.get("BENCH_RAMP_COOL_INTERVAL_S", "0.35")
)
#: Injected SLO: threshold 0.2 snaps to the 0.25s histogram bucket —
#: one queued decode chunk past the ~0.2s nominal service time is
#: already "bad" — and the 0.9 objective burns at >10% bad in-window.
RAMP_SLO_THRESHOLD_S = float(
    os.environ.get("BENCH_RAMP_SLO_THRESHOLD_S", "0.2")
)
RAMP_SLO_OBJECTIVE = float(os.environ.get("BENCH_RAMP_SLO_OBJECTIVE", "0.9"))
RAMP_LEAD_S = float(os.environ.get("BENCH_RAMP_LEAD_S", "1.5"))
RAMP_P95_MARGIN_S = float(os.environ.get("BENCH_RAMP_P95_MARGIN_S", "0.25"))
RAMP_GANG_RATIO_MAX = float(
    os.environ.get("BENCH_RAMP_GANG_RATIO_MAX", "0.85")
)
RAMP_BUDGET_S = float(os.environ.get("BENCH_RAMP_BUDGET_S", "150"))
# 570 (was 360, 480, then 540): the r4 TPU run showed the phase list
# needs ~450 s cold (tunnel compiles dominate; the persistent cache
# roughly halves a warm run) — 360 skipped lm_spec, and 480 left a warm
# run ~40 s short of the lm_serve tail phase; round 5 adds the
# lm_step_fused arm (~30 s incl. one compile), covered by +30 here so
# the tail phases keep their r4 headroom.  The preflight gate means a
# DEAD tunnel exits in minutes regardless, so the budget only bounds
# the healthy path.
TPU_BUDGET_S = float(os.environ.get("BENCH_TPU_BUDGET_S", "570"))
#: Persistent XLA compilation cache shared across bench runs (and with the
#: driver's run): compiles over the tunneled backend cost tens of seconds
#: each, and they dominate the accelerator-phase budget on a cold cache.
#: Per-user suffix: a fixed world-writable /tmp path could be pre-owned or
#: poisoned by another local user on shared machines.
JAX_CACHE_DIR = os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    f"/tmp/covalent-tpu-jax-cache-{os.getuid()}",
)


class _PhaseSkipped(Exception):
    """Raised inside a phase body when BENCH_PHASES deselects it."""


def emit(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def spread_stats(values, prefix: str) -> dict:
    """min/max/stdev fields for a list of seconds, ms-scaled.

    The r3 verdict asked for the TPU phases' honest-spread treatment on
    EVERY phase — the overhead/fanout phases previously reported point
    medians only.
    """
    out = {
        f"{prefix}_ms_min": round(min(values) * 1e3, 3),
        f"{prefix}_ms_max": round(max(values) * 1e3, 3),
    }
    if len(values) >= 2:
        out[f"{prefix}_ms_stdev"] = round(statistics.stdev(values) * 1e3, 3)
    return out


def introspection_view(metrics: list, window_s: float = 300.0) -> dict:
    """Phase-boundary introspection: windowed history timelines + SLO
    verdicts for a phase's emitted JSON.

    BENCH artifacts previously carried point summaries only; the history
    ring turns them into regression-comparable timelines (tokens/s and
    queue depth over the phase, windowed latency percentiles), and the
    SLO engine's verdicts say whether the phase burned any error budget
    while it ran.  Best-effort: introspection being disabled (env) or
    broken must never fail a bench phase.
    """
    view: dict = {"history": {}, "slo": {}}
    try:
        from covalent_tpu_plugin.obs import history as _history
        from covalent_tpu_plugin.obs import slo as _slo

        ring = _history.ensure_history()
        if ring is not None:
            ring.sample(force=True)  # pin the phase's final state
            for name in metrics:
                q = ring.query(name, window_s=window_s)
                view["history"][name] = {
                    "kind": q["kind"],
                    "samples": q["samples"],
                    "series": q["series"],
                }
        engine = _slo.ensure_slo_engine()
        if engine is not None:
            evaluated = engine.evaluate()
            view["slo"] = {
                name: {
                    "state": info["state"],
                    "burn_rate": info["burn_rate"],
                }
                for name, info in evaluated.get("slos", {}).items()
            }
    except Exception as error:  # noqa: BLE001 - observability never fatal
        view["error"] = repr(error)
    return view


def percentile(values, q: float) -> float:
    """Linear-interpolated percentile of a small sample (q in [0, 1])."""
    ordered = sorted(values)
    if not ordered:
        return float("nan")
    if len(ordered) == 1:
        return ordered[0]
    rank = q * (len(ordered) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(ordered) - 1)
    return ordered[lo] + (ordered[hi] - ordered[lo]) * (rank - lo)


#: The tiling TTFT segments every traced serving request records (the
#: decode/flush tail is excluded — attribution answers "where did my
#: TTFT go", and TTFT ends at the first streamed token).
TTFT_SEGMENTS = ("prefill", "route", "dispatch", "ttft_wait")


def latency_attribution(trace_ids) -> dict:
    """Per-segment share-of-TTFT percentiles for one arm's requests.

    Pulls each request's waterfall from the in-process trace store and
    aggregates the tiling TTFT segments into p50/p95 shares — the
    artifact-level answer to "where did my TTFT go" across the arm, and
    the completeness evidence the CI smoke asserts on: every found
    trace must carry waterfall segments, contain no orphan spans, and
    its segment sum must cover the root's end-to-end duration.
    """
    out: dict = {
        "requests": len(trace_ids),
        "traces_found": 0,
        "traces_complete": 0,
        "traces_full_waterfall": 0,
        "orphan_spans": 0,
        "ttft_segments": {},
    }
    try:
        from covalent_tpu_plugin.obs.tracestore import TRACE_STORE

        shares: dict = {}
        coverages = []
        for trace_id in trace_ids:
            view = TRACE_STORE.waterfall(str(trace_id))
            if view is None:
                continue
            out["traces_found"] += 1
            out["orphan_spans"] += sum(
                1 for s in view.get("spans", ()) if s.get("orphan")
            )
            segments = view.get("segments") or {}
            ttft = sum(
                segments[name]["duration_s"]
                for name in TTFT_SEGMENTS
                if name in segments
            )
            if view.get("coverage") is not None:
                coverages.append(view["coverage"])
            if ttft <= 0:
                continue
            out["traces_complete"] += 1
            if all(name in segments for name in TTFT_SEGMENTS):
                # A short prompt legitimately skips the prefill tile;
                # the full four-segment waterfall only appears on the
                # KV-road (long-prompt) requests.
                out["traces_full_waterfall"] += 1
            for name in TTFT_SEGMENTS:
                if name in segments:
                    shares.setdefault(name, []).append(
                        segments[name]["duration_s"] / ttft
                    )
        for name, values in shares.items():
            out["ttft_segments"][name] = {
                "p50_share": round(percentile(values, 0.50), 4),
                "p95_share": round(percentile(values, 0.95), 4),
            }
        if coverages:
            out["coverage_p50"] = round(percentile(coverages, 0.50), 4)
            out["coverage_min"] = round(min(coverages), 4)
    except Exception as error:  # noqa: BLE001 - observability never fatal
        out["error"] = repr(error)
    return out


def load_last_known_good() -> dict | None:
    """Newest committed self-run combined line, stamped with provenance.

    Two rounds in a row the driver's bench window hit a tunnel outage and
    the official BENCH_r{N}.json carried ~30 silent nulls (VERDICT r4
    "what's weak" #1).  When the live preflight never passes, the final
    combined line now attaches this sub-object — the TPU phase values
    from the newest ``benchmarks/BENCH_SELF_r*.jsonl`` artifact, plus the
    artifact path and its git commit date — under the explicitly-stale
    key ``last_known_good``.  The live fields are NEVER backfilled: a
    reader always sees which numbers were measured in this run (null on
    outage) and which are carried evidence with a timestamp.
    """
    import glob
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    paths = sorted(glob.glob(os.path.join(here, "benchmarks",
                                          "BENCH_SELF_r*.jsonl")))
    for path in reversed(paths):
        try:
            combined = None
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    obj = json.loads(line)
                    if "metric" in obj:
                        combined = obj
            if combined is None:
                continue
            stamp = None
            try:
                out = subprocess.run(
                    ["git", "log", "-1", "--format=%cI", "--",
                     os.path.relpath(path, here)],
                    cwd=here, capture_output=True, text=True, timeout=10,
                )
                stamp = out.stdout.strip() or None
            except Exception:  # noqa: BLE001
                pass
            if stamp is None:
                stamp = time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path))
                )
            # Only the accelerator-measured fields travel; the host-side
            # dispatch numbers are re-measured live every run.
            skip = {"metric", "value", "unit", "vs_baseline"}
            return {
                "provenance": "stale builder self-run artifact; live "
                              "preflight failed this run",
                "source": os.path.relpath(path, here),
                "captured_at": stamp,
                **{k: v for k, v in combined.items()
                   if k not in skip and not k.startswith("fanout")
                   and not k.startswith("dispatch_overhead")
                   and not k.startswith("electron_wall")},
            }
        except Exception:  # noqa: BLE001
            continue
    return None


def tpu_host_signals() -> dict:
    """Host-level evidence of TPU hardware, gathered WITHOUT importing jax.

    The r03+ hang lives below jax: on a host with no TPU device nodes,
    libtpu's backend init blocks indefinitely instead of failing.  These
    signals are what a TPU VM actually exposes, so their absence turns a
    45 s-per-attempt hang into an instant, actionable verdict.
    """
    import glob

    try:
        from importlib import metadata
        libtpus = sorted(
            d.metadata["Name"]
            for d in metadata.distributions()
            if (d.metadata["Name"] or "").lower().startswith("libtpu")
        )
    except Exception:  # noqa: BLE001 - diagnostics must not fail the probe
        libtpus = []
    return {
        "accel_devices": sorted(glob.glob("/dev/accel*")),
        "vfio": os.path.exists("/dev/vfio"),
        "tpu_env": bool(
            os.environ.get("TPU_NAME")
            or os.environ.get("TPU_WORKER_ID")
            or os.environ.get("TPU_WORKER_HOSTNAMES")
        ),
        "libtpu_dists": libtpus,
    }


#: Failure reasons that no amount of retrying will change (the host
#: itself lacks TPU hardware); the retry loop breaks on this marker.
PREFLIGHT_PERMANENT = "not a TPU host"


def tpu_preflight(timeout_s: float) -> tuple[bool, float, str]:
    """Staged tunnel-health probe in a throwaway subprocess.

    Round 3 lost its entire TPU evidence to a hung backend init: both
    attempts burned the full 360 s + 120 s budgets inside
    ``jax.devices()`` (BENCH_r03: two ``TimeoutError()`` lines, ~30 null
    metrics), and every round since has reported the unactionable
    ``timeout after Ns``.  Diagnosis (reproduced under
    ``JAX_PLATFORMS=tpu`` on a TPU-less host): libtpu's backend init
    BLOCKS — it never errors — when the host has no ``/dev/accel*``
    device nodes, so the old single-shot probe could only ever time out
    with no stage attribution.  Three fixes ride here:

    * **Fail fast off-TPU** — when the env pins a TPU platform and the
      host shows none of a TPU VM's signals, refuse in milliseconds with
      the actionable reason (and the installed libtpu dists, since a
      ``libtpu`` + ``libtpu_nightly`` double-install is itself a known
      init-breaker).  The retry loop treats this as permanent.
    * **Stage markers** — the child prints a progress line per stage
      (import / backend / compile), and a timeout's partial stdout names
      the stage that hung instead of just the budget that died.
    * **No silent CPU pass** — a probe that settles on a platform other
      than the TPU the env requested is a FAILURE with the settled
      platform in the reason; previously it passed, misreporting a CPU
      fallback as live TPU health.
    """
    import subprocess

    t0 = time.monotonic()
    requested = (os.environ.get("JAX_PLATFORMS") or "").lower()
    if "tpu" in requested:
        signals = tpu_host_signals()
        if not (
            signals["accel_devices"] or signals["vfio"] or signals["tpu_env"]
        ):
            return False, time.monotonic() - t0, (
                f"{PREFLIGHT_PERMANENT}: JAX_PLATFORMS={requested!r} but no "
                "/dev/accel* nodes, no /dev/vfio, no TPU_* env — libtpu "
                "backend init would hang, not fail "
                f"(libtpu dists installed: {signals['libtpu_dists'] or 'none'})"
            )
    code = (
        # Pin the platform from the env like the electron harness does —
        # site hooks (e.g. the axon TPU plugin) re-pin after interpreter
        # start, so a JAX_PLATFORMS=cpu validation run would otherwise
        # probe the TPU tunnel it was explicitly avoiding.  Stage lines
        # flush eagerly: they are the hang's attribution.
        "import os\n"
        "print('PREFLIGHT_STAGE import', flush=True)\n"
        "import jax, jax.numpy as jnp\n"
        "plat = os.environ.get('JAX_PLATFORMS')\n"
        "if plat:\n"
        "    try:\n"
        "        jax.config.update('jax_platforms', plat)\n"
        "    except RuntimeError:\n"
        "        pass  # backend already initialized by a site hook\n"
        "print('PREFLIGHT_STAGE backend', flush=True)\n"
        "devs = jax.devices()\n"
        "print('PREFLIGHT_STAGE compile', devs[0].platform, len(devs),"
        " flush=True)\n"
        "x = jnp.ones((256, 256), jnp.bfloat16)\n"
        "out = jax.jit(lambda a: a @ a)(x)\n"
        "print('PREFLIGHT_OK', float(out[0, 0]), devs[0].platform,"
        " flush=True)\n"
    )

    def last_stage(stdout: str | bytes | None) -> str:
        text = stdout or ""
        if isinstance(text, bytes):
            text = text.decode(errors="replace")
        stages = [
            line.split()[1]
            for line in text.splitlines()
            if line.startswith("PREFLIGHT_STAGE ") and len(line.split()) > 1
        ]
        return stages[-1] if stages else "interpreter-start"

    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True, text=True,
        )
        took = time.monotonic() - t0
        if proc.returncode == 0 and "PREFLIGHT_OK 256" in proc.stdout:
            settled = proc.stdout.rsplit("PREFLIGHT_OK 256", 1)[-1].split()
            platform = (settled[-1] if settled else "").lower()
            if "tpu" in requested and platform != "tpu":
                return False, took, (
                    f"backend settled on {platform!r}, not the requested "
                    f"'tpu' — silent platform fallback, not TPU health"
                )
            return True, took, ""
        tail = (proc.stderr or proc.stdout or "")[-300:]
        return False, took, (
            f"rc={proc.returncode} in stage {last_stage(proc.stdout)!r}: "
            f"{tail}"
        )
    except subprocess.TimeoutExpired as error:
        stage = last_stage(error.stdout)
        hint = (
            " (TPU backend init blocked: check /dev/accel* visibility and "
            "for conflicting libtpu installs)"
            if stage == "backend"
            else ""
        )
        return False, time.monotonic() - t0, (
            f"timeout after {timeout_s}s, hung in stage {stage!r}{hint}"
        )
    except Exception as error:  # noqa: BLE001
        return False, time.monotonic() - t0, repr(error)


def trivial_electron(i: int) -> int:
    return i * i


# --------------------------------------------------------------------------
# dispatcher_crash drill: two processes play dispatcher incarnations.
#
# The phase cannot SIGKILL *itself*, so the drill runs the dispatcher in a
# child: `bench.py --dispatcher-drill serve <dir>` journals two serving
# sessions with one slow stream each and reports delivered-token progress
# on stdout until the phase kills it -9 mid-stream; `--dispatcher-drill
# recover <dir>` is the successor incarnation — journal replay, orphan
# adoption over the rendezvous socket, stream resume from the journaled
# high-water marks — and prints one summary line the phase asserts on.
# --------------------------------------------------------------------------

DRILL_SESSIONS = 2
DRILL_TOKENS = 40


def _drill_engine_factory(step_delay: float = 0.15):
    """Deterministic slow engine (closure-local: workers can't import
    bench).  Prompt ``[base]`` streams ``base+1 .. base+DRILL_TOKENS`` —
    byte-checkable across the crash."""

    def factory():
        import time as time_mod

        class Engine:
            def __init__(self):
                self.slots = 2
                self.lanes = {}

            def admit(self, rid, prompt, params):
                cap = int((params or {}).get("max_new_tokens", 8))
                base = int(prompt[-1])
                self.lanes[rid] = [base + i + 1 for i in range(cap)]

            def step(self):
                time_mod.sleep(step_delay)
                events = []
                for rid in list(self.lanes):
                    taken = self.lanes[rid][:2]
                    self.lanes[rid] = self.lanes[rid][2:]
                    done = not self.lanes[rid]
                    if done:
                        del self.lanes[rid]
                    events.append({"rid": rid, "tokens": taken, "done": done})
                return events

            def cancel(self, rid):
                self.lanes.pop(rid, None)

        return Engine()

    return factory


def _drill_executor(dwork: str):
    root = os.path.dirname(os.path.abspath(__file__))
    return TPUExecutor(
        transport="local",
        cache_dir=f"{dwork}/cache",
        remote_cache=f"{dwork}/remote",
        python_path=sys.executable,
        poll_freq=0.2,
        use_agent="pool",
        heartbeat_interval=0.0,
        prewarm=False,
        task_env={
            "PYTHONPATH": root + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        },
    )


async def _drill_serve(dwork: str) -> None:
    """Incarnation 1: journal, stream, report progress, die by SIGKILL."""
    from covalent_tpu_plugin.fleet import journal as journal_mod
    from covalent_tpu_plugin.serving import open_session

    journal_mod.configure(f"{dwork}/journal")
    ex = _drill_executor(dwork)
    # Both sessions warm BEFORE either request: stream 0 must not run to
    # completion while session 1 is still cold-starting its worker.
    handles = await asyncio.gather(*(
        open_session(
            ex, _drill_engine_factory(step_delay=0.2),
            name=f"dcrash-s{i}", stats_interval_s=0.2,
        )
        for i in range(DRILL_SESSIONS)
    ))
    streams = []
    for i, handle in enumerate(handles):
        base = 1000 * (i + 1)
        req = await handle.request(
            [base], params={"max_new_tokens": DRILL_TOKENS}
        )
        streams.append((handle.sid, base, req))
    deadline = time.monotonic() + 120  # safety: the kill should come first
    while time.monotonic() < deadline:
        for sid, base, req in streams:
            print(json.dumps({
                "drill": "progress", "sid": sid, "rid": req.rid,
                "base": base, "tokens": list(req.tokens),
            }), flush=True)
        await asyncio.sleep(0.1)


async def _drill_recover(dwork: str) -> None:
    """Incarnation 2: replay, re-adopt, resume, report, exit clean."""
    from covalent_tpu_plugin.fleet import journal as journal_mod
    from covalent_tpu_plugin.fleet import recovery as recovery_mod  # noqa: F401

    journal_mod.configure(f"{dwork}/journal")
    ex = _drill_executor(dwork)
    report = await ex.recover()
    streams = {}
    for (sid, rid), req in report.requests.items():
        tail = await req.result(timeout=90)
        streams[f"{sid}/{rid}"] = {
            "from": req.resumed_from, "tail": list(tail),
        }
    totals = metrics_totals()
    print(json.dumps({
        "drill": "recovered",
        "epoch": report["epoch"],
        "duration_s": report["duration_s"],
        "adopted": len(report["adopted_sessions"]),
        "orphaned": len(report["orphaned_sessions"]),
        "streams": streams,
        "metrics": {
            k: v for k, v in totals.items()
            if "recovery" in k or "journal" in k or "fallback_local" in k
        },
    }), flush=True)
    for sup in report.supervisors.values():
        await sup.close()
    await ex.close()


def run_dispatcher_crash_drill(dwork: str) -> dict:
    """Phase orchestrator (sync, called off-loop): serve → kill -9 →
    recover, returning the composed evidence."""
    import signal as signal_mod
    import subprocess

    os.makedirs(dwork, exist_ok=True)
    env = dict(os.environ)
    env["COVALENT_TPU_ORPHAN_TTL_S"] = "120"
    env.setdefault("JAX_PLATFORMS", "cpu")
    argv = [sys.executable, os.path.abspath(__file__), "--dispatcher-drill"]
    serve = subprocess.Popen(
        argv + ["serve", dwork],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env,
    )
    prefixes: dict[str, dict] = {}
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            line = serve.stdout.readline()
            if not line:
                break
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if msg.get("drill") != "progress":
                continue
            prefixes[f"{msg['sid']}/{msg['rid']}"] = msg
            # Mid-stream on every session: tokens flowed, none finished.
            if len(prefixes) >= DRILL_SESSIONS and all(
                4 <= len(p["tokens"]) < DRILL_TOKENS
                for p in prefixes.values()
            ):
                break
        t_kill = time.monotonic()
        serve.send_signal(signal_mod.SIGKILL)
        serve.wait(timeout=30)
    finally:
        if serve.poll() is None:
            serve.kill()
    mid_flight = bool(prefixes) and all(
        0 < len(p["tokens"]) < DRILL_TOKENS for p in prefixes.values()
    )
    rec = subprocess.run(
        argv + ["recover", dwork],
        capture_output=True, text=True, env=env, timeout=240,
    )
    recovered = None
    for line in (rec.stdout or "").splitlines():
        try:
            msg = json.loads(line)
        except ValueError:
            continue
        if msg.get("drill") == "recovered":
            recovered = msg
    if recovered is None:
        raise AssertionError(
            f"recover drill produced no summary (rc={rec.returncode}): "
            f"{(rec.stderr or rec.stdout or '')[-400:]}"
        )
    # Exactly-once across the crash, per stream: the killed dispatcher's
    # last-reported prefix must be a prefix of the oracle, the journaled
    # splice point can exceed it only by the kill window (chunks delivered
    # between the last progress line and the SIGKILL), and the resumed
    # tail must complete the oracle byte-for-byte from that splice point.
    streams_exact = bool(recovered["streams"]) and mid_flight
    checks = []
    for key, got in recovered["streams"].items():
        progress = prefixes.get(key)
        base = progress["base"] if progress else 0
        oracle = [base + i + 1 for i in range(DRILL_TOKENS)]
        prefix = progress["tokens"] if progress else []
        splice = int(got["from"])
        ok = (
            progress is not None
            and prefix == oracle[:len(prefix)]
            and len(prefix) <= splice <= DRILL_TOKENS
            and got["tail"] == oracle[splice:]
        )
        streams_exact = streams_exact and ok
        checks.append({
            "stream": key, "prefix_tokens": len(prefix), "splice": splice,
            "tail_tokens": len(got["tail"]), "exact": ok,
        })
    return {
        "mid_flight_at_kill": mid_flight,
        "sessions_adopted": recovered["adopted"],
        "sessions_orphaned": recovered["orphaned"],
        "recovery_duration_s": recovered["duration_s"],
        "recovery_epoch": recovered["epoch"],
        "recovery_wall_s": round(time.monotonic() - t_kill, 3),
        "streams": checks,
        "streams_exact": streams_exact,
        "metrics": recovered["metrics"],
    }


def preemptible_train(steps: int, step_s: float, progress_path: str):
    """Checkpoint-cooperative training electron (preemption_chaos phase).

    Appends every executed step to ``progress_path`` so the phase can
    count recomputation across gang attempts; registers a snapshot hook
    for the harness's interval/SIGTERM checkpointer and resumes from the
    dispatcher-shipped bundle when one exists.
    """
    import time as time_mod

    from covalent_tpu_plugin.utils import checkpoint as ckpt

    state = {"acc": 0.0, "step": -1}
    start = 0
    resumed = ckpt.resume_state()
    if resumed is not None:
        step0, tree = resumed
        state.update(tree)
        start = int(step0) + 1

    def snap():
        # One read of the rebinding variable: the hook runs from the
        # checkpointer thread AND the SIGTERM handler, and each step
        # publishes a fresh dict instead of mutating in place, so a
        # snapshot is always internally consistent.
        current = state
        return dict(current), current["step"]

    ckpt.register_snapshot(snap)
    try:
        for step in range(start, steps):
            with open(progress_path, "a") as f:
                f.write(f"{step}\n")
            time_mod.sleep(step_s)
            state = {"acc": state["acc"] + step, "step": step}
    finally:
        ckpt.unregister_snapshot()
    return state["acc"], start


#: ~36 KiB of structured, compressible text per electron — the realistic
#: spec/manifest payload shape the wire codec targets (random bytes would
#: dishonestly zero the codec's win; real staged payloads are pickles and
#: JSON, which compress well).
BUNDLE_PAYLOAD = (
    '{"field": "value", "worker_env": "JAX_PLATFORMS=tpu", '
    '"path": "/workdir/covalent-tpu/artifacts"}\n'
) * 400


def payload_electron(i: int, text: str) -> tuple:
    """Unique-per-electron args force a distinct function pickle each, so
    a cold fan-out stages real per-electron payload bytes."""
    return (i, len(text))


def wire_up_bytes() -> float:
    """Total upload bytes recorded by the codec layer so far."""
    return sum(
        v for k, v in metrics_totals().items()
        if k.startswith("covalent_tpu_wire_bytes_total{")
        and "direction=up" in k
    )


def staging_ops() -> float:
    """Total staging round trips (per-file + bundled) so far."""
    return sum(
        v for k, v in metrics_totals().items()
        if k.startswith("covalent_tpu_staging_ops_total{")
    )


def agent_wire_bytes(encoding: str = "") -> float:
    """Total agent-channel bytes so far (optionally one encoding)."""
    return sum(
        v for k, v in metrics_totals().items()
        if k.startswith("covalent_tpu_agent_wire_bytes_total{")
        and (not encoding or f"encoding={encoding}" in k)
    )


def agent_frames(verb: str, encoding: str = "binary") -> float:
    """Per-verb agent-channel message count from the frame accounting."""
    return sum(
        v for k, v in metrics_totals().items()
        if k.startswith("covalent_tpu_agent_frames_total{")
        and f"verb={verb}" in k and f"encoding={encoding}" in k
    )


def upload_span_sum() -> float:
    """Cumulative seconds spent inside executor.upload spans."""
    from covalent_tpu_plugin.obs.metrics import REGISTRY
    from covalent_tpu_plugin.obs.trace import SPAN_HISTOGRAM

    snap = REGISTRY.snapshot()["metrics"].get(SPAN_HISTOGRAM, {})
    return sum(
        series["sum"]
        for series in snap.get("series", [])
        if series["labels"].get("span") == "executor.upload"
    )


def busy_electron(i: int, seconds: float) -> int:
    """A task with real duration: shows fan-out concurrency honestly
    (trivial electrons are dispatcher-event-loop-bound, so their fan-out
    wall measures per-electron overhead, not parallelism)."""
    import time

    time.sleep(seconds)
    return i


def accelerator_electron(progress_path: str, budget_s: float) -> dict:
    """ALL accelerator phases in one harness process (one backend init).

    Streams one JSON line per subphase to ``progress_path`` so the
    dispatcher-side bench can surface partial results even if this electron
    is later killed on budget overrun.  Self-contained imports per the
    harness contract; requires the package on PYTHONPATH (task_env).
    """
    import json
    import time

    t_start = time.monotonic()
    results: dict = {}

    progress = open(progress_path, "a", buffering=1)

    def report(subphase: str, **data):
        data["at_s"] = round(time.monotonic() - t_start, 1)
        results[subphase] = data
        progress.write(json.dumps({"subphase": subphase, **data}) + "\n")

    def remaining() -> float:
        return budget_s - (time.monotonic() - t_start)

    # Filled by the lm_decode phase; consumed by the lm_serve tail phase
    # (reuses the decode model + measured static-batch wall so the serving
    # arm costs no extra baseline compiles).
    serve_ctx = None

    # -- backend init (the round-1 killer: measure it explicitly) ----------
    t0 = time.monotonic()
    import os

    import jax
    import jax.numpy as jnp

    try:  # persistent compile cache: tunnel compiles cost 10s of seconds.
        # The env var is always supplied via task_env (JAX_CACHE_DIR at
        # module level); the fallback only covers out-of-bench reuse.
        cache_dir = os.environ["JAX_COMPILATION_CACHE_DIR"]
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        compile_cache = cache_dir
    except Exception as error:  # noqa: BLE001 - cache is an optimisation,
        # but a silent cold cache re-creates the budget overrun this fixes:
        # surface the reason in the init line.
        compile_cache = f"disabled: {error!r}"

    devices = jax.devices()
    device_kind = devices[0].device_kind
    backend = devices[0].platform
    report(
        "init",
        init_s=round(time.monotonic() - t0, 2),
        backend=backend,
        device_kind=device_kind,
        n_devices=len(devices),
        compile_cache=compile_cache,
    )

    # Peak bf16 dense TFLOP/s per chip, for MFU (public spec sheets).
    peak_table = {
        "v6": 918.0,        # Trillium / v6e
        "v5p": 459.0,
        "v5": 197.0,        # v5e / v5 litepod
        "v4": 275.0,
        "v3": 123.0,
        "v2": 45.0,
    }
    peak_tflops = None
    kind_lower = device_kind.lower()
    for key in ("v6", "v5p", "v5", "v4", "v3", "v2"):
        if key in kind_lower:
            peak_tflops = peak_table[key]
            break

    def mfu(tflops):
        """Model FLOP utilisation, clamped at the physical ceiling.

        A computed MFU > 1.0 is a measurement error by definition (the
        chip cannot exceed its peak): report 1.0 with the raw value in a
        warning rather than an impossible number (BENCH_r02 emitted 1.05
        once under min-of-2 delta timing; median-of-N makes this rare,
        the clamp makes it impossible).
        """
        if not peak_tflops:
            return None, None
        raw = tflops / peak_tflops
        if raw > 1.0:
            return 1.0, f"measured {raw:.4f} > physical peak; clamped"
        return round(raw, 4), None

    def unit_seconds(dispatch, fetch, target_s: float, cap: int,
                     trials: int = 5):
        """Seconds per dispatched unit, by median-of-N two-batch deltas.

        The tunneled/proxied device this bench runs against adds a large
        constant per-fetch round-trip (~65 ms measured) that would
        masquerade as low FLOP throughput.  Timing a 1-unit batch and a
        k-unit batch and dividing by (k - 1) cancels that constant:
        dispatches are async (they only enqueue), the device queue
        serialises them, and ``fetch`` forces a drain.

        The per-trial delta jitters with the round-trip constant; the
        *median* of N trials is reported (a min would let one low-jitter
        outlier overstate throughput — the BENCH_r02 >100%-MFU failure
        mode), together with the spread so the artifact carries its own
        error bars.  Returns ``(unit_s, stats_dict)``.
        """
        import statistics as stats_mod

        dispatch()
        fetch()  # compiled + warm
        t0 = time.monotonic()
        dispatch()
        fetch()
        once = time.monotonic() - t0  # includes the round-trip constant
        k = max(2, min(cap, int(target_s / max(once, 1e-6)) + 1))
        deltas = []
        for _ in range(trials):
            t0 = time.monotonic()
            dispatch()
            fetch()
            e1 = time.monotonic() - t0
            t0 = time.monotonic()
            for _ in range(k):
                dispatch()
            fetch()
            ek = time.monotonic() - t0
            if ek > e1:  # jitter can invert tiny deltas; discard, don't clamp
                deltas.append((ek - e1) / (k - 1))
        if not deltas:
            # Every trial jitter-inverted: the single-batch time (round-trip
            # included) is the honest upper bound, never a fabricated rate.
            return once, {"n_deltas": 0, "note": "round-trip bound"}
        unit = stats_mod.median(deltas)
        spread = {
            "n_deltas": len(deltas),
            "unit_ms_median": round(unit * 1e3, 3),
            "unit_ms_min": round(min(deltas) * 1e3, 3),
            "unit_ms_max": round(max(deltas) * 1e3, 3),
        }
        if len(deltas) >= 2:
            spread["unit_ms_stdev"] = round(
                stats_mod.stdev(deltas) * 1e3, 3
            )
        return unit, spread

    # Non-TPU backends (the CPU validation tier) get scaled-down shapes so
    # every subphase still executes end to end within the budget.
    small = backend != "tpu"

    # -- matmul TFLOP/s + MFU (BASELINE config 2) --------------------------
    try:
        n = 1024 if small else 4096
        chain_len = 16
        inv_n = 1.0 / n
        x = jnp.ones((n, n), jnp.bfloat16)
        y = jnp.ones((n, n), jnp.bfloat16)

        @jax.jit
        def mm_chain(a, b):
            # Rescale by 1/n so the chained all-ones product stays exactly 1
            # (a raw chain overflows bf16 to inf after ~10 iterations) —
            # the fetched scalar doubles as a correctness check.
            return jax.lax.fori_loop(
                0,
                chain_len,
                lambda _, acc: jnp.einsum("ij,jk->ik", acc, b) * inv_n,
                a,
            )

        holder = {}

        def dispatch():
            holder["out"] = mm_chain(x, y)

        def fetch():
            # device_get, not block_until_ready: proxy/tunnel backends can
            # make the latter a no-op, and a fetched scalar can't lie.
            holder["check"] = float(jax.device_get(holder["out"][0, 0]))

        unit, spread = unit_seconds(dispatch, fetch, target_s=3.0, cap=40)
        tflops = (2 * n**3 * chain_len) / unit / 1e12
        mfu_val, mfu_warning = mfu(tflops)
        report(
            "matmul",
            n=n,
            chain_len=chain_len,
            tflops=round(tflops, 2),
            mfu=mfu_val,
            **({"mfu_warning": mfu_warning} if mfu_warning else {}),
            peak_tflops=peak_tflops,
            check=holder["check"],  # must be 1.0
            **spread,
        )
    except Exception as error:  # noqa: BLE001
        report("matmul", error=repr(error))

    # -- MNIST MLP training on a multi-batch stream (north-star electron) --
    # An epoch-style pass over DISTINCT batches with a falling loss curve —
    # "trains MNIST end-to-end" (BASELINE config 4) — not a memorize-one-
    # batch throughput proxy (the BENCH_r02 final_loss=0.0 critique).
    if remaining() > 60:
        try:
            import numpy as onp
            import optax
            from flax.training import train_state

            from covalent_tpu_plugin.models.mlp import MLP, synthetic_mnist

            batch_size = 128 if small else 256
            n_batches = 24 if small else 64
            stream = [
                synthetic_mnist(batch_size, seed=i) for i in range(n_batches)
            ]
            images = jnp.asarray(onp.stack([b["image"] for b in stream]))
            labels = jnp.asarray(onp.stack([b["label"] for b in stream]))
            model = MLP()
            state = train_state.TrainState.create(
                apply_fn=model.apply,
                params=model.init(jax.random.PRNGKey(0), images[0])["params"],
                tx=optax.adam(1e-3),
            )

            @jax.jit
            def epoch(state):
                def step(state, batch):
                    def loss_fn(params):
                        logits = state.apply_fn(
                            {"params": params}, batch["image"]
                        )
                        return optax.softmax_cross_entropy_with_integer_labels(
                            logits.astype(jnp.float32), batch["label"]
                        ).mean()

                    loss, grads = jax.value_and_grad(loss_fn)(state.params)
                    return state.apply_gradients(grads=grads), loss

                return jax.lax.scan(
                    step, state, {"image": images, "label": labels}
                )

            state, losses = epoch(state)  # compile + epoch 1 (fresh params)
            curve = jax.device_get(losses).astype(float)
            holder = {"state": state}

            def dispatch():
                holder["state"], holder["losses"] = epoch(holder["state"])

            def fetch():
                holder["last"] = float(jax.device_get(holder["losses"][-1]))

            # Each unit is a full n_batches-step epoch, so the per-fetch
            # round-trip constant amortises n_batches-fold on top of the
            # delta cancellation.
            unit, spread = unit_seconds(
                dispatch, fetch, target_s=3.0, cap=40, trials=3
            )
            report(
                "mnist",
                n_batches=n_batches,
                steps_per_s=round(n_batches / unit, 2),
                loss_first=round(float(curve[:4].mean()), 4),
                loss_last=round(float(curve[-4:].mean()), 4),
                loss_final_epoch=round(holder["last"], 4),
                **spread,
            )
        except Exception as error:  # noqa: BLE001
            report("mnist", error=repr(error))
    else:
        report("mnist", skipped="budget")

    # -- flash attention forward vs dense (long-context hot op) ------------
    if remaining() > 50:
        try:
            from covalent_tpu_plugin.ops.attention import (
                flash_attention,
                mha_reference,
            )

            b, h, s, d = (1, 4, 512, 64) if small else (2, 16, 4096, 64)
            q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), jnp.bfloat16)
            k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d), jnp.bfloat16)
            v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d), jnp.bfloat16)

            def bench_fwd(fn, cap=24):
                f = jax.jit(fn)
                holder = {}

                def dispatch():
                    holder["out"] = f(q, k, v)

                def fetch():
                    jax.device_get(holder["out"][0, 0, 0, 0])

                return unit_seconds(
                    dispatch, fetch, target_s=2.0, cap=cap, trials=3
                )

            ref_s, _ = bench_fwd(lambda q, k, v: mha_reference(q, k, v, causal=True))
            flash_s, spread = bench_fwd(
                lambda q, k, v: flash_attention(q, k, v, causal=True)
            )
            report(
                "flash_fwd",
                seq_len=s,
                ref_ms=round(ref_s * 1e3, 2),
                flash_ms=round(flash_s * 1e3, 2),
                speedup=round(ref_s / flash_s, 2),
                **spread,
            )
        except Exception as error:  # noqa: BLE001
            report("flash_fwd", error=repr(error))
    else:
        report("flash_fwd", skipped="budget")

    # -- flash attention fwd+bwd (training path; VERDICT r1 #3) ------------
    if remaining() > 40:
        try:
            from covalent_tpu_plugin.ops.attention import (
                flash_attention,
                mha_reference,
            )

            b, h, s, d = (1, 4, 512, 64) if small else (2, 16, 4096, 64)
            q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), jnp.bfloat16)
            k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d), jnp.bfloat16)
            v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d), jnp.bfloat16)

            def bench_bwd(fn, cap=12):
                grad_fn = jax.jit(
                    jax.grad(
                        lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(),
                        argnums=(0, 1, 2),
                    )
                )
                holder = {}

                def dispatch():
                    holder["grads"] = grad_fn(q, k, v)

                def fetch():
                    jax.device_get(holder["grads"][0][0, 0, 0, 0])

                return unit_seconds(
                    dispatch, fetch, target_s=2.0, cap=cap, trials=3
                )

            ref_s, _ = bench_bwd(lambda q, k, v: mha_reference(q, k, v, causal=True))
            flash_s, spread = bench_bwd(
                lambda q, k, v: flash_attention(q, k, v, causal=True)
            )
            report(
                "flash_bwd",
                seq_len=s,
                ref_ms=round(ref_s * 1e3, 2),
                flash_ms=round(flash_s * 1e3, 2),
                speedup=round(ref_s / flash_s, 2),
                **spread,
            )
        except Exception as error:  # noqa: BLE001
            report("flash_bwd", error=repr(error))
    else:
        report("flash_bwd", skipped="budget")

    # -- long context: flash fwd+bwd at S=16k (dense spills/OOMs there), --
    # -- then the same shape through the sliding-window band ---------------
    if remaining() > 40:
        try:
            from covalent_tpu_plugin.ops.attention import flash_attention

            b, h, s, d = (1, 2, 2048, 64) if small else (1, 8, 16384, 64)
            win = 256 if small else 1024
            q = jax.random.normal(jax.random.PRNGKey(0), (b, h, s, d), jnp.bfloat16)
            k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d), jnp.bfloat16)
            v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d), jnp.bfloat16)

            def bwd_unit(window, iters=16, trials=5):
                """Pure ON-DEVICE fwd+bwd seconds at this shape.

                Data-dependent chain inside one jit: dq feeds the next
                iteration's q, so per-dispatch host/tunnel overhead
                appears in neither the 1-chain nor the N-chain wall and
                cancels exactly.  The r4 sweep showed the two-batch
                delta method inflating the SLOW arm of this very ratio
                (full attention) by ~40% while reading the fast arm
                near-true — overstating the windowed speedup 7.4x where
                the device does 4.7x (benchmarks/WINDOW_SWEEP.md).
                """
                import statistics as stats_mod

                def one(q_in):
                    dq = jax.grad(
                        lambda q_: flash_attention(
                            q_, k, v, causal=True, window=window
                        ).astype(jnp.float32).sum()
                    )(q_in)
                    return q_in + (1e-6 * dq).astype(q_in.dtype)

                @jax.jit
                def chain(q0, n):
                    return jax.lax.fori_loop(0, n, lambda i, q_: one(q_), q0)

                jax.device_get(chain(q, iters)[0, 0, 0, 0])  # compile both
                jax.device_get(chain(q, 1)[0, 0, 0, 0])
                samples = []
                for _ in range(trials):
                    t0 = time.monotonic()
                    jax.device_get(chain(q, 1)[0, 0, 0, 0])
                    t1 = time.monotonic() - t0
                    t0 = time.monotonic()
                    jax.device_get(chain(q, iters)[0, 0, 0, 0])
                    tn = time.monotonic() - t0
                    if tn > t1:
                        samples.append((tn - t1) / (iters - 1))
                if not samples:
                    return tn / iters, {"n_deltas": 0,
                                        "note": "chain bound"}
                unit = stats_mod.median(samples)
                spread = {
                    "n_deltas": len(samples),
                    "unit_ms_median": round(unit * 1e3, 3),
                    "unit_ms_min": round(min(samples) * 1e3, 3),
                    "unit_ms_max": round(max(samples) * 1e3, 3),
                    "method": "on-device chain",
                }
                if len(samples) >= 2:
                    spread["unit_ms_stdev"] = round(
                        stats_mod.stdev(samples) * 1e3, 3
                    )
                return unit, spread

            # Exactness probe for the compiled (Mosaic) banded grid: the
            # CPU test tier runs the kernel in interpret mode only, so a
            # Mosaic-specific miscompile of the clamped index maps would
            # otherwise show up as silently wrong numbers here.
            from covalent_tpu_plugin.ops.attention import mha_reference

            pq, pk, pv = (
                jax.random.normal(
                    jax.random.PRNGKey(7 + i), (1, 2, 512, 64), jnp.bfloat16
                )
                for i in range(3)
            )
            probe_err = float(
                jax.device_get(
                    jnp.max(jnp.abs(
                        flash_attention(
                            pq, pk, pv, causal=True, window=96,
                            block_q=128, block_k=128,
                        ).astype(jnp.float32)
                        - mha_reference(
                            pq, pk, pv, causal=True, window=96
                        ).astype(jnp.float32)
                    ))
                )
            )

            unit, spread = bwd_unit(None)
            # attention flops: 4*S^2*D fwd + 10*S^2*D bwd, * 0.5 causal
            # (matches the kernels' own CostEstimates in ops/attention.py)
            att_tflops = 14 * b * h * s * s * d * 0.5 / unit / 1e12
            report(
                "flash_long",
                seq_len=s,
                fwd_bwd_ms=round(unit * 1e3, 2),
                attn_tflops=round(att_tflops, 2),
                note="dense S^2 path spills at this length (see benchmarks/)",
                **spread,
            )
            if remaining() > 25:
                win_unit, win_spread = bwd_unit(win)
                report(
                    "flash_window",
                    seq_len=s,
                    window=win,
                    fwd_bwd_ms=round(win_unit * 1e3, 2),
                    speedup_vs_full=round(unit / win_unit, 2),
                    banded_max_err=round(probe_err, 5),
                    **win_spread,
                )
                # Second band width: w=512's tighter band has a higher
                # tile-geometry ceiling (the w=1k multiple saturates its
                # own ceiling — see benchmarks/WINDOW_SWEEP.md).
                if not small and remaining() > 25:
                    w2 = 512
                    w2_unit, w2_spread = bwd_unit(w2)
                    report(
                        "flash_window_512",
                        seq_len=s,
                        window=w2,
                        fwd_bwd_ms=round(w2_unit * 1e3, 2),
                        speedup_vs_full=round(unit / w2_unit, 2),
                        **w2_spread,
                    )
            else:
                report("flash_window", skipped="budget")
        except Exception as error:  # noqa: BLE001
            report("flash_long", error=repr(error))
    else:
        report("flash_long", skipped="budget")
        report("flash_window", skipped="budget")

    # -- 125M-class LM train step + MFU (BASELINE config 5's model, 1 chip) -
    if remaining() > 75:
        try:
            import optax

            from covalent_tpu_plugin.models.train import (
                TrainState,
                lm_loss,
            )
            from covalent_tpu_plugin.models.transformer import (
                TransformerLM,
                lm_125m_config,
            )

            # Sweep winner on v5e (benchmarks/LM_STEP_SWEEP.md): unrolled
            # layers let XLA optimise across block boundaries (+33% over
            # lax.scan), dots-remat recomputes only the cheap elementwise
            # ops, and bsz 8 saturates the chip without b16's compile cost.
            if small:
                bsz, seq = 2, 256
                config = lm_125m_config(
                    max_seq=seq, n_layers=2, d_model=256, n_heads=4,
                    d_ff=1024, vocab_size=4096, remat=True,
                    remat_policy="dots", scan_layers=False,
                )
            else:
                bsz, seq = 8, 1024
                config = lm_125m_config(
                    max_seq=seq, remat=True, remat_policy="dots",
                    scan_layers=False,
                )
            model = TransformerLM(config=config)
            # seq+1 tokens: lm_loss shifts by one, so the model sees exactly
            # `seq` positions (a tileable multiple of 128 for flash).
            tokens = jax.random.randint(
                jax.random.PRNGKey(0), (bsz, seq + 1), 0, config.vocab_size
            )
            params = model.init(jax.random.PRNGKey(1), tokens[:, :-1])["params"]
            state = TrainState.create(
                apply_fn=model.apply, params=params, tx=optax.adamw(3e-4)
            )
            n_params = model.parameter_count(params)

            @jax.jit
            def step(state, tokens):
                loss, grads = jax.value_and_grad(
                    lambda p: lm_loss(p, state.apply_fn, {"tokens": tokens})
                )(state.params)
                return state.apply_gradients(grads=grads), loss

            holder = {"state": state}

            def dispatch():
                holder["state"], holder["loss"] = step(holder["state"], tokens)

            def fetch():
                holder["final"] = float(jax.device_get(holder["loss"]))

            step_s, spread = unit_seconds(dispatch, fetch, target_s=4.0, cap=10)
            final_loss = holder["final"]
            # 6ND for fwd+bwd (+ remat recompute ~ +1 fwd -> 8ND ceiling;
            # report the standard 6ND so MFU is comparable across frameworks)
            lm_tflops = 6 * n_params * bsz * seq / step_s / 1e12
            mfu_val, mfu_warning = mfu(lm_tflops)
            report(
                "lm_step",
                n_params=n_params,
                step_ms=round(step_s * 1e3, 1),
                tokens_per_s=round(bsz * seq / step_s),
                tflops_6nd=round(lm_tflops, 2),
                mfu=mfu_val,
                **({"mfu_warning": mfu_warning} if mfu_warning else {}),
                final_loss=round(final_loss, 4),
                **spread,
            )

            # Fused-xent arm (VERDICT r4 #7): the same step with the
            # vocab-chunked loss (ops/xent.py) — the lm_head matmul runs
            # bf16-native and the (B,S,V) logits tensor never reaches
            # HBM.  A/B against the standard arm above; own try so a
            # fused failure can't void the standard number.  The gate is
            # deliberately conservative (150 s, not this phase's usual
            # 40): the serving wall (lm_serve, the round's #1 ask) runs
            # LAST and must not lose its budget to a new mid-order arm.
            if remaining() > 150:
                try:
                    v_chunk = min(8192, config.vocab_size)

                    @jax.jit
                    def step_fused(state, tokens):
                        loss, grads = jax.value_and_grad(
                            lambda p: lm_loss(
                                p, state.apply_fn, {"tokens": tokens},
                                vocab_chunk=v_chunk,
                            )
                        )(state.params)
                        return state.apply_gradients(grads=grads), loss

                    holder_f = {"state": holder["state"]}

                    def dispatch_f():
                        holder_f["state"], holder_f["loss"] = step_fused(
                            holder_f["state"], tokens
                        )

                    def fetch_f():
                        holder_f["final"] = float(
                            jax.device_get(holder_f["loss"])
                        )

                    fused_s, fspread = unit_seconds(
                        dispatch_f, fetch_f, target_s=4.0, cap=10
                    )
                    f_tflops = 6 * n_params * bsz * seq / fused_s / 1e12
                    f_mfu, f_warn = mfu(f_tflops)
                    report(
                        "lm_step_fused",
                        vocab_chunk=v_chunk,
                        step_ms=round(fused_s * 1e3, 1),
                        tokens_per_s=round(bsz * seq / fused_s),
                        tflops_6nd=round(f_tflops, 2),
                        mfu=f_mfu,
                        **({"mfu_warning": f_warn} if f_warn else {}),
                        speedup_vs_std_step=round(step_s / fused_s, 3),
                        final_loss=round(holder_f["final"], 4),
                        **fspread,
                    )
                except Exception as error:  # noqa: BLE001
                    report("lm_step_fused", error=repr(error))
            else:
                report("lm_step_fused", skipped="budget")
        except Exception as error:  # noqa: BLE001
            report("lm_step", error=repr(error))
    else:
        report("lm_step", skipped="budget")

    # -- 125M generation throughput (KV-cache decode) ----------------------
    if remaining() > 60:
        try:
            from covalent_tpu_plugin.models import (
                TransformerLM,
                generate,
                inference_params,
                lm_125m_config,
            )

            # Serving config (benchmarks/DECODE_SWEEP.md): bf16 inference
            # weights halve the per-step HBM reads and unrolled layers
            # cut per-step overheads — +48% tokens/s over the scanned
            # f32-master baseline at batch 8.
            if small:
                gen_config = lm_125m_config(
                    max_seq=128, n_layers=2, d_model=256, n_heads=4,
                    d_ff=1024, vocab_size=4096, scan_layers=False,
                )
                bsz, prompt_len, new_tokens = 2, 16, 32
            else:
                gen_config = lm_125m_config(max_seq=512, scan_layers=False)
                bsz, prompt_len, new_tokens = 8, 128, 128
            model = TransformerLM(gen_config)
            prompt = jax.random.randint(
                jax.random.PRNGKey(0), (bsz, prompt_len), 0,
                gen_config.vocab_size,
            )
            params = inference_params(
                model.init(jax.random.PRNGKey(1), prompt)["params"]
            )
            import statistics as stats_mod

            gen = jax.jit(
                lambda p, t: generate(model, p, t, max_new_tokens=new_tokens)
            )
            jax.device_get(gen(params, prompt)[0, -1])  # compile + warm

            def time_gen(fn, p):
                t0 = time.monotonic()
                out = fn(p, prompt)
                jax.device_get(out[0, -1])
                return time.monotonic() - t0

            # Weight-only int8 serving (models/quant.py): halves the
            # per-step HBM reads again on top of the bf16 cast.  Own try
            # so a quant failure can't lose the bf16 line below.
            qgen = qparams = None
            if remaining() > 30:
                try:
                    from covalent_tpu_plugin.models import quantize_lm

                    qmodel, qparams = quantize_lm(model, params)
                    qparams = inference_params(qparams)
                    qgen = jax.jit(
                        lambda p, t: generate(
                            qmodel, p, t, max_new_tokens=new_tokens
                        )
                    )
                    jax.device_get(qgen(qparams, prompt)[0, -1])  # warm
                except Exception as error:  # noqa: BLE001
                    report("lm_decode_int8", error=repr(error))
                    qgen = None

            # int8 KV cache: halves the per-step CACHE reads (the other
            # bandwidth half); also its own try.
            kvq_gen = None
            if remaining() > 30:
                try:
                    import dataclasses as _dc

                    kvq_model = TransformerLM(
                        _dc.replace(gen_config, quantized_kv_cache=True)
                    )
                    kvq_gen = jax.jit(
                        lambda p, t: generate(
                            kvq_model, p, t, max_new_tokens=new_tokens
                        )
                    )
                    jax.device_get(kvq_gen(params, prompt)[0, -1])  # warm
                except Exception as error:  # noqa: BLE001
                    report("lm_decode_kvq", error=repr(error))
                    kvq_gen = None

            # The FULL quantized serving stack: int8 weights AND int8 KV
            # in one model — both bandwidth halves cut together.
            full_q_gen = None
            if qgen is not None and remaining() > 30:
                try:
                    import dataclasses as _dc

                    fullq_model = TransformerLM(
                        _dc.replace(
                            qmodel.config, quantized_kv_cache=True
                        )
                    )
                    full_q_gen = jax.jit(
                        lambda p, t: generate(
                            fullq_model, p, t, max_new_tokens=new_tokens
                        )
                    )
                    jax.device_get(full_q_gen(qparams, prompt)[0, -1])
                except Exception as error:  # noqa: BLE001
                    report("lm_decode_fullq", error=repr(error))
                    full_q_gen = None

            # Like-for-like A/B: alternate bf16/int8 measurements inside
            # one phase so tunnel drift hits both arms equally (BENCH_r02's
            # int8 delta was within cross-session variance).  The int8 arm
            # keeps its own try at measurement time too — a quant-side
            # failure mid-loop must not void the bf16 numbers.
            bf16_times, int8_times, kvq_times = [], [], []
            fullq_times = []
            for _ in range(3):
                bf16_times.append(time_gen(gen, params))
                if qgen is not None:
                    try:
                        int8_times.append(time_gen(qgen, qparams))
                    except Exception as error:  # noqa: BLE001
                        report("lm_decode_int8", error=repr(error))
                        qgen, int8_times = None, []
                if kvq_gen is not None:
                    try:
                        kvq_times.append(time_gen(kvq_gen, params))
                    except Exception as error:  # noqa: BLE001
                        report("lm_decode_kvq", error=repr(error))
                        kvq_gen, kvq_times = None, []
                if full_q_gen is not None:
                    try:
                        fullq_times.append(time_gen(full_q_gen, qparams))
                    except Exception as error:  # noqa: BLE001
                        report("lm_decode_fullq", error=repr(error))
                        full_q_gen, fullq_times = None, []
            elapsed = stats_mod.median(bf16_times)
            # One batched prefill + (new_tokens - 1) decode steps share the
            # wall; metrics are labelled end-to-end, not per decode step.
            report(
                "lm_decode",
                prompt_len=prompt_len,
                new_tokens=new_tokens,
                batch=bsz,
                e2e_tokens_per_s=round(bsz * new_tokens / elapsed),
                e2e_ms_per_new_token=round(elapsed / new_tokens * 1e3, 2),
                e2e_s_spread=[round(t, 3) for t in sorted(bf16_times)],
            )
            serve_ctx = {
                "model": model, "params": params, "config": gen_config,
                "batch": bsz, "prompt_len": prompt_len,
                "new_tokens": new_tokens, "static_batch_s": elapsed,
            }
            if int8_times:
                q_elapsed = stats_mod.median(int8_times)
                report(
                    "lm_decode_int8",
                    batch=bsz,
                    tokens_per_s=round(bsz * new_tokens / q_elapsed),
                    ms_per_new_token=round(q_elapsed / new_tokens * 1e3, 2),
                    speedup_vs_bf16_same_phase=round(elapsed / q_elapsed, 3),
                    e2e_s_spread=[round(t, 3) for t in sorted(int8_times)],
                )
            elif qgen is None and remaining() <= 30:
                report("lm_decode_int8", skipped="budget")
            if kvq_times:
                kv_elapsed = stats_mod.median(kvq_times)
                report(
                    "lm_decode_kvq",
                    batch=bsz,
                    tokens_per_s=round(bsz * new_tokens / kv_elapsed),
                    speedup_vs_bf16_same_phase=round(
                        elapsed / kv_elapsed, 3
                    ),
                    e2e_s_spread=[round(t, 3) for t in sorted(kvq_times)],
                )
            if fullq_times:
                fq_elapsed = stats_mod.median(fullq_times)
                report(
                    "lm_decode_fullq",
                    batch=bsz,
                    tokens_per_s=round(bsz * new_tokens / fq_elapsed),
                    speedup_vs_bf16_same_phase=round(
                        elapsed / fq_elapsed, 3
                    ),
                    e2e_s_spread=[round(t, 3) for t in sorted(fullq_times)],
                )
        except Exception as error:  # noqa: BLE001
            report("lm_decode", error=repr(error))
    else:
        report("lm_decode", skipped="budget")

    # -- speculative decoding: trained draft/target pair (VERDICT r2 #4) ---
    # The serving stack's most advanced feature, previously proven exact
    # but never proven USEFUL: train a 2-layer draft + 6-layer target on
    # the learnable synthetic stream (models/data.py — the affine bigram
    # map drives both models to near-agreement in a few hundred steps),
    # then measure acceptance rate and end-to-end tokens/s vs plain decode
    # of the SAME target.
    if remaining() > 100:
        try:
            import statistics as stats_mod

            import optax

            from covalent_tpu_plugin.models import (
                TransformerLM,
                generate,
                inference_params,
                lm_125m_config,
                speculative_generate,
            )
            from covalent_tpu_plugin.models.data import synthetic_lm_batch
            from covalent_tpu_plugin.models.train import TrainState, lm_loss

            # The target must be MUCH heavier per decode step than the
            # draft or speculation cannot win (the r4 first run used a
            # 256-d toy target: accept 0.97, speedup 0.95 — every step
            # was launch-overhead-bound, so 4 draft steps + 1 verify cost
            # exactly 5 plain steps).  Production shape: the 125M-class
            # body (768×12) as target, a 128×2 draft — the setting the
            # feature exists for.
            if small:
                vocab, seq, sbsz = 512, 128, 16
                t_steps, d_steps = 30, 60
                spec_new, spec_prompt, spec_bsz = 48, 16, 2
                t_dims = dict(d_model=256, n_layers=6, n_heads=4, d_ff=1024)
            else:
                vocab, seq, sbsz = 512, 128, 32
                t_steps, d_steps = 120, 300
                spec_new, spec_prompt, spec_bsz = 192, 32, 8
                t_dims = {}  # 125M-class defaults (768 x 12)
            # draft_len 6 (not 4): acceptance on the trained pair runs
            # ~0.97, so a longer window amortises each verify slab
            # further — measured 1.14x at k=4.
            draft_len = 4 if small else 6
            cap = spec_prompt + spec_new + draft_len + 1
            t_cfg = lm_125m_config(
                vocab_size=vocab, max_seq=max(seq, cap),
                scan_layers=False, **t_dims,
            )
            d_cfg = lm_125m_config(
                vocab_size=vocab, d_model=128, n_layers=2, n_heads=4,
                d_ff=512, max_seq=max(seq, cap), scan_layers=False,
            )

            def train_lm(cfg, model_seed, train_steps):
                model = TransformerLM(cfg)
                tokens0 = jnp.asarray(
                    synthetic_lm_batch(sbsz, seq + 1, vocab, seed=0)["tokens"]
                )
                params = model.init(
                    jax.random.PRNGKey(model_seed), tokens0[:, :-1]
                )["params"]
                state = TrainState.create(
                    apply_fn=model.apply, params=params, tx=optax.adamw(1e-3)
                )

                @jax.jit
                def step(state, tokens):
                    loss, grads = jax.value_and_grad(
                        lambda p: lm_loss(
                            p, state.apply_fn, {"tokens": tokens}
                        )
                    )(state.params)
                    return state.apply_gradients(grads=grads), loss

                # Distinct batches each step (seed advances): honest
                # streaming, same rule the data module's stream uses.
                # Bail early when the phase budget runs low — a shorter
                # training run lowers acceptance but still completes the
                # phase (better than the parent killing the electron).
                # Always takes step 0 (compile can eat the margin BEFORE
                # the loop; a zero-step bail would leave loss undefined).
                loss = None
                for i in range(train_steps):
                    if i and i % 25 == 0 and remaining() < 60:
                        break
                    tokens = jnp.asarray(
                        synthetic_lm_batch(
                            sbsz, seq + 1, vocab, seed=1 + i
                        )["tokens"]
                    )
                    state, loss = step(state, tokens)
                return model, state.params, float(jax.device_get(loss))

            target_model, target_params, t_loss = train_lm(t_cfg, 1, t_steps)
            draft_model, draft_params, d_loss = train_lm(d_cfg, 2, d_steps)
            target_params = inference_params(target_params)
            draft_params = inference_params(draft_params)
            if remaining() < 45:
                # Training (or its compiles) ate the margin: the generate
                # compiles ahead are the expensive part — skip cleanly
                # rather than letting the parent kill the electron.
                raise TimeoutError("budget exhausted after draft training")

            prompt = jnp.asarray(
                synthetic_lm_batch(spec_bsz, spec_prompt, vocab, seed=999)[
                    "tokens"
                ]
            )
            plain = jax.jit(
                lambda p, t: generate(
                    target_model, p, t, max_new_tokens=spec_new
                )
            )
            spec = jax.jit(
                lambda tp, dp, t: speculative_generate(
                    target_model, tp, draft_model, dp, t, spec_new,
                    draft_len=draft_len, return_stats=True,
                )
            )
            out_plain = plain(target_params, prompt)
            out_spec, stats = spec(target_params, draft_params, prompt)
            jax.device_get(out_spec[0, -1])  # compile + warm both
            jax.device_get(out_plain[0, -1])
            exact = bool(
                jax.device_get((out_plain == out_spec).all())
            )  # bit-exactness contract, checked on-device
            rounds = int(jax.device_get(stats["rounds"]))
            # Each round commits (accepted drafts + 1): the +1 is the
            # correction or bonus token.  spec_new - 1 tokens came from
            # rounds rounds (token #1 is the prefill's), so accepted
            # drafts = spec_new - 1 - rounds of rounds * draft_len
            # proposals — the standard acceptance-rate definition.
            accept = (spec_new - 1 - rounds) / max(rounds * draft_len, 1)

            plain_t, spec_t = [], []
            for _ in range(3):  # alternating A/B, median
                t0 = time.monotonic()
                jax.device_get(plain(target_params, prompt)[0, -1])
                plain_t.append(time.monotonic() - t0)
                t0 = time.monotonic()
                out, _ = spec(target_params, draft_params, prompt)
                jax.device_get(out[0, -1])
                spec_t.append(time.monotonic() - t0)
            plain_s = stats_mod.median(plain_t)
            spec_s = stats_mod.median(spec_t)
            report(
                "lm_spec",
                target_loss=round(t_loss, 3),
                draft_loss=round(d_loss, 3),
                exact=exact,
                rounds=rounds,
                draft_len=draft_len,
                accept_rate=round(accept, 3),
                plain_tokens_per_s=round(spec_bsz * spec_new / plain_s),
                spec_tokens_per_s=round(spec_bsz * spec_new / spec_s),
                speedup=round(plain_s / spec_s, 3),
                plain_s_spread=[round(t, 3) for t in sorted(plain_t)],
                spec_s_spread=[round(t, 3) for t in sorted(spec_t)],
            )

            # Composed serving stack: the SAME spec machinery over an
            # int8-weight + int8-KV target (tests prove the composition
            # bit-exact vs the quantized target's own decode; this arm
            # measures it).  Own try: a quant failure must not void the
            # float lm_spec numbers above.
            if remaining() > 60:
                try:
                    import dataclasses as _dc

                    from covalent_tpu_plugin.models import quantize_lm

                    qt_model, qt_params = quantize_lm(
                        target_model, target_params
                    )
                    qt_model = TransformerLM(
                        _dc.replace(
                            qt_model.config, quantized_kv_cache=True
                        )
                    )
                    qplain = jax.jit(
                        lambda p, t: generate(
                            qt_model, p, t, max_new_tokens=spec_new
                        )
                    )
                    qspec = jax.jit(
                        lambda tp, dp, t: speculative_generate(
                            qt_model, tp, draft_model, dp, t, spec_new,
                            draft_len=draft_len, return_stats=True,
                        )
                    )
                    out_qp = qplain(qt_params, prompt)
                    out_qs, qstats = qspec(qt_params, draft_params, prompt)
                    jax.device_get(out_qp[0, -1])  # compile + warm
                    jax.device_get(out_qs[0, -1])
                    q_exact = bool(
                        jax.device_get((out_qp == out_qs).all())
                    )
                    q_rounds = int(jax.device_get(qstats["rounds"]))
                    q_accept = (spec_new - 1 - q_rounds) / max(
                        q_rounds * draft_len, 1
                    )
                    qp_t, qs_t = [], []
                    for _ in range(3):  # alternating A/B, median
                        t0 = time.monotonic()
                        jax.device_get(qplain(qt_params, prompt)[0, -1])
                        qp_t.append(time.monotonic() - t0)
                        t0 = time.monotonic()
                        out, _ = qspec(qt_params, draft_params, prompt)
                        jax.device_get(out[0, -1])
                        qs_t.append(time.monotonic() - t0)
                    qp_s = stats_mod.median(qp_t)
                    qs_s = stats_mod.median(qs_t)
                    report(
                        "lm_spec_quant",
                        exact=q_exact,
                        rounds=q_rounds,
                        accept_rate=round(q_accept, 3),
                        plain_tokens_per_s=round(
                            spec_bsz * spec_new / qp_s
                        ),
                        spec_tokens_per_s=round(
                            spec_bsz * spec_new / qs_s
                        ),
                        speedup=round(qp_s / qs_s, 3),
                        plain_s_spread=[round(t, 3) for t in sorted(qp_t)],
                        spec_s_spread=[round(t, 3) for t in sorted(qs_t)],
                    )
                except Exception as error:  # noqa: BLE001
                    report("lm_spec_quant", error=repr(error))
            else:
                report("lm_spec_quant", skipped="budget")
        except Exception as error:  # noqa: BLE001
            report("lm_spec", error=repr(error))
    else:
        report("lm_spec", skipped="budget")

    # -- continuous batching serving loop (beyond-parity; models/serve.py) -
    # A mixed-budget workload (half short, half long requests) through
    # fixed serving slots with rolling admission, vs static wave batching.
    # The static arm needs NO extra device work: a wave is exactly the
    # (batch, prompt_len) -> new_tokens generate() the lm_decode phase
    # already timed, so its wall is len(waves) * that measurement.  Step
    # accounting is structural (host arithmetic, sync-quantized the way
    # the real loop admits).  Runs last: it is the bonus phase that gets
    # skipped first when the budget is tight.
    if serve_ctx is not None and remaining() > 45:
        try:
            import numpy as np

            from covalent_tpu_plugin.models import (
                continuous_generate,
                step_accounting,
            )

            s_model = serve_ctx["model"]
            s_params = serve_ctx["params"]
            s_cfg = serve_ctx["config"]
            slots = serve_ctx["batch"]
            s_plen = serve_ctx["prompt_len"]
            long_cap = serve_ctx["new_tokens"]
            short_cap = max(2, long_cap // 4)
            n_req = 2 * slots
            # Admission granularity: the host only syncs every `sync`
            # decode steps, and each sync is a full round trip (65 ms on
            # the tunneled backend vs ~0.2 ms host-attached) — tunnelled
            # TPUs want it large (models/serve.py docstring).  Matching
            # the short budget keeps quantization stranding negligible.
            sync = min(32, max(8, short_cap))
            keys = jax.random.split(jax.random.PRNGKey(7), n_req)
            s_prompts = [
                np.asarray(
                    jax.random.randint(
                        keys[i], (s_plen,), 0, s_cfg.vocab_size
                    ),
                    np.int32,
                )
                for i in range(n_req)
            ]
            caps = [short_cap if i % 2 else long_cap for i in range(n_req)]

            serve_stats: dict = {}

            def run_serve():
                return continuous_generate(
                    s_model, s_params, s_prompts, caps,
                    max_batch=slots, sync_steps=sync, stats=serve_stats,
                )

            t0 = time.monotonic()
            outs = run_serve()  # compile + warm
            compile_wall = time.monotonic() - t0
            complete = all(
                o is not None and o.size == s_plen + c
                for o, c in zip(outs, caps)
            )

            # Structural decode-step accounting, shared with
            # benchmarks/serve_bench.py so the model cannot drift from
            # the admission rule continuous_generate implements.
            steps = step_accounting(caps, slots, sync)
            static_steps = steps["static_wave_steps"]
            cont_steps = steps["continuous_steps_sync"]
            n_waves = -(-n_req // slots)
            static_wall = n_waves * serve_ctx["static_batch_s"]
            structural = {
                "n_requests": n_req,
                "max_batch": slots,
                "sync_steps": sync,
                # Counters measured by the host loop itself
                # (models/serve.py `stats`): fused admission waves and
                # blocking fetches — the tunnel-dominated costs the wall
                # ratio carries that a host-attached TPU would not.
                "prefill_passes": serve_stats.get("prefill_passes"),
                "sync_fetches": serve_stats.get("sync_fetches"),
                "device_chunks": serve_stats.get("device_chunks"),
                "caps_short_long": [short_cap, long_cap],
                "complete": complete,
                "compile_wall_s": round(compile_wall, 2),
                "step_reduction_vs_static": round(
                    static_steps / cont_steps, 2
                ),
            }
            if remaining() < 12:
                # Compile ate the tail of the budget: salvage the
                # structural line rather than dying mid-timing with no
                # lm_serve report at all.
                report("lm_serve", **structural, skipped_timing="budget")
            else:
                serve_walls = []
                for _ in range(2):
                    t0 = time.monotonic()
                    outs = run_serve()
                    serve_walls.append(time.monotonic() - t0)
                wall = min(serve_walls)
                report(
                    "lm_serve",
                    **structural,
                    tokens_per_s=round(sum(caps) / wall),
                    wall_s=round(wall, 3),
                    wall_speedup_vs_static_waves=round(
                        static_wall / wall, 2
                    ),
                    serve_s_spread=[round(t, 3) for t in sorted(serve_walls)],
                )
        except Exception as error:  # noqa: BLE001
            report("lm_serve", error=repr(error))
    elif serve_ctx is not None:
        report("lm_serve", skipped="budget")

    progress.close()
    return results


async def tail_progress(path: str, collected: dict, stop: asyncio.Event) -> None:
    """Re-emit the accelerator electron's subphase lines as they appear."""
    pos = 0
    while True:
        try:
            with open(path) as f:
                f.seek(pos)
                chunk = f.read()
            # Only consume complete lines; a partial line stays for later.
            if chunk:
                complete, _, _ = chunk.rpartition("\n")
                for line in complete.splitlines():
                    if not line.strip():
                        continue
                    try:
                        data = json.loads(line)
                    except ValueError:
                        continue
                    collected[data.get("subphase", "?")] = data
                    emit({"phase": f"tpu.{data.pop('subphase', '?')}", **data})
                pos += len(complete) + (1 if complete else 0)
        except FileNotFoundError:
            pass
        if stop.is_set():
            return
        await asyncio.sleep(0.5)


async def main() -> None:
    workdir = f"/tmp/covalent-tpu-bench-{os.getpid()}"
    repo_root = os.path.dirname(os.path.abspath(__file__))
    executor = TPUExecutor(
        transport="local",
        cache_dir=f"{workdir}/cache",
        remote_cache=f"{workdir}/remote",
        python_path=sys.executable,
        poll_freq=0.2,
        pool_preload="cloudpickle",
        defer_cleanup=True,
        task_env={
            "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "JAX_COMPILATION_CACHE_DIR": JAX_CACHE_DIR,
        },
    )
    emit({"phase": "start", "pid": os.getpid(), "budgets_s": {
        "overhead": OVERHEAD_BUDGET_S, "fanout": FANOUT_BUDGET_S,
        "tpu": TPU_BUDGET_S,
    }})

    # Start the introspection plane before the first phase: the history
    # sampler needs to be recording WHILE phases run for their emitted
    # timelines to have points (0.25 s ticks — bench phases are seconds
    # long), and the SLO engine evaluates on every sample.
    try:
        from covalent_tpu_plugin.obs.history import ensure_history
        from covalent_tpu_plugin.obs.slo import ensure_slo_engine

        if os.environ.get("COVALENT_TPU_HISTORY_S"):
            ensure_history()  # env wins, incl. "0"/"off" to disable
        else:
            ensure_history(interval_s=0.25)
        ensure_slo_engine()
        from covalent_tpu_plugin.obs.tracestore import ensure_trace_store

        # Keep EVERY trace for the bench run (env still wins): the serve
        # phases' latency_attribution blocks and the CI completeness
        # assertions need each request's waterfall, not a 10% sample.
        ensure_trace_store().sample = float(
            os.environ.get("COVALENT_TPU_TRACE_SAMPLE", "") or 1.0
        )
    except Exception as error:  # noqa: BLE001 - observability never fatal
        emit({"phase": "introspection", "error": repr(error)})

    summary: dict = {}

    # ---- phase 1: dispatch overhead (the headline metric) ----------------
    overhead = None
    try:
        if "overhead" not in BENCH_PHASES:
            raise _PhaseSkipped

        async def overhead_phase():
            # Warm the pooled transport + agent; steady state is what an
            # N-electron lattice pays per electron.
            await executor.run(
                trivial_electron, [0], {}, {"dispatch_id": "warm", "node_id": 0}
            )
            overheads = []
            singles = []
            wall_overheads = []
            for i in range(OVERHEAD_PROBES):
                t0 = time.perf_counter()
                await executor.run(
                    trivial_electron, [i], {}, {"dispatch_id": "probe", "node_id": i}
                )
                singles.append(time.perf_counter() - t0)
                overheads.append(executor.last_timings["overhead"])
                wall_overheads.append(
                    executor.last_timings.get("wall_overhead", 0.0)
                )
            return overheads, singles, wall_overheads

        wire0 = wire_up_bytes()
        overheads, singles, wall_overheads = await asyncio.wait_for(
            overhead_phase(), OVERHEAD_BUDGET_S
        )
        overhead = statistics.median(overheads)
        summary["dispatch_overhead_s"] = round(overhead, 4)
        # Stage spans SUM pipelined work; the wall view is what the caller
        # actually waited with serialization overlapping the dial.
        summary["dispatch_wall_overhead_s"] = round(
            statistics.median(wall_overheads), 4
        )
        summary["electron_wall_s"] = round(statistics.median(singles), 4)
        summary["dispatch_overhead_ms_stdev"] = spread_stats(
            overheads, "overhead"
        ).get("overhead_ms_stdev")
        # SLO view: percentile summary of the wall overhead (what a caller
        # actually waited beyond the task), asserted against the dispatch
        # budget so CI turns red the day the control plane regresses.
        summary["wall_overhead_p50_s"] = round(
            percentile(wall_overheads, 0.50), 4
        )
        summary["wall_overhead_p95_s"] = round(
            percentile(wall_overheads, 0.95), 4
        )
        summary["wall_overhead_budget_s"] = WALL_OVERHEAD_BUDGET_S
        summary["wall_overhead_within_budget"] = (
            summary["wall_overhead_p95_s"] <= WALL_OVERHEAD_BUDGET_S
        )
        emit({"phase": "overhead", "dispatch_overhead_s": summary[
            "dispatch_overhead_s"], "per_probe": [round(o, 4) for o in overheads],
            "electron_wall_s": summary["electron_wall_s"],
            "wall_overhead_s": summary["dispatch_wall_overhead_s"],
            "wall_overhead_p50_s": summary["wall_overhead_p50_s"],
            "wall_overhead_p95_s": summary["wall_overhead_p95_s"],
            "wall_overhead_within_budget":
                summary["wall_overhead_within_budget"],
            # Per-stage latency breakdown of the final probe (same keys as
            # last_timings: connect/stage/upload/submit/execute/fetch/...).
            "breakdown": {
                k: round(v, 5) for k, v in executor.last_timings.items()
                if isinstance(v, (int, float))
            },
            "wire_bytes": round(wire_up_bytes() - wire0, 1),
            **spread_stats(overheads, "overhead"),
            **spread_stats(singles, "electron_wall")})
    except _PhaseSkipped:
        emit({"phase": "overhead", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "overhead", "error": repr(error)})

    # ---- phase 1b: telemetry tax (obs-on vs obs-off wall delta) ----------
    # The fleet observability plane (event stream + heartbeats + backhaul +
    # ops endpoint) must never become the new hot path: measure the same
    # trivial electron with everything on vs everything off
    # (COVALENT_TPU_METRICS=0 semantics: no events, no heartbeats) and
    # assert the per-electron delta stays under OBS_TAX_BUDGET_PCT.
    try:
        if "obs_tax" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.obs import events as obs_events
        from covalent_tpu_plugin.obs.opsserver import (
            ensure_ops_server,
            shutdown_ops_server,
        )

        OBS_TAX_PROBES = 7

        async def tax_arm(obs_on: bool) -> list:
            arm = "on" if obs_on else "off"
            # Agent (pool) mode on both arms: completion is PUSHED, so the
            # wall numbers measure real work, not poll-schedule alignment
            # (a poll-based arm quantizes to the probe boundary, which
            # dwarfs any telemetry delta with bimodal noise).
            arm_executor = TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_obs_{arm}",
                remote_cache=f"{workdir}/remote_obs_{arm}",
                python_path=sys.executable,
                poll_freq=0.2,
                heartbeat_interval=0.5 if obs_on else 0.0,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )
            if obs_on:
                obs_events.configure(f"{workdir}/obs_tax_events.jsonl")
                ensure_ops_server(port=0)
            else:
                obs_events.configure(None)
            walls = []
            try:
                await arm_executor.run(
                    trivial_electron, [0], {},
                    {"dispatch_id": f"taxwarm{arm}", "node_id": 0},
                )
                for i in range(OBS_TAX_PROBES):
                    t0 = time.perf_counter()
                    await arm_executor.run(
                        trivial_electron, [i], {},
                        {"dispatch_id": f"tax{arm}", "node_id": i},
                    )
                    walls.append(time.perf_counter() - t0)
            finally:
                await arm_executor.close()
                if obs_on:
                    shutdown_ops_server()
                obs_events.reset()
            return walls

        async def obs_tax_phase():
            # off first, then on: any residual warmup bias favors the OFF
            # arm, making the <budget assertion strictly harder to pass.
            off_walls = await tax_arm(False)
            on_walls = await tax_arm(True)
            return on_walls, off_walls

        on_walls, off_walls = await asyncio.wait_for(
            obs_tax_phase(), OVERHEAD_BUDGET_S
        )
        on_s = statistics.median(on_walls)
        off_s = statistics.median(off_walls)
        tax_pct = (on_s - off_s) / off_s * 100.0
        # 15 ms absolute floor keeps subprocess-spawn jitter from failing a
        # run whose relative delta is noise, not telemetry cost.
        tax_ok = on_s <= off_s * (1.0 + OBS_TAX_BUDGET_PCT / 100.0) + 0.015
        summary["obs_tax_on_wall_s"] = round(on_s, 4)
        summary["obs_tax_off_wall_s"] = round(off_s, 4)
        summary["obs_tax_pct"] = round(tax_pct, 2)
        summary["obs_tax_budget_pct"] = OBS_TAX_BUDGET_PCT
        summary["obs_tax_ok"] = tax_ok
        emit({
            "phase": "obs_tax",
            "on_wall_s": summary["obs_tax_on_wall_s"],
            "off_wall_s": summary["obs_tax_off_wall_s"],
            "tax_pct": summary["obs_tax_pct"],
            "budget_pct": OBS_TAX_BUDGET_PCT,
            "ok": tax_ok,
            **spread_stats(on_walls, "obs_on_wall"),
            **spread_stats(off_walls, "obs_off_wall"),
        })
    except _PhaseSkipped:
        emit({"phase": "obs_tax", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "obs_tax", "error": repr(error)})

    # ---- phase 2: 8-electron fan-out (BASELINE config 3) -----------------
    async def fanout8(fn, extra_args, dispatch_id):
        t0 = time.perf_counter()
        await asyncio.gather(
            *(
                executor.run(
                    fn, [i, *extra_args], {},
                    {"dispatch_id": dispatch_id, "node_id": i},
                )
                for i in range(8)
            )
        )
        return time.perf_counter() - t0

    try:
        if "fanout" not in BENCH_PHASES:
            raise _PhaseSkipped

        async def fanout_trials():
            # 3 trials -> median + spread (r3 verdict: honest statistics
            # on every phase, not just the TPU ones).
            return [await fanout8(trivial_electron, [], f"fan{t}")
                    for t in range(3)]

        wire0, ops0, upload0 = wire_up_bytes(), staging_ops(), upload_span_sum()
        fanout_walls = await asyncio.wait_for(fanout_trials(), FANOUT_BUDGET_S)
        fanout_wall = statistics.median(fanout_walls)
        single = summary.get("electron_wall_s") or fanout_wall / 8
        summary["fanout8_wall_s"] = round(fanout_wall, 3)
        summary["fanout8_per_electron_s"] = round(fanout_wall / 8, 4)
        summary["fanout8_speedup_vs_serial"] = round(8 * single / fanout_wall, 2)
        emit({"phase": "fanout8", **{k: summary[k] for k in (
            "fanout8_wall_s", "fanout8_per_electron_s",
            "fanout8_speedup_vs_serial")},
            # Dispatch-plane breakdown across the trials: staging round
            # trips, upload-stage seconds, and bytes shipped.
            "breakdown": {
                "staging_ops": round(staging_ops() - ops0, 1),
                "upload_s": round(upload_span_sum() - upload0, 4),
            },
            "wire_bytes": round(wire_up_bytes() - wire0, 1),
            **spread_stats(fanout_walls, "fanout8_wall")})
    except _PhaseSkipped:
        emit({"phase": "fanout8", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "fanout8", "error": repr(error)})

    # Same fan-out with 300 ms of real work per electron: serial would
    # take >= 2.4 s, so the wall directly exposes task concurrency.
    try:
        if "fanout" not in BENCH_PHASES:
            raise _PhaseSkipped
        task_s = 0.3

        async def busy_trials():
            return [await fanout8(busy_electron, [task_s], f"busy{t}")
                    for t in range(3)]

        busy_walls = await asyncio.wait_for(busy_trials(), FANOUT_BUDGET_S)
        busy_wall = statistics.median(busy_walls)
        summary["fanout8_busy_wall_s"] = round(busy_wall, 3)
        summary["fanout8_busy_speedup"] = round(8 * task_s / busy_wall, 2)
        emit({"phase": "fanout8_busy", "task_s": task_s, **{k: summary[k] for k in (
            "fanout8_busy_wall_s", "fanout8_busy_speedup")},
            **spread_stats(busy_walls, "fanout8_busy_wall")})
    except _PhaseSkipped:
        emit({"phase": "fanout8_busy", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "fanout8_busy", "error": repr(error)})

    # ---- phase 2b: two-level cache, same electron N times ----------------
    # Warm vs cold through a cache_results executor: the cold first run
    # pays connect + CAS-miss uploads + launch + execute; the warm repeats
    # memoize (level 2) and, where they do dispatch, skip repeat payloads
    # (level 1).  The trajectory JSON carries the measured speedup plus the
    # hit/miss counter deltas so the win is attributable, not inferred.
    try:
        if "cached_fanout" not in BENCH_PHASES:
            raise _PhaseSkipped

        def cache_counters() -> dict:
            # Same public snapshot path as the final line's metrics_totals.
            return {
                key: value
                for key, value in metrics_totals().items()
                if key.startswith(("covalent_tpu_result_cache_total",
                                   "covalent_tpu_cas_uploads_total"))
            }

        async def cached_phase():
            cache_ex = TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_memo",
                remote_cache=f"{workdir}/remote_memo",
                python_path=sys.executable,
                poll_freq=0.2,
                pool_preload="cloudpickle",
                cache_results=True,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )
            try:
                t0 = time.perf_counter()
                await cache_ex.run(
                    trivial_electron, [7], {},
                    {"dispatch_id": "cache_cold", "node_id": 0},
                )
                cold = time.perf_counter() - t0
                warm = []
                for i in range(4):
                    t0 = time.perf_counter()
                    await cache_ex.run(
                        trivial_electron, [7], {},
                        {"dispatch_id": "cache_warm", "node_id": i},
                    )
                    warm.append(time.perf_counter() - t0)
            finally:
                await cache_ex.close()
            return cold, warm

        counters_before = cache_counters()
        cold_s, warm_list = await asyncio.wait_for(
            cached_phase(), FANOUT_BUDGET_S
        )
        warm_s = statistics.median(warm_list)
        counters_delta = {
            key: round(value - counters_before.get(key, 0.0), 1)
            for key, value in cache_counters().items()
            if value != counters_before.get(key, 0.0)
        }
        summary["cached_fanout_cold_s"] = round(cold_s, 4)
        summary["cached_fanout_warm_s"] = round(warm_s, 4)
        summary["cached_fanout_speedup"] = round(cold_s / max(warm_s, 1e-9), 2)
        summary["cached_fanout_warm_below_cold"] = bool(warm_s < cold_s)
        emit({
            "phase": "cached_fanout",
            "cold_s": summary["cached_fanout_cold_s"],
            "warm_s_median": summary["cached_fanout_warm_s"],
            "warm_per_run_s": [round(w, 4) for w in warm_list],
            "speedup": summary["cached_fanout_speedup"],
            "warm_below_cold": summary["cached_fanout_warm_below_cold"],
            "cache_counters_delta": counters_delta,
            **spread_stats(warm_list, "warm"),
        })
    except _PhaseSkipped:
        emit({"phase": "cached_fanout", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "cached_fanout", "error": repr(error)})

    # ---- phase 2b': bundled+compressed staging vs the per-file path ------
    # Two cold 4-electron fan-outs with identical unique-payload electrons:
    # one through the PR-2 per-file CAS path (bundle=False, compress=off),
    # one through the fast path (one compressed tar per worker).  Both run
    # over a ChaosTransport that ONLY injects per-op latency (a simulated
    # network RTT, deterministic — a pure-local wire would hide the round
    # trips this phase exists to count).  The counters give exact round
    # trips + wire bytes; upload-span seconds give the staging latency.
    try:
        if "bundled_fanout" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.transport import ChaosPlan as _ChaosPlan

        def fastpath_executor(tag: str, bundle: bool, compress: str):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_{tag}",
                remote_cache=f"{workdir}/remote_{tag}",
                python_path=sys.executable,
                poll_freq=0.2,
                use_agent=False,  # nohup path: identical launch RTs both ways
                prewarm=False,
                bundle=bundle,
                compress=compress,
                # 60 ms simulated RTT per op — a realistic cross-zone SSH
                # round trip.  The chaos wrapper also makes every publish
                # a real shell round trip (its rename/remove ride run, as
                # on a genuine wire), so the per-file path pays its honest
                # per-artifact exec cost.
                chaos=_ChaosPlan(delay=0.06),
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        async def measured_fanout(ex, dispatch_id):
            # SEQUENTIAL electrons: this phase measures per-electron
            # staging cost, and serial dispatch keeps the upload spans
            # free of single-flight waits and CPU contention between
            # concurrent unpack execs (fanout8 owns the concurrency
            # story).
            ops0, wire0, up0 = staging_ops(), wire_up_bytes(), upload_span_sum()
            t0 = time.perf_counter()
            results = []
            for i in range(4):
                results.append(await ex.run(
                    payload_electron, [i, BUNDLE_PAYLOAD + str(i)], {},
                    {"dispatch_id": dispatch_id, "node_id": i},
                ))
            return {
                "wall_s": time.perf_counter() - t0,
                "staging_ops": staging_ops() - ops0,
                "wire_bytes": wire_up_bytes() - wire0,
                "upload_s": upload_span_sum() - up0,
                "results": results,
            }

        async def bundled_phase():
            per = fastpath_executor("perfile", bundle=False, compress="off")
            try:
                perfile = await measured_fanout(per, "perfilefan")
            finally:
                await per.close()
            bun = fastpath_executor("bundled", bundle=True, compress="auto")
            try:
                bundled = await measured_fanout(bun, "bundledfan")
            finally:
                await bun.close()
            return perfile, bundled

        perfile, bundled = await asyncio.wait_for(
            bundled_phase(), FANOUT_BUDGET_S
        )
        # Equal results at fewer round trips / fewer bytes is the claim.
        assert bundled["results"] == perfile["results"], (
            bundled["results"], perfile["results"])
        summary["bundled_fanout_wall_s"] = round(bundled["wall_s"], 3)
        summary["bundled_fanout_perfile_wall_s"] = round(perfile["wall_s"], 3)
        summary["bundled_fanout_staging_ops"] = round(
            bundled["staging_ops"], 1)
        summary["bundled_fanout_perfile_staging_ops"] = round(
            perfile["staging_ops"], 1)
        summary["bundled_fanout_wire_bytes"] = round(bundled["wire_bytes"], 1)
        summary["bundled_fanout_perfile_wire_bytes"] = round(
            perfile["wire_bytes"], 1)
        summary["bundled_fanout_upload_s"] = round(bundled["upload_s"], 4)
        summary["bundled_fanout_perfile_upload_s"] = round(
            perfile["upload_s"], 4)
        summary["bundled_fanout_fewer_round_trips"] = bool(
            bundled["staging_ops"] < perfile["staging_ops"])
        summary["bundled_fanout_fewer_wire_bytes"] = bool(
            bundled["wire_bytes"] < perfile["wire_bytes"])
        # "No slower" is judged on the staging latency the feature owns
        # (upload spans): whole-electron wall also rides along, but its
        # poll-cadence noise under the injected RTT is not the feature's.
        summary["bundled_fanout_staging_no_slower"] = bool(
            bundled["upload_s"] <= perfile["upload_s"])
        emit({
            "phase": "bundled_fanout",
            "wall_s": summary["bundled_fanout_wall_s"],
            "perfile_wall_s": summary["bundled_fanout_perfile_wall_s"],
            "staging_ops": summary["bundled_fanout_staging_ops"],
            "perfile_staging_ops":
                summary["bundled_fanout_perfile_staging_ops"],
            "wire_bytes": summary["bundled_fanout_wire_bytes"],
            "perfile_wire_bytes":
                summary["bundled_fanout_perfile_wire_bytes"],
            "upload_s": summary["bundled_fanout_upload_s"],
            "perfile_upload_s": summary["bundled_fanout_perfile_upload_s"],
            "fewer_round_trips":
                summary["bundled_fanout_fewer_round_trips"],
            "fewer_wire_bytes": summary["bundled_fanout_fewer_wire_bytes"],
            "staging_no_slower":
                summary["bundled_fanout_staging_no_slower"],
        })
    except _PhaseSkipped:
        emit({"phase": "bundled_fanout", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "bundled_fanout", "error": repr(error)})

    # ---- phase 2b'': RPC dispatch vs process launch, same 8-fanout -------
    # The ROADMAP item-3 claim, measured: after the connection-scoped warm
    # -up (dial, pre-flight, pool server, register_fn), an RPC-mode
    # electron costs one invoke write + one pushed result on the agent
    # channel — no harness process, no pid file, no staging, no poll, no
    # result fetch — so its per-electron wall_overhead must sit in the
    # tens of milliseconds where launch mode sits in the hundreds (or
    # seconds on a real wire).  Both arms run the SAME 8 electrons
    # sequentially over a ChaosTransport injecting per-op latency (a
    # simulated cross-zone RTT: the round trips RPC mode eliminates must
    # cost something, as on a genuine wire), through the same pool-agent
    # runtime; results must be byte-equal across modes, and the RPC
    # median is asserted against BENCH_RPC_OVERHEAD_BUDGET_S in CI.
    try:
        if "rpc_overhead" not in BENCH_PHASES:
            raise _PhaseSkipped
        import cloudpickle as _cloudpickle

        from covalent_tpu_plugin.transport import ChaosPlan as _RpcChaosPlan

        RPC_ELECTRONS = 8

        def rpc_arm_executor(tag: str, mode: str, frames: bool = True):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_rpc_{tag}",
                remote_cache=f"{workdir}/remote_rpc_{tag}",
                python_path=sys.executable,
                poll_freq=0.2,
                use_agent="pool",
                pool_preload="cloudpickle",
                dispatch_mode=mode,
                agent_frames=frames,
                prewarm=False,
                heartbeat_interval=0.0,
                # 30 ms simulated RTT per control-plane op; the agent
                # channel itself is a held-open stream, so RPC invokes
                # ride it untaxed — exactly the wire economics the mode
                # exists to exploit.  dispatch_mode="rpc" stays pinned
                # under the plan ("auto" would defer to launch).
                chaos=_RpcChaosPlan(delay=0.03),
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        async def rpc_arm(tag: str, mode: str, frames: bool = True) -> dict:
            ex = rpc_arm_executor(tag, mode, frames)
            overheads, results, modes = [], [], []
            wire0 = agent_wire_bytes()
            framed0 = agent_frames("invoke") + agent_frames("multi_invoke")
            try:
                # Warm-up electron pays the connection-scoped costs (pool
                # server start, harness/function staging, register_fn) so
                # the measured electrons show the steady state.  It runs
                # the MEASURED function so its digest registration (CAS
                # put + register round trips under the injected RTT) is
                # amortized too — otherwise the first measured electron
                # carries a ~100ms outlier into both wire arms' spreads.
                await ex.run(
                    payload_electron, [99, BUNDLE_PAYLOAD], {},
                    {"dispatch_id": f"rpcwarm{tag}", "node_id": 0},
                )
                wire0 = agent_wire_bytes()  # exclude warm-up traffic
                framed0 = (
                    agent_frames("invoke") + agent_frames("multi_invoke")
                )
                t0 = time.perf_counter()
                for i in range(RPC_ELECTRONS):
                    results.append(await ex.run(
                        payload_electron, [i, BUNDLE_PAYLOAD], {},
                        {"dispatch_id": f"rpcfan{tag}", "node_id": i},
                    ))
                    overheads.append(
                        ex.last_timings.get("wall_overhead", 0.0)
                    )
                    modes.append(ex.last_dispatch_mode)
                wall = time.perf_counter() - t0
            finally:
                await ex.close()
            return {
                "wall_s": wall,
                "overheads": overheads,
                "results": results,
                "modes": modes,
                "wire_bytes": agent_wire_bytes() - wire0,
                "framed_invokes": (
                    agent_frames("invoke") + agent_frames("multi_invoke")
                    - framed0
                ),
            }

        async def rpc_phase():
            launch = await rpc_arm("launch", "launch")
            # Both wire arms in the SAME run: the binary-frame claim is a
            # measured speedup over the JSONL fallback, not an assertion
            # against history.
            jsonl = await rpc_arm("jsonl", "rpc", frames=False)
            rpc = await rpc_arm("rpc", "rpc", frames=True)
            return launch, jsonl, rpc

        launch_arm, jsonl_arm, rpc_arm_run = await asyncio.wait_for(
            rpc_phase(), FANOUT_BUDGET_S * 3
        )
        # The fast path must have actually engaged — a silent fallback to
        # launch would "pass" the budget by measuring the wrong thing —
        # and the binary arm must have actually shipped frames (a silent
        # JSONL fallback would "pass" by measuring the wrong protocol).
        assert all(m == "rpc" for m in rpc_arm_run["modes"]), (
            rpc_arm_run["modes"])
        assert all(m == "rpc" for m in jsonl_arm["modes"]), (
            jsonl_arm["modes"])
        assert all(m == "launch" for m in launch_arm["modes"]), (
            launch_arm["modes"])
        assert rpc_arm_run["framed_invokes"] >= RPC_ELECTRONS, (
            rpc_arm_run["framed_invokes"])
        assert jsonl_arm["framed_invokes"] == 0, (
            jsonl_arm["framed_invokes"])
        # Byte-equal results across ALL arms: the streamed (result,
        # exception) pickle must carry exactly what the staged result file
        # does, whichever encoding the channel negotiated.
        byte_equal = (
            _cloudpickle.dumps(rpc_arm_run["results"])
            == _cloudpickle.dumps(launch_arm["results"])
            == _cloudpickle.dumps(jsonl_arm["results"])
        )
        assert rpc_arm_run["results"] == launch_arm["results"], (
            rpc_arm_run["results"], launch_arm["results"])
        assert rpc_arm_run["results"] == jsonl_arm["results"], (
            rpc_arm_run["results"], jsonl_arm["results"])
        rpc_median = statistics.median(rpc_arm_run["overheads"])
        jsonl_median = statistics.median(jsonl_arm["overheads"])
        launch_median = statistics.median(launch_arm["overheads"])
        summary["rpc_overhead_s"] = round(rpc_median, 4)
        summary["rpc_overhead_jsonl_s"] = round(jsonl_median, 4)
        summary["rpc_overhead_launch_s"] = round(launch_median, 4)
        summary["rpc_overhead_budget_s"] = RPC_OVERHEAD_BUDGET_S
        summary["rpc_overhead_within_budget"] = bool(
            rpc_median <= RPC_OVERHEAD_BUDGET_S
        )
        summary["rpc_results_byte_equal"] = bool(byte_equal)
        summary["rpc_overhead_speedup"] = round(
            launch_median / max(rpc_median, 1e-9), 2
        )
        # The binary-frame claims, asserted against the JSONL arm of the
        # SAME run: no slower on median wall overhead (timing — speedup
        # reported), strictly fewer bytes on the agent channel for the
        # same electrons (deterministic — base64 alone is a 33% tax).
        summary["rpc_frames_speedup"] = round(
            jsonl_median / max(rpc_median, 1e-9), 2
        )
        # 5% + 1ms noise floor: both arms' medians sit under 5ms, where
        # a fraction-of-a-millisecond scheduler hiccup on a loaded CI
        # machine flips a bare <= — the same timer-noise floor rationale
        # as obs_tax's absolute allowance.
        summary["rpc_frames_no_slower"] = bool(
            rpc_median <= jsonl_median * 1.05 + 0.001
        )
        summary["rpc_wire_bytes_per_electron"] = round(
            rpc_arm_run["wire_bytes"] / RPC_ELECTRONS, 1
        )
        summary["rpc_jsonl_wire_bytes_per_electron"] = round(
            jsonl_arm["wire_bytes"] / RPC_ELECTRONS, 1
        )
        summary["rpc_frames_fewer_wire_bytes"] = bool(
            rpc_arm_run["wire_bytes"] < jsonl_arm["wire_bytes"]
        )
        emit({
            "phase": "rpc_overhead",
            "electrons": RPC_ELECTRONS,
            "rpc_overhead_s": summary["rpc_overhead_s"],
            "jsonl_overhead_s": summary["rpc_overhead_jsonl_s"],
            "launch_overhead_s": summary["rpc_overhead_launch_s"],
            "rpc_wall_s": round(rpc_arm_run["wall_s"], 3),
            "jsonl_wall_s": round(jsonl_arm["wall_s"], 3),
            "launch_wall_s": round(launch_arm["wall_s"], 3),
            "frames_speedup": summary["rpc_frames_speedup"],
            "frames_no_slower": summary["rpc_frames_no_slower"],
            "wire_bytes_per_electron":
                summary["rpc_wire_bytes_per_electron"],
            "jsonl_wire_bytes_per_electron":
                summary["rpc_jsonl_wire_bytes_per_electron"],
            "frames_fewer_wire_bytes":
                summary["rpc_frames_fewer_wire_bytes"],
            "framed_invokes": rpc_arm_run["framed_invokes"],
            "per_electron_rpc_s": [
                round(o, 4) for o in rpc_arm_run["overheads"]
            ],
            "per_electron_launch_s": [
                round(o, 4) for o in launch_arm["overheads"]
            ],
            "budget_s": RPC_OVERHEAD_BUDGET_S,
            "within_budget": summary["rpc_overhead_within_budget"],
            "results_byte_equal": summary["rpc_results_byte_equal"],
            "speedup": summary["rpc_overhead_speedup"],
            # Regression-comparable timeline + budget verdicts, not just
            # the point medians above.
            "introspection": introspection_view([
                "covalent_tpu_wall_overhead_seconds",
                "covalent_tpu_tasks_total",
            ]),
            **spread_stats(rpc_arm_run["overheads"], "rpc_overhead"),
        })
    except _PhaseSkipped:
        emit({"phase": "rpc_overhead", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "rpc_overhead", "error": repr(error)})

    # ---- phase 2b2: resident serving session vs per-electron dispatch ----
    # The serving tier's whole argument in one phase: a generate "model"
    # that costs SERVE_LOAD_S to load+compile and SERVE_STEP_S per decode
    # chunk, driven two ways over the same pool-agent runtime.  The
    # per-electron arm pays the load on EVERY call (exactly what a generate
    # electron pays today, even via the millisecond RPC path); the resident
    # arm opens ONE session — the factory runs once — and fires every
    # request concurrently through the handle, sharing the engine's
    # fixed-slot batch.  Token streams must be identical across arms; the
    # resident arm must beat the per-electron arm on p50 request latency by
    # SERVE_SPEEDUP_MIN and on aggregate tokens/s, with streamed TTFT
    # strictly inside full-response latency — all asserted in CI.
    try:
        if "serve_traffic" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin import serving as _serving

        serve_chunk = 4  # tokens per decode chunk (per busy lane per step)

        def _serve_tokens_for(seed: int) -> list:
            return [seed * 100 + j + 1 for j in range(SERVE_TOKENS)]

        def make_serve_factory(load_s: float, step_s: float, slots: int = 4):
            # Closure-local engine: cloudpickled BY VALUE into the CAS, so
            # the resident worker needs no bench import.  Same duck-typed
            # surface ContinuousEngine implements for real LMs.
            def factory():
                import time as _time

                _time.sleep(load_s)  # the amortized cost: load + compile

                class Engine:
                    def __init__(self):
                        self.slots = slots
                        self.lanes = {}

                    def admit(self, rid, prompt, params):
                        seed = int(prompt[-1])
                        cap = int((params or {}).get(
                            "max_new_tokens", SERVE_TOKENS
                        ))
                        self.lanes[rid] = [
                            seed * 100 + j + 1 for j in range(cap)
                        ]

                    def step(self):
                        _time.sleep(step_s)  # one decode chunk, all lanes
                        events = []
                        for rid in list(self.lanes):
                            chunk = self.lanes[rid][:serve_chunk]
                            self.lanes[rid] = self.lanes[rid][serve_chunk:]
                            done = not self.lanes[rid]
                            if done:
                                del self.lanes[rid]
                            events.append({
                                "rid": rid, "tokens": chunk, "done": done,
                            })
                        return events

                    def cancel(self, rid):
                        self.lanes.pop(rid, None)

                return Engine()

            return factory

        def generate_electron(seed, n_tokens, load_s, step_s):
            # The per-electron status quo: model load + compile, then the
            # same decode chunks — all paid inside ONE call.
            import math
            import time as _time

            _time.sleep(load_s)
            for _ in range(math.ceil(n_tokens / serve_chunk)):
                _time.sleep(step_s)
            return [seed * 100 + j + 1 for j in range(n_tokens)]

        def serve_arm_executor(tag: str):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_serve_{tag}",
                remote_cache=f"{workdir}/remote_serve_{tag}",
                python_path=sys.executable,
                poll_freq=0.2,
                use_agent="pool",
                pool_preload="cloudpickle",
                dispatch_mode="rpc",
                prewarm=False,
                heartbeat_interval=0.0,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        async def per_electron_arm() -> dict:
            ex = serve_arm_executor("electron")
            latencies, results = [], []
            try:
                # Warm-up pays connection-scoped costs (pool server, fn
                # registration) so the arm measures steady-state per-call
                # economics, exactly like the rpc_overhead phase.
                await ex.run(
                    generate_electron, [0, 1, 0.0, 0.0], {},
                    {"dispatch_id": "servewarm", "node_id": 0},
                )
                t0 = time.perf_counter()
                for i in range(SERVE_REQUESTS):
                    t_req = time.perf_counter()
                    results.append(await ex.run(
                        generate_electron,
                        [i, SERVE_TOKENS, SERVE_LOAD_S, SERVE_STEP_S], {},
                        {"dispatch_id": "servefan", "node_id": i},
                    ))
                    latencies.append(time.perf_counter() - t_req)
                wall = time.perf_counter() - t0
            finally:
                await ex.close()
            return {"wall_s": wall, "latencies": latencies,
                    "results": results}

        async def resident_arm() -> dict:
            ex = serve_arm_executor("resident")
            batches0 = agent_frames("telemetry_batch")
            wire_down0 = agent_wire_bytes()
            try:
                t_open0 = time.perf_counter()
                handle = await _serving.open_session(
                    ex,
                    make_serve_factory(SERVE_LOAD_S, SERVE_STEP_S),
                    stats_interval_s=0.2,
                )
                open_s = time.perf_counter() - t_open0
                t0 = time.perf_counter()
                requests = [
                    await handle.request(
                        [i], params={"max_new_tokens": SERVE_TOKENS},
                        tenant=f"t{i % 2}",
                    )
                    for i in range(SERVE_REQUESTS)
                ]
                results = await asyncio.gather(
                    *(r.result(timeout=SERVE_BUDGET_S) for r in requests)
                )
                wall = time.perf_counter() - t0
                latencies = [r.latency_s for r in requests]
                ttfts = [r.ttft_s for r in requests]
                stats = dict(handle.stats)
                await handle.close()
            finally:
                await ex.close()
            return {
                "wall_s": wall, "open_s": open_s, "latencies": latencies,
                "ttfts": ttfts, "results": list(results), "stats": stats,
                "coalesced_batches": (
                    agent_frames("telemetry_batch") - batches0
                ),
                "wire_bytes": agent_wire_bytes() - wire_down0,
            }

        async def serve_phase():
            electron = await per_electron_arm()
            resident = await resident_arm()
            return electron, resident

        electron_arm, resident_arm_run = await asyncio.wait_for(
            serve_phase(), SERVE_BUDGET_S
        )
        expected = [_serve_tokens_for(i) for i in range(SERVE_REQUESTS)]
        assert electron_arm["results"] == expected, electron_arm["results"]
        assert resident_arm_run["results"] == expected, (
            resident_arm_run["results"])
        assert all(t is not None for t in resident_arm_run["ttfts"])
        electron_p50 = percentile(electron_arm["latencies"], 0.50)
        electron_p99 = percentile(electron_arm["latencies"], 0.99)
        resident_p50 = percentile(resident_arm_run["latencies"], 0.50)
        resident_p99 = percentile(resident_arm_run["latencies"], 0.99)
        ttft_p50 = percentile(resident_arm_run["ttfts"], 0.50)
        total_tokens = SERVE_REQUESTS * SERVE_TOKENS
        electron_tps = total_tokens / max(electron_arm["wall_s"], 1e-9)
        resident_tps = total_tokens / max(resident_arm_run["wall_s"], 1e-9)
        speedup = electron_p50 / max(resident_p50, 1e-9)
        summary["serve_p50_s"] = round(resident_p50, 4)
        summary["serve_p99_s"] = round(resident_p99, 4)
        summary["serve_electron_p50_s"] = round(electron_p50, 4)
        summary["serve_ttft_p50_s"] = round(ttft_p50, 4)
        summary["serve_tokens_per_s"] = round(resident_tps, 1)
        summary["serve_electron_tokens_per_s"] = round(electron_tps, 1)
        summary["serve_speedup"] = round(speedup, 2)
        summary["serve_speedup_min"] = SERVE_SPEEDUP_MIN
        summary["serve_beats_per_electron"] = bool(
            speedup >= SERVE_SPEEDUP_MIN and resident_tps > electron_tps
        )
        # Streaming must be real: first tokens land while the stream is
        # still going, not at end-of-batch.
        summary["serve_ttft_streams_early"] = bool(ttft_p50 < resident_p50)
        # Token coalescing: the resident arm's streams — already asserted
        # token-identical above — must have ridden batched binary frames,
        # and the per-token wire cost is a first-class observable.
        summary["serve_coalesced_batches"] = round(
            resident_arm_run["coalesced_batches"], 1
        )
        summary["serve_coalescing_engaged"] = bool(
            resident_arm_run["coalesced_batches"] >= 1
        )
        summary["serve_wire_bytes_per_token"] = round(
            resident_arm_run["wire_bytes"] / max(total_tokens, 1), 1
        )
        emit({
            "phase": "serve_traffic",
            "requests": SERVE_REQUESTS,
            "tokens_per_request": SERVE_TOKENS,
            "model_load_s": SERVE_LOAD_S,
            "resident_p50_s": summary["serve_p50_s"],
            "resident_p99_s": summary["serve_p99_s"],
            "resident_ttft_p50_s": summary["serve_ttft_p50_s"],
            "resident_tokens_per_s": summary["serve_tokens_per_s"],
            "resident_wall_s": round(resident_arm_run["wall_s"], 3),
            "resident_open_s": round(resident_arm_run["open_s"], 3),
            "per_electron_p50_s": summary["serve_electron_p50_s"],
            "per_electron_p99_s": round(electron_p99, 4),
            "per_electron_tokens_per_s":
                summary["serve_electron_tokens_per_s"],
            "per_electron_wall_s": round(electron_arm["wall_s"], 3),
            "speedup": summary["serve_speedup"],
            "speedup_min": SERVE_SPEEDUP_MIN,
            "beats_per_electron": summary["serve_beats_per_electron"],
            "ttft_streams_early": summary["serve_ttft_streams_early"],
            "coalesced_batches": summary["serve_coalesced_batches"],
            "coalescing_engaged": summary["serve_coalescing_engaged"],
            "wire_bytes_per_token": summary["serve_wire_bytes_per_token"],
            "worker_stats": resident_arm_run["stats"],
            # The serving timeline (tokens/s + queue depth per session,
            # windowed latency/TTFT percentiles) + end-of-phase SLO
            # verdicts: BENCH artifacts carry the whole shape of the
            # phase, not just its point summary.
            "introspection": introspection_view([
                "covalent_tpu_serve_tokens_per_s",
                "covalent_tpu_serve_queue_depth",
                "covalent_tpu_serve_request_seconds",
                "covalent_tpu_serve_ttft_seconds",
            ]),
            **spread_stats(resident_arm_run["latencies"], "serve_latency"),
        })
    except _PhaseSkipped:
        emit({"phase": "serve_traffic", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "serve_traffic", "error": repr(error)})

    # ---- phase 2b3: horizontal serving scale (replica sets) --------------
    # ONE resident session's ceiling is one engine's slot count; this
    # phase offers the SAME concurrent load to a 1-replica set and an
    # N-replica set (each replica its own pool-server process, so the
    # step_s decode sleeps genuinely parallelize) and asserts the three
    # scaling SLOs: aggregate tokens/s grows >= SERVE_SCALE_MIN from
    # 1 -> N replicas, p99 request latency at N is no worse than at 1,
    # and the router's median per-request decision stays under
    # ROUTER_DECISION_BUDGET_S — scaling out must not re-tax the dispatch
    # path.  A final arm proves the engine-side half of the ISSUE:
    # shared-prefix prefill reuse on the REAL ContinuousEngine, bit-equal
    # greedy streams with measurably fewer prefill positions.
    try:
        if "serve_scale" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.serving import open_replica_set

        def make_scale_factory(step_s: float, slots: int = 4):
            # Same closure-local stub shape as serve_traffic: streams are
            # deterministic per prompt, one step_s sleep per decode chunk
            # across all busy lanes — the per-process serial resource a
            # replica adds a copy of.
            def factory():
                import time as _time

                class Engine:
                    def __init__(self):
                        self.slots = slots
                        self.lanes = {}

                    def admit(self, rid, prompt, params):
                        seed = int(prompt[-1])
                        cap = int((params or {}).get(
                            "max_new_tokens", SERVE_SCALE_TOKENS
                        ))
                        self.lanes[rid] = [
                            seed * 100 + j + 1 for j in range(cap)
                        ]

                    def step(self):
                        _time.sleep(step_s)
                        events = []
                        for rid in list(self.lanes):
                            chunk = self.lanes[rid][:4]
                            self.lanes[rid] = self.lanes[rid][4:]
                            done = not self.lanes[rid]
                            if done:
                                del self.lanes[rid]
                            events.append({
                                "rid": rid, "tokens": chunk, "done": done,
                            })
                        return events

                    def cancel(self, rid):
                        self.lanes.pop(rid, None)

                return Engine()

            return factory

        def scale_executor(tag: str):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_scale_{tag}",
                remote_cache=f"{workdir}/remote_scale_{tag}",
                python_path=sys.executable,
                poll_freq=0.2,
                use_agent="pool",
                pool_preload="cloudpickle",
                prewarm=False,
                heartbeat_interval=0.0,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        async def scale_arm(n_replicas: int) -> dict:
            executors = [
                scale_executor(f"n{n_replicas}_{i}")
                for i in range(n_replicas)
            ]
            try:
                rset = await open_replica_set(
                    executors,
                    make_scale_factory(SERVE_SCALE_STEP_S),
                    name=f"scale{n_replicas}",
                    stats_interval_s=0.2,
                )
                t0 = time.perf_counter()
                requests = [
                    await rset.request(
                        [i],
                        params={"max_new_tokens": SERVE_SCALE_TOKENS},
                        tenant=f"t{i % 2}",
                    )
                    for i in range(SERVE_SCALE_REQUESTS)
                ]
                results = await asyncio.gather(
                    *(
                        r.result(timeout=SERVE_SCALE_BUDGET_S)
                        for r in requests
                    )
                )
                wall = time.perf_counter() - t0
                latencies = [r.latency_s for r in requests]
                trace_ids = [r.span.trace_id for r in requests]
                decisions = sorted(rset.decision_s)
                status = rset.status()
                await rset.close()
            finally:
                for ex in executors:
                    await ex.close()
            return {
                "wall_s": wall,
                "latencies": latencies,
                "trace_ids": trace_ids,
                "results": list(results),
                "decisions": decisions,
                "per_replica_served": {
                    rid: view["served"]
                    for rid, view in status["replicas"].items()
                },
            }

        def prefix_probe(prefix_len, n_requests, cap):
            # Runs INSIDE a worker process (the bench parent never
            # imports jax): the real ContinuousEngine, driven with and
            # without shared-prefix reuse over identical prompts.
            import time as _time

            import jax
            import jax.numpy as jnp
            import numpy as np

            from covalent_tpu_plugin.models import (
                TransformerConfig,
                TransformerLM,
            )
            from covalent_tpu_plugin.models.serve import ContinuousEngine

            cfg = TransformerConfig(
                vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                d_ff=64, max_seq=64, dtype=jnp.float32,
                attention="reference",
            )
            model = TransformerLM(cfg)
            params = model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
            )["params"]
            rng = np.random.default_rng(0)
            prefix = rng.integers(0, 64, prefix_len).astype(np.int32)
            prompts = [
                np.concatenate([
                    prefix,
                    rng.integers(0, 64, 2 + i % 3).astype(np.int32),
                ])
                for i in range(n_requests)
            ]

            def run(shared):
                engine = ContinuousEngine(
                    model, params, max_batch=2, sync_steps=4,
                    max_new_tokens=cap,
                    shared_prefix=prefix if shared else None,
                )
                streams = {}
                queue = [(f"r{i}", p) for i, p in enumerate(prompts)]
                done = set()
                t0 = _time.perf_counter()
                for _ in range(500):
                    while queue and engine.busy < engine.slots:
                        rid, p = queue.pop(0)
                        engine.admit(rid, p, {"max_new_tokens": cap})
                        streams[rid] = []
                    for event in engine.step():
                        streams[event["rid"]].extend(event["tokens"])
                        if event["done"]:
                            done.add(event["rid"])
                    if len(done) == len(prompts) and not queue:
                        break
                wall = _time.perf_counter() - t0
                stats = dict(engine.stats)
                engine.close()
                return streams, stats, wall

            reuse_streams, reuse_stats, reuse_wall = run(True)
            full_streams, full_stats, full_wall = run(False)
            return {
                "equal": reuse_streams == full_streams,
                "requests": n_requests,
                "prefix_hits": reuse_stats["prefix_hits"],
                "prefill_positions_reuse":
                    reuse_stats["prefill_positions"],
                "prefill_positions_full":
                    full_stats["prefill_positions"],
                "wall_reuse_s": round(reuse_wall, 4),
                "wall_full_s": round(full_wall, 4),
            }

        async def prefix_arm() -> dict:
            ex = scale_executor("prefix")
            try:
                return await ex.run(
                    prefix_probe, [12, 6, 6], {},
                    {"dispatch_id": "prefixprobe", "node_id": 0},
                )
            finally:
                await ex.close()

        async def scale_phase():
            one = await scale_arm(1)
            many = await scale_arm(SERVE_SCALE_REPLICAS)
            prefix = await prefix_arm()
            return one, many, prefix

        one_arm, many_arm, prefix_info = await asyncio.wait_for(
            scale_phase(), SERVE_SCALE_BUDGET_S
        )
        expected = [
            [i * 100 + j + 1 for j in range(SERVE_SCALE_TOKENS)]
            for i in range(SERVE_SCALE_REQUESTS)
        ]
        assert one_arm["results"] == expected, one_arm["results"]
        assert many_arm["results"] == expected, many_arm["results"]
        total_tokens = SERVE_SCALE_REQUESTS * SERVE_SCALE_TOKENS
        tps_one = total_tokens / max(one_arm["wall_s"], 1e-9)
        tps_many = total_tokens / max(many_arm["wall_s"], 1e-9)
        scale = tps_many / max(tps_one, 1e-9)
        p99_one = percentile(one_arm["latencies"], 0.99)
        p99_many = percentile(many_arm["latencies"], 0.99)
        decisions = sorted(one_arm["decisions"] + many_arm["decisions"])
        router_p50 = (
            decisions[len(decisions) // 2] if decisions else 0.0
        )
        assert prefix_info["equal"] is True, prefix_info
        prefix_reuse_ok = bool(
            prefix_info["prefill_positions_reuse"]
            < prefix_info["prefill_positions_full"]
        )
        summary["serve_scale_replicas"] = SERVE_SCALE_REPLICAS
        summary["serve_scale_tokens_per_s_1"] = round(tps_one, 1)
        summary["serve_scale_tokens_per_s_n"] = round(tps_many, 1)
        summary["serve_scale_speedup"] = round(scale, 2)
        summary["serve_scale_min"] = SERVE_SCALE_MIN
        summary["serve_scale_linear_ok"] = bool(scale >= SERVE_SCALE_MIN)
        summary["serve_scale_p99_1_s"] = round(p99_one, 4)
        summary["serve_scale_p99_n_s"] = round(p99_many, 4)
        summary["serve_scale_p99_ok"] = bool(p99_many <= p99_one)
        summary["serve_scale_router_p50_ms"] = round(router_p50 * 1e3, 4)
        summary["serve_scale_router_ok"] = bool(
            router_p50 < ROUTER_DECISION_BUDGET_S
        )
        summary["serve_prefix_reuse_ok"] = prefix_reuse_ok
        summary["serve_prefix_prefill_full"] = (
            prefix_info["prefill_positions_full"]
        )
        summary["serve_prefix_prefill_reuse"] = (
            prefix_info["prefill_positions_reuse"]
        )
        emit({
            "phase": "serve_scale",
            "replicas": SERVE_SCALE_REPLICAS,
            "requests": SERVE_SCALE_REQUESTS,
            "tokens_per_request": SERVE_SCALE_TOKENS,
            "step_s": SERVE_SCALE_STEP_S,
            "wall_1_s": round(one_arm["wall_s"], 3),
            "wall_n_s": round(many_arm["wall_s"], 3),
            "tokens_per_s_1": summary["serve_scale_tokens_per_s_1"],
            "tokens_per_s_n": summary["serve_scale_tokens_per_s_n"],
            "speedup": summary["serve_scale_speedup"],
            "speedup_min": SERVE_SCALE_MIN,
            "linear_ok": summary["serve_scale_linear_ok"],
            "p99_1_s": summary["serve_scale_p99_1_s"],
            "p99_n_s": summary["serve_scale_p99_n_s"],
            "p99_ok": summary["serve_scale_p99_ok"],
            "router_decision_p50_ms":
                summary["serve_scale_router_p50_ms"],
            "router_decision_budget_ms":
                round(ROUTER_DECISION_BUDGET_S * 1e3, 3),
            "router_ok": summary["serve_scale_router_ok"],
            "per_replica_served": many_arm["per_replica_served"],
            "latency_attribution": latency_attribution(
                many_arm["trace_ids"]
            ),
            "prefix_reuse": prefix_info,
            "prefix_reuse_ok": prefix_reuse_ok,
            "introspection": introspection_view([
                "covalent_tpu_serve_replicas",
                "covalent_tpu_serve_replica_in_flight",
                "covalent_tpu_serve_router_decision_seconds",
            ]),
            **spread_stats(many_arm["latencies"], "serve_scale_latency"),
        })
    except _PhaseSkipped:
        emit({"phase": "serve_scale", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "serve_scale", "error": repr(error)})

    # ---- phase 2b4: gray-failure defense (health + hedging) --------------
    # One replica of three is browned out (every engine step pays a
    # GRAY_SLOW_S chaos sleep — alive, heartbeating, just 50x slower:
    # the gray failure a crash-stop breaker never sees).  Three arms
    # under the SAME open-loop load: healthy baseline, brownout with the
    # defense OFF (pre-defense behavior: ~1/3 of requests eat the
    # brownout), and brownout with health scoring + tail hedging ON.
    # Asserted: hedged p99 recovers to within GRAY_HEDGED_MAX of
    # healthy, unhedged degrades >= GRAY_UNHEDGED_MIN, every stream
    # byte-equal across arms (the hedge's exactly-once splice), zero
    # shed, hedges actually fired, and health transitions are in the
    # archived metrics.
    try:
        if "gray_failure" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.fleet.health import HEALTH
        from covalent_tpu_plugin.serving import open_replica_set

        def make_gray_factory(step_s: float, slots: int = 4):
            def factory():
                import time as _time

                class Engine:
                    def __init__(self):
                        self.slots = slots
                        self.lanes = {}

                    def admit(self, rid, prompt, params):
                        seed = int(prompt[-1])
                        cap = int((params or {}).get(
                            "max_new_tokens", GRAY_TOKENS
                        ))
                        self.lanes[rid] = [
                            seed * 100 + j + 1 for j in range(cap)
                        ]

                    def step(self):
                        _time.sleep(step_s)
                        events = []
                        for rid in list(self.lanes):
                            chunk = self.lanes[rid][:4]
                            self.lanes[rid] = self.lanes[rid][4:]
                            done = not self.lanes[rid]
                            if done:
                                del self.lanes[rid]
                            events.append({
                                "rid": rid, "tokens": chunk, "done": done,
                            })
                        return events

                    def cancel(self, rid):
                        self.lanes.pop(rid, None)

                return Engine()

            return factory

        # The brownout rides the worker-side gray-chaos hook: the slow
        # replica's harness parses COVALENT_TPU_CHAOS from its process
        # env and pays a seeded slow-tail sleep per engine pump.
        # slow_s = slow_factor * max(jitter, 0.01).
        gray_chaos = (
            f"seed=11,jitter=0.02,p_slow=1.0,"
            f"slow_factor={GRAY_SLOW_S / 0.02:.0f}"
        )

        def gray_executor(tag: str, brownout: bool):
            env = {
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            }
            if brownout:
                env["COVALENT_TPU_CHAOS"] = gray_chaos
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_gray_{tag}",
                remote_cache=f"{workdir}/remote_gray_{tag}",
                python_path=sys.executable,
                poll_freq=0.2,
                use_agent="pool",
                pool_preload="cloudpickle",
                prewarm=False,
                heartbeat_interval=0.0,
                task_env=env,
            )

        async def gray_arm(tag: str, brownout: bool, defended: bool) -> dict:
            # Arm-scoped env: the defense toggles read os.environ at
            # ReplicaSet construction / per judge call.
            overrides = {
                "COVALENT_TPU_HEDGE": "on" if defended else "off",
                "COVALENT_TPU_HEALTH": "" if defended else "off",
                "COVALENT_TPU_HEDGE_BUDGET_PCT": "60",
                "COVALENT_TPU_HEDGE_PERCENTILE": "90",
            }
            saved = {k: os.environ.get(k) for k in overrides}
            saved_min_samples = HEALTH.min_samples
            HEALTH.reset()
            HEALTH.min_samples = 3
            for k, v in overrides.items():
                os.environ[k] = v
            executors = [
                gray_executor(f"{tag}_{i}", brownout and i == 2)
                for i in range(3)
            ]
            try:
                rset = await open_replica_set(
                    executors,
                    make_gray_factory(GRAY_STEP_S),
                    name=f"gray_{tag}",
                    stats_interval_s=0.2,
                )
                shed = 0

                async def offer(n: int, base: int) -> list:
                    nonlocal shed
                    out = []
                    for i in range(n):
                        try:
                            out.append(await rset.request(
                                [base + i],
                                params={"max_new_tokens": GRAY_TOKENS},
                                tenant=f"t{i % 2}",
                            ))
                        except Exception:  # noqa: BLE001 - shed counts
                            shed += 1
                        await asyncio.sleep(GRAY_ARRIVAL_S)
                    return out

                # Warm-up: trains the hedge TTFT ring and lets the
                # health monitor learn the brownout (lost hedges charge
                # the straggling primary); excluded from the measurement.
                warm = await offer(GRAY_WARMUP, 100)
                await asyncio.gather(
                    *(r.result(timeout=GRAY_BUDGET_S) for r in warm)
                )
                if brownout and defended:
                    # Measure the RECOVERED steady state, not the
                    # detection window: wait (bounded) until the health
                    # monitor has actually demoted the browned-out
                    # replica before offering the measured batch.
                    for _ in range(100):
                        states = {
                            HEALTH.state(sup.sid)
                            for sup in rset.supervisors.values()
                        }
                        if states & {"degraded", "quarantined"}:
                            break
                        await asyncio.sleep(0.1)
                measured = await offer(GRAY_REQUESTS, 200)
                results = await asyncio.gather(
                    *(r.result(timeout=GRAY_BUDGET_S) for r in measured)
                )
                latencies = [r.latency_s for r in measured]
                status = rset.status()
                await rset.close()
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                HEALTH.min_samples = saved_min_samples
                for ex in executors:
                    await ex.close()
            return {
                "results": list(results),
                "latencies": latencies,
                "p99_s": percentile(latencies, 0.99),
                "shed": shed,
                "hedge": status.get("hedge", {}),
                "health": {
                    rid: {
                        "score": view.get("health_score"),
                        "state": view.get("health_state"),
                    }
                    for rid, view in status["replicas"].items()
                },
            }

        async def gray_phase():
            healthy = await gray_arm("healthy", False, False)
            unhedged = await gray_arm("unhedged", True, False)
            hedged = await gray_arm("hedged", True, True)
            return healthy, unhedged, hedged

        healthy_arm, unhedged_arm, hedged_arm = await asyncio.wait_for(
            gray_phase(), GRAY_BUDGET_S
        )
        expected = [
            [(200 + i) * 100 + j + 1 for j in range(GRAY_TOKENS)]
            for i in range(GRAY_REQUESTS)
        ]
        byte_equal = (
            healthy_arm["results"] == expected
            and unhedged_arm["results"] == expected
            and hedged_arm["results"] == expected
        )
        total_shed = (
            healthy_arm["shed"] + unhedged_arm["shed"] + hedged_arm["shed"]
        )
        p99_floor = max(healthy_arm["p99_s"], GRAY_P99_FLOOR_S)
        hedge_recovered = bool(
            hedged_arm["p99_s"] <= GRAY_HEDGED_MAX * p99_floor
        )
        unhedged_degraded = bool(
            unhedged_arm["p99_s"] >= GRAY_UNHEDGED_MIN * p99_floor
        )
        hedges_issued = int(hedged_arm["hedge"].get("issued") or 0)
        summary["gray_failure_p99_healthy_s"] = round(
            healthy_arm["p99_s"], 4
        )
        summary["gray_failure_p99_unhedged_s"] = round(
            unhedged_arm["p99_s"], 4
        )
        summary["gray_failure_p99_hedged_s"] = round(hedged_arm["p99_s"], 4)
        summary["gray_failure_hedge_p99_recovered"] = hedge_recovered
        summary["gray_failure_unhedged_degraded"] = unhedged_degraded
        summary["gray_failure_streams_byte_equal"] = byte_equal
        summary["gray_failure_shed"] = total_shed
        summary["gray_failure_hedges_issued"] = hedges_issued
        summary["gray_failure_hedge_wins"] = int(
            hedged_arm["hedge"].get("wins") or 0
        )
        emit({
            "phase": "gray_failure",
            "requests": GRAY_REQUESTS,
            "warmup": GRAY_WARMUP,
            "slow_s": GRAY_SLOW_S,
            "p99_healthy_s": summary["gray_failure_p99_healthy_s"],
            "p99_unhedged_s": summary["gray_failure_p99_unhedged_s"],
            "p99_hedged_s": summary["gray_failure_p99_hedged_s"],
            "hedged_max": GRAY_HEDGED_MAX,
            "unhedged_min": GRAY_UNHEDGED_MIN,
            "hedge_p99_recovered": hedge_recovered,
            "unhedged_degraded": unhedged_degraded,
            "streams_byte_equal": byte_equal,
            "shed": total_shed,
            "hedge": hedged_arm["hedge"],
            "replica_health": hedged_arm["health"],
            "introspection": introspection_view([
                "covalent_tpu_health_score",
                "covalent_tpu_health_transitions_total",
                "covalent_tpu_serve_hedges_total",
            ]),
            **spread_stats(hedged_arm["latencies"], "gray_hedged_latency"),
        })
    except _PhaseSkipped:
        emit({"phase": "gray_failure", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "gray_failure", "error": repr(error)})

    # ---- phase 2b-ter: disaggregated prefill/decode serving --------------
    # The SAME open-loop mixed short/long-prompt traffic through the SAME
    # decode tier twice: fused (every replica prefills its own long
    # prompts inside its engine loop, stalling every stream it hosts) vs
    # disaggregated (a prefill tier runs prefill_only, ships the KV
    # bundle through the CAS/channel digest-verified, and decode replicas
    # admit_from_kv).  Asserted: byte-equal streams across arms (and vs
    # the deterministic single-engine expectation), decode tokens/s no
    # lower with the split (expected higher — that is the phase's point),
    # KV transfer bytes + p50 latency accounted in the artifact, and a
    # real-ContinuousEngine arm proving prefix-tree hits > 0 plus
    # bit-equal KV-disaggregated streams.
    try:
        if "serve_disagg" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.serving import (
            open_disaggregated_set,
            open_replica_set,
        )

        def make_disagg_factory():
            step_s = SERVE_DISAGG_STEP_S
            prefill_s = SERVE_DISAGG_PREFILL_S_PER_TOK

            def factory():
                import pickle as pickle_mod
                import time as _time

                class Engine:
                    def __init__(self):
                        self.slots = 2
                        self.lanes = {}
                        self.stats = {"prefill_positions": 0,
                                      "kv_exports": 0}

                    def _tokens(self, prompt, cap):
                        base = int(prompt[-1])
                        return [base + j + 1 for j in range(cap)]

                    def admit(self, rid, prompt, params):
                        cap = int((params or {}).get("max_new_tokens", 8))
                        _time.sleep(prefill_s * len(prompt))
                        self.stats["prefill_positions"] += len(prompt)
                        self.lanes[rid] = self._tokens(prompt, cap)

                    def prefill_only(self, prompt, params):
                        _time.sleep(prefill_s * len(prompt))
                        self.stats["prefill_positions"] += len(prompt)
                        self.stats["kv_exports"] += 1
                        return pickle_mod.dumps({
                            "prompt": [int(t) for t in prompt],
                        })

                    def admit_from_kv(self, rid, data, params):
                        bundle = pickle_mod.loads(bytes(data))
                        cap = int((params or {}).get("max_new_tokens", 8))
                        self.lanes[rid] = self._tokens(
                            bundle["prompt"], cap
                        )

                    def step(self):
                        _time.sleep(step_s)
                        events = []
                        for rid in list(self.lanes):
                            chunk = self.lanes[rid][:2]
                            self.lanes[rid] = self.lanes[rid][2:]
                            done = not self.lanes[rid]
                            if done:
                                del self.lanes[rid]
                            events.append({
                                "rid": rid, "tokens": chunk, "done": done,
                            })
                        return events

                    def cancel(self, rid):
                        self.lanes.pop(rid, None)

                return Engine()

            return factory

        def disagg_executor(tag: str):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_disagg_{tag}",
                remote_cache=f"{workdir}/remote_disagg_{tag}",
                python_path=sys.executable,
                poll_freq=0.2,
                use_agent="pool",
                pool_preload="cloudpickle",
                prewarm=False,
                heartbeat_interval=0.0,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        def disagg_prompts():
            prompts = []
            for i in range(SERVE_DISAGG_REQUESTS):
                if i % 4 == 0:  # every fourth request is a long prompt
                    prompts.append(
                        list(range(SERVE_DISAGG_LONG_PROMPT - 1))
                        + [1000 + i]
                    )
                else:
                    prompts.append([7, 1000 + i])
            return prompts

        async def disagg_arm(disaggregate: bool) -> dict:
            tags = [
                f"{'d' if disaggregate else 'f'}dec{i}"
                for i in range(SERVE_DISAGG_DECODE)
            ]
            executors = [disagg_executor(tag) for tag in tags]
            prefill_ex = None
            try:
                if disaggregate:
                    prefill_ex = disagg_executor("pre")
                    sset = await open_disaggregated_set(
                        [prefill_ex] + executors,
                        make_disagg_factory(),
                        decode_replicas=SERVE_DISAGG_DECODE,
                        prefill_replicas=1,
                        min_prompt_tokens=8,
                        name="disagg",
                        stats_interval_s=0.2,
                    )
                else:
                    sset = await open_replica_set(
                        executors,
                        make_disagg_factory(),
                        name="fused",
                        stats_interval_s=0.2,
                    )
                prompts = disagg_prompts()
                t0 = time.perf_counter()
                tasks = []
                for prompt in prompts:
                    tasks.append(asyncio.ensure_future(sset.request(
                        prompt,
                        params={"max_new_tokens": SERVE_DISAGG_TOKENS},
                    )))
                    await asyncio.sleep(SERVE_DISAGG_ARRIVAL_S)
                requests = await asyncio.gather(*tasks)
                results = await asyncio.gather(
                    *(
                        r.result(timeout=SERVE_DISAGG_BUDGET_S)
                        for r in requests
                    )
                )
                wall = time.perf_counter() - t0
                latencies = [r.latency_s for r in requests]
                trace_ids = [r.span.trace_id for r in requests]
                status = sset.status()
                await sset.close()
            finally:
                for ex in executors:
                    await ex.close()
                if prefill_ex is not None:
                    await prefill_ex.close()
            return {
                "wall_s": wall,
                "results": list(results),
                "latencies": latencies,
                "trace_ids": trace_ids,
                "status": status,
            }

        def kv_probe(prefix_len, n_requests, cap):
            # Runs INSIDE a worker process (the bench parent never
            # imports jax): the REAL ContinuousEngine split into a
            # prefill engine and a decode engine over serialized KV
            # bundles, driven with repeated-prefix prompts so the
            # prefix tree gets exercised on the prefill tier.
            import jax
            import jax.numpy as jnp
            import numpy as np

            from covalent_tpu_plugin.models import (
                TransformerConfig,
                TransformerLM,
            )
            from covalent_tpu_plugin.models.serve import ContinuousEngine

            cfg = TransformerConfig(
                vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                d_ff=64, max_seq=64, dtype=jnp.float32,
                attention="reference",
            )
            model = TransformerLM(cfg)
            params = model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
            )["params"]
            rng = np.random.default_rng(0)
            prefix = rng.integers(0, 64, prefix_len).astype(np.int32)
            prompts = [
                np.concatenate([
                    prefix,
                    rng.integers(0, 64, 2 + i % 3).astype(np.int32),
                ])
                for i in range(n_requests)
            ]

            def drive(engine, admitter):
                streams = {}
                done = set()
                queue = list(enumerate(prompts))
                for _ in range(500):
                    while queue and engine.busy < engine.slots:
                        i, p = queue.pop(0)
                        admitter(f"r{i}", p)
                        streams[f"r{i}"] = []
                    for event in engine.step():
                        streams[event["rid"]].extend(event["tokens"])
                        if event["done"]:
                            done.add(event["rid"])
                    if len(done) == len(prompts) and not queue:
                        break
                return streams

            joint = ContinuousEngine(
                model, params, max_batch=2, sync_steps=4,
                max_new_tokens=cap,
            )
            joint_streams = drive(
                joint,
                lambda rid, p: joint.admit(
                    rid, p, {"max_new_tokens": cap}
                ),
            )
            joint.close()
            prefill_engine = ContinuousEngine(
                model, params, max_batch=2, sync_steps=4,
                max_new_tokens=cap,
            )
            decode_engine = ContinuousEngine(
                model, params, max_batch=2, sync_steps=4,
                max_new_tokens=cap,
            )
            bundles = {
                f"r{i}": prefill_engine.prefill_only(
                    p, {"max_new_tokens": cap}
                )
                for i, p in enumerate(prompts)
            }
            kv_bytes = sum(len(b) for b in bundles.values())
            disagg_streams = drive(
                decode_engine,
                lambda rid, p: decode_engine.admit_from_kv(
                    rid, bundles[rid], {"max_new_tokens": cap}
                ),
            )
            out = {
                "equal": disagg_streams == joint_streams,
                "requests": n_requests,
                "prefix_hits": prefill_engine.stats["prefix_hits"],
                "kv_exports": prefill_engine.stats["kv_exports"],
                "kv_admits": decode_engine.stats["kv_admits"],
                "decode_prefill_positions":
                    decode_engine.stats["prefill_positions"],
                "kv_bundle_bytes": kv_bytes,
            }
            prefill_engine.close()
            decode_engine.close()
            return out

        async def kv_probe_arm() -> dict:
            ex = disagg_executor("probe")
            try:
                return await ex.run(
                    kv_probe, [10, 6, 6], {},
                    {"dispatch_id": "kvprobe", "node_id": 0},
                )
            finally:
                await ex.close()

        async def disagg_phase():
            fused = await disagg_arm(False)
            split = await disagg_arm(True)
            probe = await kv_probe_arm()
            return fused, split, probe

        fused_arm, split_arm, probe_info = await asyncio.wait_for(
            disagg_phase(), SERVE_DISAGG_BUDGET_S * 3
        )
        expected = [
            [p[-1] + j + 1 for j in range(SERVE_DISAGG_TOKENS)]
            for p in disagg_prompts()
        ]
        streams_identical = (
            fused_arm["results"] == expected
            and split_arm["results"] == expected
        )
        assert streams_identical, (fused_arm["results"],
                                   split_arm["results"])
        total_tokens = SERVE_DISAGG_REQUESTS * SERVE_DISAGG_TOKENS
        tps_fused = total_tokens / max(fused_arm["wall_s"], 1e-9)
        tps_split = total_tokens / max(split_arm["wall_s"], 1e-9)
        split_status = split_arm["status"]
        n_long = len([
            p for p in disagg_prompts() if len(p) >= 8
        ])
        assert split_status["requests_by_path"].get("disagg") == n_long, (
            split_status["requests_by_path"]
        )
        kv_accounted = bool(
            split_status["kv_bytes_total"] > 0
            and split_status["kv_transfer_p50_ms"] > 0
        )
        assert probe_info["equal"] is True, probe_info
        assert probe_info["decode_prefill_positions"] == 0, probe_info
        prefix_hit_ok = probe_info["prefix_hits"] > 0
        summary["serve_disagg_tokens_per_s_fused"] = round(tps_fused, 1)
        summary["serve_disagg_tokens_per_s"] = round(tps_split, 1)
        summary["serve_disagg_speedup"] = round(
            tps_split / max(tps_fused, 1e-9), 3
        )
        summary["disagg_no_slower"] = bool(
            tps_split >= tps_fused * 0.98
        )
        summary["disagg_beats_fused"] = bool(tps_split > tps_fused)
        summary["disagg_streams_identical"] = streams_identical
        summary["kv_transfer_accounted"] = kv_accounted
        summary["serve_disagg_kv_bytes"] = split_status["kv_bytes_total"]
        summary["serve_disagg_kv_p50_ms"] = (
            split_status["kv_transfer_p50_ms"]
        )
        summary["serve_disagg_prefix_hits"] = probe_info["prefix_hits"]
        summary["serve_disagg_prefix_hit_ok"] = prefix_hit_ok
        # Trace completeness verdicts ride the final combined line: the
        # disagg arm is the acceptance target (dispatcher -> prefill
        # worker -> decode worker under ONE trace), so its long-prompt
        # requests must yield at least one full four-segment waterfall
        # with zero orphan spans.
        attribution = latency_attribution(split_arm["trace_ids"])
        summary["trace_traces_found"] = attribution["traces_found"]
        summary["trace_traces_complete"] = attribution["traces_complete"]
        summary["trace_full_waterfalls"] = attribution[
            "traces_full_waterfall"
        ]
        summary["trace_orphan_spans"] = attribution["orphan_spans"]
        summary["trace_coverage_min"] = attribution.get("coverage_min")
        summary["trace_completeness_ok"] = bool(
            attribution["traces_complete"] >= 1
            and attribution["traces_full_waterfall"] >= 1
            and attribution["orphan_spans"] == 0
            and "error" not in attribution
        )
        emit({
            "phase": "serve_disagg",
            "requests": SERVE_DISAGG_REQUESTS,
            "long_prompt_tokens": SERVE_DISAGG_LONG_PROMPT,
            "decode_replicas": SERVE_DISAGG_DECODE,
            "wall_fused_s": round(fused_arm["wall_s"], 3),
            "wall_disagg_s": round(split_arm["wall_s"], 3),
            "tokens_per_s_fused": summary["serve_disagg_tokens_per_s_fused"],
            "tokens_per_s_disagg": summary["serve_disagg_tokens_per_s"],
            "speedup": summary["serve_disagg_speedup"],
            "no_slower": summary["disagg_no_slower"],
            "beats_fused": summary["disagg_beats_fused"],
            "streams_identical": streams_identical,
            "requests_by_path": split_status["requests_by_path"],
            "kv_bytes_total": split_status["kv_bytes_total"],
            "kv_transfer_p50_ms": split_status["kv_transfer_p50_ms"],
            "kv_transfer_accounted": kv_accounted,
            "kv_probe": probe_info,
            "latency_attribution": attribution,
            "p95_fused_s": round(
                percentile(fused_arm["latencies"], 0.95), 4
            ),
            "p95_disagg_s": round(
                percentile(split_arm["latencies"], 0.95), 4
            ),
            "introspection": introspection_view([
                "covalent_tpu_serve_kv_transfers_total",
                "covalent_tpu_serve_kv_transfer_seconds",
                "covalent_tpu_serve_disagg_requests_total",
            ]),
            **spread_stats(split_arm["latencies"], "serve_disagg_latency"),
        })
    except _PhaseSkipped:
        emit({"phase": "serve_disagg", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "serve_disagg", "error": repr(error)})

    # ---- phase 2b'': speculative + quantized decoding in the engine ------
    # Open-loop greedy load through three REAL ContinuousEngine arms in one
    # worker: fp, fp+draft (speculative), and a kv_quant lane group reached
    # through the per-request ``quality`` knob.  Asserted: the spec arm's
    # streams are byte-equal to fp's (greedy/exact contract) and its
    # aggregate tokens/s beats fp by >= SERVE_SPEC_SPEEDUP_MIN; accept
    # rate, per-mode token counters, and prefix-tree composition ride the
    # artifact.  These numbers fill the final JSON's spec_* fields when
    # the TPU lm_spec subphase did not run (tunnel outage) — the fields
    # have been null since r03.
    try:
        if "serve_spec" not in BENCH_PHASES:
            raise _PhaseSkipped

        def spec_probe(n_requests, cap, draft_len, n_layers):
            # Runs INSIDE a worker process (the bench parent never
            # imports jax).
            import dataclasses as dc
            import time as _time

            import jax
            import jax.numpy as jnp
            import numpy as np

            from covalent_tpu_plugin.models import (
                TransformerConfig,
                TransformerLM,
            )
            from covalent_tpu_plugin.models.serve import ContinuousEngine
            from covalent_tpu_plugin.parallel.sharding import unbox

            cfg = TransformerConfig(
                vocab_size=64, d_model=128, n_layers=n_layers, n_heads=4,
                d_ff=512, max_seq=96, dtype=jnp.float32,
                attention="reference",
            )
            model = TransformerLM(cfg)
            params = unbox(model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
            )["params"])
            # Zero the upper layers' residual contributions (attention
            # out-proj + MLP down-proj): the residual stream after the
            # full stack equals the stream after layer 0, so a 1-layer
            # draft sharing layer 0 + embed/unembed/final-norm predicts
            # the target's greedy argmax exactly.  Accept rate is 1.0 by
            # construction, making the measured speedup the pure
            # verify-slab amortization rather than model luck — while the
            # draft still genuinely costs 1/n_layers of a target step.
            layers = params["layers"]
            o_kernel = layers["attention"]["out_proj"]["kernel"]
            w_kernel = layers["mlp"]["wo"]["kernel"]
            layers = {
                **layers,
                "attention": {
                    **layers["attention"],
                    "out_proj": {"kernel": o_kernel.at[1:].set(0.0)},
                },
                "mlp": {
                    **layers["mlp"],
                    "wo": {
                        **layers["mlp"]["wo"],
                        "kernel": w_kernel.at[1:].set(0.0),
                    },
                },
            }
            params = {**params, "layers": layers}
            draft = TransformerLM(dc.replace(cfg, n_layers=1))
            dparams = {
                **params,
                "layers": jax.tree_util.tree_map(
                    lambda leaf: leaf[:1], params["layers"]
                ),
            }
            rng = np.random.default_rng(0)
            prompts = [
                rng.integers(1, 64, 4 + i % 4).astype(np.int32)
                for i in range(n_requests)
            ]

            def drive(engine, quality=None):
                base = {"max_new_tokens": cap}
                if quality is not None:
                    base["quality"] = quality
                streams, done = {}, set()
                queue = list(enumerate(prompts))
                for _ in range(10000):
                    while queue and engine.busy < engine.slots:
                        i, p = queue.pop(0)
                        engine.admit(f"r{i}", p, dict(base))
                        streams[f"r{i}"] = []
                    for event in engine.step():
                        streams[event["rid"]].extend(event["tokens"])
                        if event["done"]:
                            done.add(event["rid"])
                    if len(done) == len(prompts) and not queue:
                        break
                return streams

            def arm(quality=None, **kw):
                engine = ContinuousEngine(
                    model, params, max_batch=4,
                    sync_steps=2 * (draft_len + 1), max_new_tokens=cap,
                    length=cfg.max_seq - draft_len - 2, **kw,
                )
                # TWO warmup drives before timing: the first compiles the
                # cold-tree admission waves + the decode loop; the second
                # compiles the warm-prefix-tree SUFFIX admission waves
                # (the timed pass re-admits the same prompts into a tree
                # the warmups left warm, a different wave shape).  A
                # single warmup leaves a multi-second recompile inside
                # the timed window.
                drive(engine, quality)
                repeat = drive(engine, quality)
                seen = dict(engine.stats)
                t0 = _time.perf_counter()
                streams = drive(engine, quality)
                wall = _time.perf_counter() - t0
                stats = dict(engine.stats)
                refusal = getattr(engine, "_spec_refusal", None)
                engine.close()
                proposed = (
                    stats.get("spec_proposed", 0)
                    - seen.get("spec_proposed", 0)
                )
                accepted = (
                    stats.get("spec_accepted", 0)
                    - seen.get("spec_accepted", 0)
                )
                return {
                    "streams": {
                        rid: [int(t) for t in toks]
                        for rid, toks in streams.items()
                    },
                    "deterministic": streams == repeat,
                    "tokens": sum(len(s) for s in streams.values()),
                    "wall_s": wall,
                    "accept_rate": (
                        round(accepted / proposed, 4) if proposed else None
                    ),
                    "prefix_hits": int(stats.get("prefix_hits", 0)),
                    "mode_tokens": {
                        key[len("mode_tokens_"):]: int(v)
                        for key, v in stats.items()
                        if key.startswith("mode_tokens_")
                    },
                    "spec_refusal": refusal,
                    "mode_refusals": int(stats.get("mode_refusals", 0)),
                }

            fp = arm()
            spec = arm(
                draft_model=draft, draft_params=dparams,
                draft_len=draft_len,
            )
            quant = arm(
                quality="kv_quant", decode_modes=("fp", "kv_quant"),
                draft_model=draft, draft_params=dparams,
                draft_len=draft_len,
            )
            return {
                "fp": fp, "spec": spec, "spec_quant": quant,
                "exact": fp["streams"] == spec["streams"],
            }

        spec_ex = TPUExecutor(
            transport="local",
            cache_dir=f"{workdir}/cache_spec",
            remote_cache=f"{workdir}/remote_spec",
            python_path=sys.executable,
            poll_freq=0.2,
            use_agent="pool",
            pool_preload="cloudpickle",
            prewarm=False,
            heartbeat_interval=0.0,
            task_env={
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            },
        )
        try:
            probe = await asyncio.wait_for(
                spec_ex.run(
                    spec_probe,
                    [SERVE_SPEC_REQUESTS, SERVE_SPEC_TOKENS,
                     SERVE_SPEC_DRAFT_LEN, SERVE_SPEC_LAYERS], {},
                    {"dispatch_id": "specprobe", "node_id": 0},
                ),
                SERVE_SPEC_BUDGET_S,
            )
        finally:
            await spec_ex.close()
        assert probe["spec"]["spec_refusal"] is None, (
            probe["spec"]["spec_refusal"]
        )
        assert probe["exact"] is True, "spec arm diverged from fp arm"
        tps_fp = probe["fp"]["tokens"] / max(probe["fp"]["wall_s"], 1e-9)
        tps_spec = (
            probe["spec"]["tokens"] / max(probe["spec"]["wall_s"], 1e-9)
        )
        tps_quant = (
            probe["spec_quant"]["tokens"]
            / max(probe["spec_quant"]["wall_s"], 1e-9)
        )
        speedup = tps_spec / max(tps_fp, 1e-9)
        summary["serve_spec_tokens_per_s_fp"] = round(tps_fp, 1)
        summary["serve_spec_tokens_per_s"] = round(tps_spec, 1)
        summary["serve_spec_quant_tokens_per_s"] = round(tps_quant, 1)
        summary["serve_spec_speedup"] = round(speedup, 3)
        summary["serve_spec_speedup_ok"] = bool(
            speedup >= SERVE_SPEC_SPEEDUP_MIN
        )
        summary["serve_spec_exact"] = bool(probe["exact"])
        summary["serve_spec_accept_rate"] = probe["spec"]["accept_rate"]
        summary["serve_spec_quant_accept_rate"] = (
            probe["spec_quant"]["accept_rate"]
        )
        summary["serve_spec_quant_speedup"] = round(
            tps_quant / max(tps_fp, 1e-9), 3
        )
        # The kv_quant lane is not bit-equal to fp by design (quantized
        # KV numerics); its exactness contract is determinism — repeat
        # greedy drives produce identical streams.
        summary["serve_spec_quant_deterministic"] = bool(
            probe["spec_quant"]["deterministic"]
        )
        summary["serve_spec_prefix_hits"] = probe["spec"]["prefix_hits"]
        emit({
            "phase": "serve_spec",
            "requests": SERVE_SPEC_REQUESTS,
            "tokens_per_request": SERVE_SPEC_TOKENS,
            "draft_len": SERVE_SPEC_DRAFT_LEN,
            "target_layers": SERVE_SPEC_LAYERS,
            "tokens_per_s_fp": summary["serve_spec_tokens_per_s_fp"],
            "tokens_per_s_spec": summary["serve_spec_tokens_per_s"],
            "tokens_per_s_spec_quant":
                summary["serve_spec_quant_tokens_per_s"],
            "speedup": summary["serve_spec_speedup"],
            "speedup_quant": summary["serve_spec_quant_speedup"],
            "speedup_min": SERVE_SPEC_SPEEDUP_MIN,
            "speedup_ok": summary["serve_spec_speedup_ok"],
            "exact": summary["serve_spec_exact"],
            "accept_rate": summary["serve_spec_accept_rate"],
            "accept_rate_quant": summary["serve_spec_quant_accept_rate"],
            "quant_deterministic":
                summary["serve_spec_quant_deterministic"],
            "prefix_hits": summary["serve_spec_prefix_hits"],
            "mode_tokens": probe["spec_quant"]["mode_tokens"],
            "mode_refusals": probe["spec_quant"]["mode_refusals"],
            "wall_fp_s": round(probe["fp"]["wall_s"], 3),
            "wall_spec_s": round(probe["spec"]["wall_s"], 3),
            "wall_spec_quant_s": round(
                probe["spec_quant"]["wall_s"], 3
            ),
        })
    except _PhaseSkipped:
        emit({"phase": "serve_spec", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "serve_spec", "error": repr(error)})

    # ---- phase 2b-iv: multi-adapter LoRA multiplexing inside the engine --
    # One REAL ContinuousEngine hosting an adapter bank serves a mixed
    # round-robin load over MULTILORA_ADAPTERS distinct LoRA adapters in
    # co-batched decode waves, against per-adapter single-tenant engines
    # time-sharing the same device.  Asserted: streams byte-equal across
    # arms per request, the multiplexed aggregate tokens/s beats the
    # single-tenant aggregate by >= MULTILORA_SPEEDUP_MIN, and a hot
    # swap mid-stream drops nothing (the in-flight lane finishes on the
    # old generation byte-equal; the next admission decodes the new).
    try:
        if "serve_multilora" not in BENCH_PHASES:
            raise _PhaseSkipped

        def multilora_probe(n_adapters, n_requests, cap, rank, n_layers):
            # Runs INSIDE a worker process (the bench parent never
            # imports jax).
            import time as _time

            import jax
            import numpy as np
            import jax.numpy as jnp

            from covalent_tpu_plugin.models import (
                TransformerConfig,
                TransformerLM,
            )
            from covalent_tpu_plugin.models import lora as lora_mod
            from covalent_tpu_plugin.models.serve import ContinuousEngine
            from covalent_tpu_plugin.parallel.sharding import unbox

            cfg = TransformerConfig(
                vocab_size=64, d_model=64, n_layers=n_layers, n_heads=4,
                d_ff=256, max_seq=96, dtype=jnp.float32,
                attention="reference", scan_layers=False,
            )
            model = TransformerLM(cfg)
            params = unbox(model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
            )["params"])

            def make_adapter(seed):
                # A "fine-tuned" adapter: randomized nonzero lora_a AND
                # lora_b (add_lora's fresh B is zero — the identity),
                # so every adapter genuinely changes the argmax.
                lmodel, filled = lora_mod.add_lora(
                    model, params, rank=rank, alpha=16.0
                )
                mask = jax.tree_util.tree_leaves(
                    lora_mod.lora_mask(filled)
                )
                leaves, treedef = jax.tree_util.tree_flatten(filled)
                key = jax.random.PRNGKey(seed)
                out = []
                for leaf, m in zip(leaves, mask):
                    if m:
                        key, sub = jax.random.split(key)
                        out.append(
                            jax.random.normal(
                                sub, leaf.shape, leaf.dtype
                            ) * 0.05
                        )
                    else:
                        out.append(leaf)
                tuned = jax.tree_util.tree_unflatten(treedef, out)
                return lmodel, tuned

            lmodel = None
            tuned, banks = [], {}
            for i in range(n_adapters):
                lmodel, tree = make_adapter(i + 1)
                tuned.append(tree)
                banks[f"a{i}"] = lora_mod.adapter_leaves(tree)
            rng = np.random.default_rng(0)
            requests = [
                (
                    f"a{i % n_adapters}",
                    rng.integers(1, 64, 4 + i % 4).astype(np.int32),
                )
                for i in range(n_requests)
            ]
            slots = max(4, n_adapters * 2)

            def drive(engine, subset):
                streams, done = {}, set()
                queue = [
                    (f"r{i}", name, prompt)
                    for i, (name, prompt) in enumerate(requests)
                    if subset is None or name == subset
                ]
                pending = list(queue)
                for _ in range(10000):
                    while pending and engine.busy < engine.slots:
                        rid, name, prompt = pending.pop(0)
                        prm = {"max_new_tokens": cap}
                        if subset is None:
                            prm["adapter"] = name
                        engine.admit(rid, prompt, prm)
                        streams[rid] = []
                    for event in engine.step():
                        streams[event["rid"]].extend(event["tokens"])
                        if event["done"]:
                            done.add(event["rid"])
                    if len(done) == len(queue) and not pending:
                        break
                return streams

            def timed(engine, subset=None):
                drive(engine, subset)   # cold compiles
                drive(engine, subset)   # warm prefix-tree wave shapes
                # Best-of-3: the min wall is the least-noise estimate on
                # a shared CPU box (scheduler jitter only ever adds).
                streams, best = None, float("inf")
                for _ in range(3):
                    t0 = _time.perf_counter()
                    streams = drive(engine, subset)
                    best = min(best, _time.perf_counter() - t0)
                return streams, best

            # Arm 1: ONE multiplexed engine, all adapters co-batched.
            mux = ContinuousEngine(
                model, params, max_batch=slots, sync_steps=4,
                max_new_tokens=cap, length=cfg.max_seq - 4,
                adapters=banks,
            )
            mux_streams, mux_wall = timed(mux)

            # Arm 2: per-adapter single-tenant engines PARTITIONING the
            # same slot budget (slots/N lanes each — dedicating a
            # session per tenant statically splits the device's batch
            # capacity, which is exactly the cost the bank removes),
            # each timed on its own quarter of the load; the device
            # time-shares them, so the aggregate wall is the sum.
            single_streams, single_wall = {}, 0.0
            for i in range(n_adapters):
                engine = ContinuousEngine(
                    lmodel, tuned[i],
                    max_batch=max(1, slots // n_adapters), sync_steps=4,
                    max_new_tokens=cap, length=cfg.max_seq - 4,
                )
                streams, wall = timed(engine, subset=f"a{i}")
                single_streams.update(streams)
                single_wall += wall
                engine.close()
            exact = all(
                [int(t) for t in mux_streams[rid]]
                == [int(t) for t in single_streams[rid]]
                for rid in single_streams
            )

            # Hot swap mid-stream: admit on a0, swap a0's generation
            # while the lane is mid-decode, admit again.  The in-flight
            # stream finishes on the OLD weights; the new admission
            # decodes the new — zero drops either side.
            _, fresh = make_adapter(97)
            old_oracle = mux_streams["r0"]
            swap_prompt = requests[0][1]
            mux.admit("swap_old", swap_prompt,
                      {"max_new_tokens": cap, "adapter": "a0"})
            swapped = {"swap_old": [], "swap_new": []}
            for _ in range(2):      # a couple of waves in flight first
                for event in mux.step():
                    swapped[event["rid"]].extend(event["tokens"])
            mux.attach_adapter("a0", lora_mod.adapter_leaves(fresh))
            mux.admit("swap_new", swap_prompt,
                      {"max_new_tokens": cap, "adapter": "a0"})
            for _ in range(10000):
                for event in mux.step():
                    swapped[event["rid"]].extend(event["tokens"])
                if not mux.busy:
                    break
            new_engine = ContinuousEngine(
                lmodel, fresh, max_batch=slots, sync_steps=4,
                max_new_tokens=cap, length=cfg.max_seq - 4,
            )
            new_engine.admit("swap_new", swap_prompt,
                             {"max_new_tokens": cap})
            new_oracle = []
            for _ in range(10000):
                for event in new_engine.step():
                    new_oracle.extend(event["tokens"])
                if not new_engine.busy:
                    break
            new_engine.close()
            stats = dict(mux.stats)
            mux.close()
            total = sum(len(s) for s in mux_streams.values())
            return {
                "tokens": total,
                "mux_wall_s": mux_wall,
                "single_wall_s": single_wall,
                "exact": bool(exact),
                "swap_old_exact": swapped["swap_old"] == old_oracle,
                "swap_new_exact": swapped["swap_new"] == new_oracle,
                "swap_complete": (
                    len(swapped["swap_old"]) == cap
                    and len(swapped["swap_new"]) == cap
                ),
                "adapter_tokens": {
                    key[len("adapter_tokens_"):]: int(v)
                    for key, v in stats.items()
                    if key.startswith("adapter_tokens_")
                },
                "swaps": int(stats.get("adapter_swaps", 0)),
                "attaches": int(stats.get("adapter_attaches", 0)),
                "prefix_blocked": int(
                    stats.get("adapter_prefix_blocked", 0)
                ),
            }

        multilora_ex = TPUExecutor(
            transport="local",
            cache_dir=f"{workdir}/cache_multilora",
            remote_cache=f"{workdir}/remote_multilora",
            python_path=sys.executable,
            poll_freq=0.2,
            use_agent="pool",
            pool_preload="cloudpickle",
            prewarm=False,
            heartbeat_interval=0.0,
            task_env={
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            },
        )
        try:
            probe = await asyncio.wait_for(
                multilora_ex.run(
                    multilora_probe,
                    [MULTILORA_ADAPTERS, MULTILORA_REQUESTS,
                     MULTILORA_TOKENS, MULTILORA_RANK,
                     MULTILORA_LAYERS], {},
                    {"dispatch_id": "multiloraprobe", "node_id": 0},
                ),
                MULTILORA_BUDGET_S,
            )
        finally:
            await multilora_ex.close()
        assert probe["exact"] is True, (
            "multiplexed streams diverged from single-adapter oracles"
        )
        tps_mux = probe["tokens"] / max(probe["mux_wall_s"], 1e-9)
        tps_single = probe["tokens"] / max(probe["single_wall_s"], 1e-9)
        speedup = tps_mux / max(tps_single, 1e-9)
        # "Zero drops" at engine level IS stream completion: both the
        # in-flight lane (old generation) and the post-swap admission
        # ran to their full caps, byte-equal to their oracles — nothing
        # was cancelled, truncated, or re-decoded on the wrong weights.
        zero_drops = bool(
            probe["swap_old_exact"] and probe["swap_new_exact"]
            and probe["swap_complete"]
        )
        summary["serve_multilora_tokens_per_s"] = round(tps_mux, 1)
        summary["serve_multilora_tokens_per_s_single"] = round(
            tps_single, 1
        )
        summary["serve_multilora_speedup"] = round(speedup, 3)
        summary["serve_multilora_speedup_ok"] = bool(
            speedup >= MULTILORA_SPEEDUP_MIN
        )
        summary["serve_multilora_exact"] = bool(probe["exact"])
        summary["serve_multilora_swap_zero_drops"] = zero_drops
        emit({
            "phase": "serve_multilora",
            "adapters": MULTILORA_ADAPTERS,
            "requests": MULTILORA_REQUESTS,
            "tokens_per_request": MULTILORA_TOKENS,
            "rank": MULTILORA_RANK,
            "tokens_per_s_mux": summary["serve_multilora_tokens_per_s"],
            "tokens_per_s_single":
                summary["serve_multilora_tokens_per_s_single"],
            "speedup": summary["serve_multilora_speedup"],
            "speedup_min": MULTILORA_SPEEDUP_MIN,
            "speedup_ok": summary["serve_multilora_speedup_ok"],
            "exact": summary["serve_multilora_exact"],
            "swap_zero_drops": zero_drops,
            "swap_old_exact": probe["swap_old_exact"],
            "swap_new_exact": probe["swap_new_exact"],
            "hot_swaps": probe["swaps"],
            "attaches": probe["attaches"],
            "adapter_tokens": probe["adapter_tokens"],
            "prefix_blocked": probe["prefix_blocked"],
            "wall_mux_s": round(probe["mux_wall_s"], 3),
            "wall_single_s": round(probe["single_wall_s"], 3),
        })
    except _PhaseSkipped:
        emit({"phase": "serve_multilora", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "serve_multilora", "error": repr(error)})

    # ---- phase 2c: recovery overhead under one injected channel death ----
    # A 4-electron fan-out through a ChaosTransport that kills exactly ONE
    # control-plane channel mid-poll, with 2 gang retries budgeted: the
    # resilience layer must complete every electron with zero local
    # fallbacks, and the wall-clock delta vs the clean fanout8 phase IS the
    # measured recovery overhead (teardown + redial + CAS re-stage +
    # relaunch + backoff).
    try:
        if "chaos_fanout" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.transport import ChaosPlan

        def resilience_counters() -> dict:
            return {
                key: value
                for key, value in metrics_totals().items()
                if key.startswith(("covalent_tpu_task_retries_total",
                                   "covalent_tpu_chaos_faults_total"))
            }

        def chaos_executor(plan):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_chaos",
                remote_cache=f"{workdir}/remote_chaos",
                python_path=sys.executable,
                poll_freq=0.2,
                pool_preload="cloudpickle",
                use_agent=False,  # poll path: where the drop_match bites
                max_task_retries=2,
                retry_base_delay=0.05,
                retry_max_delay=0.2,
                chaos=plan,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        async def fanout4(ex, dispatch_id):
            t0 = time.perf_counter()
            results = await asyncio.gather(
                *(
                    ex.run(
                        trivial_electron, [i], {},
                        {"dispatch_id": dispatch_id, "node_id": i},
                    )
                    for i in range(4)
                )
            )
            return time.perf_counter() - t0, results

        async def chaos_phase():
            # Clean baseline FIRST, same shape and config (4 concurrent
            # electrons do NOT cost half an 8-fan-out's wall — dispatch is
            # parallel — so the overhead must be measured against an
            # actual clean 4-fan-out, not a scaled fanout8 number).
            clean_ex = chaos_executor(None)
            try:
                await fanout4(clean_ex, "chaoswarm")  # warm pool/CAS
                clean_wall, _ = await fanout4(clean_ex, "chaosclean")
            finally:
                await clean_ex.close()
            plan = ChaosPlan(drop_match="if test -f", max_faults=1)
            chaos_ex = chaos_executor(plan)
            try:
                wall, results = await fanout4(chaos_ex, "chaosfan")
            finally:
                await chaos_ex.close()
            return clean_wall, wall, results, plan.faults_injected

        counters_before = resilience_counters()
        clean_wall, chaos_wall, results, faults = await asyncio.wait_for(
            chaos_phase(), FANOUT_BUDGET_S
        )
        assert results == [trivial_electron(i) for i in range(4)], results
        counters_delta = {
            key: round(value - counters_before.get(key, 0.0), 1)
            for key, value in resilience_counters().items()
            if value != counters_before.get(key, 0.0)
        }
        summary["chaos_fanout4_wall_s"] = round(chaos_wall, 3)
        summary["chaos_fanout4_clean_wall_s"] = round(clean_wall, 3)
        summary["chaos_fanout_faults_injected"] = faults
        summary["chaos_fanout_recovery_overhead_s"] = round(
            chaos_wall - clean_wall, 3
        )
        emit({
            "phase": "chaos_fanout",
            "wall_s": summary["chaos_fanout4_wall_s"],
            "clean_wall_s": summary["chaos_fanout4_clean_wall_s"],
            "faults_injected": faults,
            "completed": len(results),
            "resilience_counters_delta": counters_delta,
            "recovery_overhead_s":
                summary["chaos_fanout_recovery_overhead_s"],
        })
    except _PhaseSkipped:
        emit({"phase": "chaos_fanout", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "chaos_fanout", "error": repr(error)})

    # ---- phase 2c': elastic gangs under spot preemption ------------------
    # The same checkpoint-cooperative training electron through three arms:
    # clean (no faults), full-retry (preempted, checkpointing OFF — the
    # pre-elastic behavior: the retry recomputes from step 0), and resume
    # (preempted, interval checkpointing ON — the retry restores the
    # newest complete checkpoint).  The artifact records recomputed steps
    # and recovered wall per arm; CI asserts the resume arm recomputes at
    # most HALF the full-retry arm's steps and recovers strictly faster.
    try:
        if "preemption_chaos" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.transport import ChaosPlan

        PREEMPT_STEPS = int(os.environ.get("BENCH_PREEMPT_STEPS", "80"))
        PREEMPT_STEP_S = float(os.environ.get("BENCH_PREEMPT_STEP_S", "0.05"))

        def preempt_executor(arm: str, plan, checkpoint_s: float):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_preempt_{arm}",
                remote_cache=f"{workdir}/remote_preempt_{arm}",
                python_path=sys.executable,
                poll_freq=0.1,
                pool_preload="cloudpickle",
                use_agent=False,       # poll path: ops drive the preempt op count
                heartbeat_interval=0.5,  # telemetry carries the preempt notice
                max_task_retries=2,
                retry_base_delay=0.05,
                retry_max_delay=0.2,
                checkpoint_interval_s=checkpoint_s,
                chaos=plan,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        async def preempt_arm(arm: str, chaos: bool, checkpoint_s: float):
            plan = (
                ChaosPlan(preempt_after=20, preempt_grace=1.0, max_faults=1)
                if chaos
                else None
            )
            ex = preempt_executor(arm, plan, checkpoint_s)
            progress = f"{workdir}/preempt_progress_{arm}.txt"
            t0 = time.perf_counter()
            try:
                result = await ex.run(
                    preemptible_train,
                    [PREEMPT_STEPS, PREEMPT_STEP_S, progress],
                    {},
                    {"dispatch_id": f"preempt-{arm}", "node_id": 0},
                )
            finally:
                await ex.close()
            wall = time.perf_counter() - t0
            with open(progress) as f:
                executed = [int(x) for x in f.read().split()]
            return {
                "arm": arm,
                "wall_s": round(wall, 3),
                "result_ok": result[0] == sum(range(PREEMPT_STEPS)),
                "resumed_start": int(result[1]),
                "steps_executed": len(executed),
                "steps_recomputed": len(executed) - len(set(executed)),
                "faults_injected": plan.faults_injected if plan else 0,
            }

        async def preemption_phase():
            clean = await preempt_arm("clean", chaos=False, checkpoint_s=0.0)
            retry = await preempt_arm("retry", chaos=True, checkpoint_s=0.0)
            resume = await preempt_arm(
                "resume", chaos=True, checkpoint_s=0.1
            )
            return clean, retry, resume

        clean, retry, resume = await asyncio.wait_for(
            preemption_phase(), FANOUT_BUDGET_S * 2
        )
        assert clean["result_ok"] and retry["result_ok"], (clean, retry)
        assert resume["result_ok"], resume
        retry_recovered = max(0.0, retry["wall_s"] - clean["wall_s"])
        resume_recovered = max(0.0, resume["wall_s"] - clean["wall_s"])
        summary["preemption_clean_wall_s"] = clean["wall_s"]
        summary["preemption_retry_wall_s"] = retry["wall_s"]
        summary["preemption_resume_wall_s"] = resume["wall_s"]
        summary["preemption_retry_recomputed_steps"] = (
            retry["steps_recomputed"]
        )
        summary["preemption_resume_recomputed_steps"] = (
            resume["steps_recomputed"]
        )
        summary["preemption_retry_recovered_wall_s"] = round(
            retry_recovered, 3
        )
        summary["preemption_resume_recovered_wall_s"] = round(
            resume_recovered, 3
        )
        # Both faulted arms must actually have been preempted for the
        # comparison to mean anything; the resume arm must have resumed.
        faulted = (
            retry["faults_injected"] == 1
            and resume["faults_injected"] == 1
            and retry["resumed_start"] == 0
            and resume["resumed_start"] > 0
        )
        summary["preemption_resume_recomputed_ok"] = bool(
            faulted
            and resume["steps_recomputed"]
            <= retry["steps_recomputed"] / 2
        )
        summary["preemption_resume_recovered_ok"] = bool(
            faulted and resume_recovered < retry_recovered
        )
        emit({
            "phase": "preemption_chaos",
            "steps": PREEMPT_STEPS,
            "arms": [clean, retry, resume],
            "retry_recovered_wall_s": round(retry_recovered, 3),
            "resume_recovered_wall_s": round(resume_recovered, 3),
            "resume_recomputed_ok":
                summary["preemption_resume_recomputed_ok"],
            "resume_recovered_ok":
                summary["preemption_resume_recovered_ok"],
        })
    except _PhaseSkipped:
        emit({"phase": "preemption_chaos", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "preemption_chaos", "error": repr(error)})

    # ---- phase 2c'': dispatcher crash recovery ---------------------------
    # SIGKILL the *dispatcher* (not a worker) mid-stream and prove the
    # successor incarnation replays the journal, re-adopts the surviving
    # pool servers and serving sessions, and resumes every in-flight
    # stream exactly once — the resumed tail splices byte-for-byte onto
    # the journaled high-water mark, no duplicate and no lost token.
    # Drill children carry the actual kill (a process cannot -9 itself
    # and keep benching); see run_dispatcher_crash_drill.
    try:
        if "dispatcher_crash" not in BENCH_PHASES:
            raise _PhaseSkipped
        # Overridable so CI can land the journal inside its artifact dir.
        drill_dir = (
            os.environ.get("BENCH_DISPATCHER_CRASH_DIR")
            or f"{workdir}/dispatcher_crash"
        )
        drill = await asyncio.get_running_loop().run_in_executor(
            None, run_dispatcher_crash_drill, drill_dir
        )
        summary["dispatcher_crash_recovery_s"] = drill["recovery_duration_s"]
        summary["dispatcher_crash_adopted"] = drill["sessions_adopted"]
        summary["dispatcher_crash_orphaned"] = drill["sessions_orphaned"]
        summary["dispatcher_crash_fallback_local"] = sum(
            value for key, value in drill["metrics"].items()
            if "fallback_local" in key
        )
        summary["recovery_streams_exact"] = drill["streams_exact"]
        emit({"phase": "dispatcher_crash", **drill})
    except _PhaseSkipped:
        emit({"phase": "dispatcher_crash", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "dispatcher_crash", "error": repr(error)})

    # ---- phase 2d: fleet scheduler fan-out vs naive 1:1 dispatch ---------
    # 16 electrons, 2 tenants, through the fleet work queue onto 2 warm
    # local pools (bin-packed onto pooled gangs, deficit-round-robin
    # fairness between the tenants) vs the pre-fleet shape: one FRESH
    # executor per electron, mapped 1:1 and dispatched sequentially.  The
    # scheduler arm's wall includes its own prewarm, so the comparison
    # charges the fleet for warming its gangs; warm-gang reuse must still
    # show as strictly fewer transport dials (connects < electrons) at
    # wall no worse than the naive arm's.
    try:
        if "sched_fanout" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.fleet import FleetExecutor

        SCHED_ELECTRONS = 16

        def pool_connect_misses() -> float:
            """Fresh transport dials (pool misses) recorded so far."""
            return sum(
                value for key, value in metrics_totals().items()
                if key.startswith("covalent_tpu_pool_acquires_total{")
                and "result=miss" in key
            )

        def sched_task_env() -> dict:
            return {
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
            }

        def sched_pool(tag: str, capacity: int) -> dict:
            return {
                "name": tag,
                "transport": "local",
                "capacity": capacity,
                "executor": {
                    "cache_dir": f"{workdir}/cache_sched_{tag}",
                    "remote_cache": f"{workdir}/remote_sched_{tag}",
                    "python_path": sys.executable,
                    "poll_freq": 0.2,
                    "use_agent": False,
                    "prewarm": True,
                    "task_env": sched_task_env(),
                },
            }

        async def naive_arm() -> dict:
            connects0 = pool_connect_misses()
            t0 = time.perf_counter()
            results = []
            for i in range(SCHED_ELECTRONS):
                ex = TPUExecutor(
                    transport="local",
                    cache_dir=f"{workdir}/cache_sched_naive",
                    remote_cache=f"{workdir}/remote_sched_naive_{i}",
                    python_path=sys.executable,
                    poll_freq=0.2,
                    use_agent=False,
                    prewarm=False,
                    task_env=sched_task_env(),
                )
                try:
                    results.append(await ex.run(
                        trivial_electron, [i], {},
                        {"dispatch_id": "schednaive", "node_id": i},
                    ))
                finally:
                    await ex.close()
            return {
                "wall_s": time.perf_counter() - t0,
                "connects": pool_connect_misses() - connects0,
                "results": results,
            }

        async def fleet_arm() -> dict:
            fleet = FleetExecutor(
                pools=[sched_pool("sa", 4), sched_pool("sb", 4)],
                ensure_fallback=False,
            )
            try:
                connects0 = pool_connect_misses()
                t0 = time.perf_counter()
                # Warm both gangs THEN pack the whole backlog onto them:
                # the dial + pre-flight cost is inside the measured wall.
                await fleet.prewarm()
                results = await asyncio.gather(*(
                    fleet.run(
                        trivial_electron, [i], {},
                        {"dispatch_id": "schedfleet", "node_id": i,
                         "tenant": "heavy" if i % 2 else "light"},
                    )
                    for i in range(SCHED_ELECTRONS)
                ))
                wall = time.perf_counter() - t0
                connects = pool_connect_misses() - connects0
                status = fleet.scheduler.status()
                placements = {
                    name: view["placed_total"]
                    for name, view in status["pools"].items()
                }
                decisions = dict(fleet.scheduler.decisions)
            finally:
                await fleet.close()
            return {
                "wall_s": wall,
                "connects": connects,
                "results": list(results),
                "placements": placements,
                "decisions": decisions,
            }

        async def sched_phase():
            return await naive_arm(), await fleet_arm()

        naive, fleet_run = await asyncio.wait_for(
            sched_phase(), FANOUT_BUDGET_S * 2
        )
        assert fleet_run["results"] == naive["results"], (
            fleet_run["results"], naive["results"])
        summary["sched_fanout_wall_s"] = round(fleet_run["wall_s"], 3)
        summary["sched_fanout_naive_wall_s"] = round(naive["wall_s"], 3)
        summary["sched_fanout_connects"] = round(fleet_run["connects"], 1)
        summary["sched_fanout_naive_connects"] = round(naive["connects"], 1)
        summary["sched_fanout_placements"] = fleet_run["placements"]
        summary["sched_fanout_decisions"] = fleet_run["decisions"]
        # Warm-gang bin-packing: 16 electrons over 2 pooled gangs dial a
        # handful of channels, never one per electron.
        summary["sched_fanout_fewer_connects"] = bool(
            fleet_run["connects"] < SCHED_ELECTRONS
        )
        summary["sched_fanout_no_slower"] = bool(
            fleet_run["wall_s"] <= naive["wall_s"]
        )
        emit({
            "phase": "sched_fanout",
            "electrons": SCHED_ELECTRONS,
            "wall_s": summary["sched_fanout_wall_s"],
            "naive_wall_s": summary["sched_fanout_naive_wall_s"],
            "connects": summary["sched_fanout_connects"],
            "naive_connects": summary["sched_fanout_naive_connects"],
            "placements": fleet_run["placements"],
            "decisions": fleet_run["decisions"],
            "fewer_connects": summary["sched_fanout_fewer_connects"],
            "no_slower": summary["sched_fanout_no_slower"],
        })
    except _PhaseSkipped:
        emit({"phase": "sched_fanout", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "sched_fanout", "error": repr(error)})

    # ---- phase 2e: closed-loop autoscaling under a traffic ramp ----------
    # The SAME ramping open-loop load (light warm-up, a surge past one
    # replica's throughput ceiling, a cool tail) through two arms: a
    # statically over-provisioned RAMP_REPLICAS_MAX-replica set, and a
    # 1-replica set under the AutoscaleController with a deliberately
    # tight injected latency SLO.  The autoscaled arm must see the
    # injected burn fire, scale up (trend- and burn-driven), CLEAR the
    # burn while traffic still flows, hold p95 within a decode chunk of
    # the static arm, and consume measurably fewer warm gang-seconds
    # (live replicas integrated over the run) — right-sized capacity,
    # not over-provisioned capacity, is what holds the SLO.
    try:
        if "traffic_ramp" not in BENCH_PHASES:
            raise _PhaseSkipped
        from covalent_tpu_plugin.fleet import AutoscaleController
        from covalent_tpu_plugin.obs.history import HISTORY
        from covalent_tpu_plugin.obs.slo import SLOEngine, SLOSpec
        from covalent_tpu_plugin.serving import open_replica_set

        def make_ramp_factory():
            step_s, cap = RAMP_STEP_S, RAMP_TOKENS

            def factory():
                import time as _time

                class Engine:
                    def __init__(self):
                        self.slots = 2
                        self.lanes = {}

                    def admit(self, rid, prompt, params):
                        seed = int(prompt[-1])
                        n = int((params or {}).get("max_new_tokens", cap))
                        self.lanes[rid] = [
                            seed * 100 + j + 1 for j in range(n)
                        ]

                    def step(self):
                        _time.sleep(step_s)
                        events = []
                        for rid in list(self.lanes):
                            chunk = self.lanes[rid][:2]
                            self.lanes[rid] = self.lanes[rid][2:]
                            done = not self.lanes[rid]
                            if done:
                                del self.lanes[rid]
                            events.append({
                                "rid": rid, "tokens": chunk, "done": done,
                            })
                        return events

                    def cancel(self, rid):
                        self.lanes.pop(rid, None)

                return Engine()

            return factory

        def ramp_executor(tag: str):
            return TPUExecutor(
                transport="local",
                cache_dir=f"{workdir}/cache_ramp_{tag}",
                remote_cache=f"{workdir}/remote_ramp_{tag}",
                python_path=sys.executable,
                poll_freq=0.2,
                use_agent="pool",
                pool_preload="cloudpickle",
                prewarm=False,
                heartbeat_interval=0.0,
                task_env={
                    "PYTHONPATH": repo_root + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                },
            )

        def ramp_schedule() -> list[float]:
            """Arrival intervals: warm, accelerating surge, cool."""
            intervals = [RAMP_WARM_INTERVAL_S] * RAMP_WARM_REQUESTS
            surge_n = max(1, RAMP_SURGE_REQUESTS)
            for i in range(surge_n):
                frac = i / max(1, surge_n - 1)
                intervals.append(
                    RAMP_SURGE_START_S
                    + (RAMP_SURGE_END_S - RAMP_SURGE_START_S) * frac
                )
            intervals += [RAMP_COOL_INTERVAL_S] * RAMP_COOL_REQUESTS
            return intervals

        async def ramp_arm(autoscaled: bool) -> dict:
            tag = "auto" if autoscaled else "static"
            executors = [
                ramp_executor(f"{tag}{i}")
                for i in range(RAMP_REPLICAS_MAX)
            ]
            controller = None
            listener = None
            rset = None
            meter = None
            stop = asyncio.Event()
            gang_samples: list = []
            burn_events: list = []
            try:
                rset = await open_replica_set(
                    executors,
                    make_ramp_factory(),
                    replicas=(1 if autoscaled else RAMP_REPLICAS_MAX),
                    name=f"ramp_{tag}",
                    stats_interval_s=0.2,
                )

                async def gang_meter():
                    while not stop.is_set():
                        gang_samples.append(
                            (time.perf_counter(), rset.live_replicas)
                        )
                        await asyncio.sleep(0.05)

                meter = asyncio.ensure_future(gang_meter())
                if autoscaled:
                    # A long bench run has downsampled the ring (stride
                    # doubling): a coarse-grained trend holds the set's
                    # own startup transient for seconds and can scale up
                    # during the warm phase.  Reset to fine-grained
                    # samples for the arm under measurement.
                    HISTORY.clear()
                    spec = SLOSpec(
                        name="ramp_injected_latency",
                        metric="covalent_tpu_serve_request_seconds",
                        kind="latency",
                        threshold_s=RAMP_SLO_THRESHOLD_S,
                        objective=RAMP_SLO_OBJECTIVE,
                        windows=[3.0, 8.0],
                    )
                    engine = SLOEngine(HISTORY, specs=[spec])
                    engine.add_alert_hook(
                        lambda _name, state, _info: burn_events.append(
                            (state, time.perf_counter())
                        )
                    )
                    listener = lambda _ts: engine.evaluate()  # noqa: E731
                    HISTORY.add_listener(listener)
                    controller = AutoscaleController(
                        history=HISTORY,
                        slo_engine=engine,
                        interval_s=0.15,
                        up_cooldown_s=0.4,
                        down_cooldown_s=6.0,
                        idle_ttl_s=0.0,
                        lead_s=RAMP_LEAD_S,
                        # 3s: long enough for a real trend, short enough
                        # that the set's own 0->1 startup transient has
                        # aged out before the surge (a 4s window plus a
                        # 0.6 utilization band flaked an early scale-up
                        # during the warm phase, erasing the burn AND the
                        # gang-second savings the phase asserts).
                        trend_window_s=3.0,
                    )
                    controller.manage_replica_set(
                        rset,
                        min_replicas=1,
                        max_replicas=RAMP_REPLICAS_MAX,
                        target_utilization=0.8,
                        # ~0.45s of sustained demand before a trend-
                        # driven scale-up: a single warm-phase overlap
                        # (one request's service time) is not the surge.
                        # The injected burn bypasses this entirely.
                        up_stabilization_ticks=3,
                    )
                    controller.start()
                t0 = time.perf_counter()
                tasks = []
                for seed, interval in enumerate(ramp_schedule()):
                    tasks.append(asyncio.ensure_future(rset.request(
                        [seed], params={"max_new_tokens": RAMP_TOKENS},
                    )))
                    await asyncio.sleep(interval)
                requests = await asyncio.gather(*tasks)
                results = await asyncio.gather(
                    *(r.result(timeout=RAMP_BUDGET_S) for r in requests)
                )
                wall = time.perf_counter() - t0
                latencies = [r.latency_s for r in requests]
                trace_ids = [r.span.trace_id for r in requests]
                scale_decisions = (
                    dict(controller.decision_counts)
                    if controller is not None else {}
                )
                controller_status = (
                    controller.status() if controller is not None else {}
                )
            finally:
                # Cleanup lives HERE, not in the try body: a failed arm
                # (stream timeout mid-gather) must not leak the 20 Hz
                # gang meter or an open replica set into the phases that
                # run after the phase-level except swallows the error.
                stop.set()
                if meter is not None:
                    try:
                        await meter
                    except Exception:  # noqa: BLE001
                        meter.cancel()
                if controller is not None:
                    await controller.close()
                if listener is not None:
                    HISTORY.remove_listener(listener)
                if rset is not None:
                    try:
                        await rset.close()
                    except Exception:  # noqa: BLE001 - teardown best-effort
                        pass
                for ex in executors:
                    await ex.close()
            gang_seconds = sum(
                max(0.0, t_b - t_a) * live_a
                for (t_a, live_a), (t_b, _live_b) in zip(
                    gang_samples, gang_samples[1:]
                )
            )
            return {
                "wall_s": wall,
                "results": list(results),
                "latencies": latencies,
                "trace_ids": trace_ids,
                "gang_seconds": gang_seconds,
                "max_live": max(
                    (live for _t, live in gang_samples), default=0
                ),
                "burn_events": [
                    (state, round(ts - t0, 3))
                    for state, ts in burn_events
                ],
                "decisions": scale_decisions,
                "controller": controller_status,
            }

        async def ramp_phase():
            static = await ramp_arm(False)
            # A short gap so the static arm's (all-good) latency samples
            # age out of the injected SLO's short window before the
            # autoscaled arm starts.
            await asyncio.sleep(2.0)
            auto = await ramp_arm(True)
            return static, auto

        static_arm, auto_arm = await asyncio.wait_for(
            ramp_phase(), RAMP_BUDGET_S * 2
        )
        n_requests = (
            RAMP_WARM_REQUESTS + RAMP_SURGE_REQUESTS + RAMP_COOL_REQUESTS
        )
        expected = [
            [i * 100 + j + 1 for j in range(RAMP_TOKENS)]
            for i in range(n_requests)
        ]
        assert static_arm["results"] == expected, "static streams diverged"
        assert auto_arm["results"] == expected, "autoscaled streams diverged"
        p95_static = percentile(static_arm["latencies"], 0.95)
        p95_auto = percentile(auto_arm["latencies"], 0.95)
        burn_states = [state for state, _ts in auto_arm["burn_events"]]
        burn_fired = "burning" in burn_states
        burn_cleared = bool(
            burn_fired and burn_states[-1] == "ok"
        )
        scaled_up = bool(
            auto_arm["decisions"].get("set_up", 0) >= 1
            and auto_arm["max_live"] > 1
        )
        gang_ratio = auto_arm["gang_seconds"] / max(
            static_arm["gang_seconds"], 1e-9
        )
        summary["ramp_requests"] = n_requests
        summary["ramp_p95_static_s"] = round(p95_static, 4)
        summary["ramp_p95_auto_s"] = round(p95_auto, 4)
        summary["ramp_p95_ok"] = bool(
            p95_auto <= p95_static + RAMP_P95_MARGIN_S
        )
        summary["ramp_gang_seconds_static"] = round(
            static_arm["gang_seconds"], 2
        )
        summary["ramp_gang_seconds_auto"] = round(
            auto_arm["gang_seconds"], 2
        )
        summary["ramp_gang_ratio"] = round(gang_ratio, 3)
        summary["ramp_fewer_gang_seconds_ok"] = bool(
            gang_ratio <= RAMP_GANG_RATIO_MAX
        )
        summary["ramp_burn_fired_ok"] = burn_fired
        summary["ramp_burn_cleared_ok"] = burn_cleared
        summary["ramp_scaled_up_ok"] = scaled_up
        emit({
            "phase": "traffic_ramp",
            "requests": n_requests,
            "tokens_per_request": RAMP_TOKENS,
            "step_s": RAMP_STEP_S,
            "replicas_static": RAMP_REPLICAS_MAX,
            "replicas_auto_max": auto_arm["max_live"],
            "wall_static_s": round(static_arm["wall_s"], 3),
            "wall_auto_s": round(auto_arm["wall_s"], 3),
            "p95_static_s": summary["ramp_p95_static_s"],
            "p95_auto_s": summary["ramp_p95_auto_s"],
            "p95_margin_s": RAMP_P95_MARGIN_S,
            "p95_ok": summary["ramp_p95_ok"],
            "gang_seconds_static": summary["ramp_gang_seconds_static"],
            "gang_seconds_auto": summary["ramp_gang_seconds_auto"],
            "gang_ratio": summary["ramp_gang_ratio"],
            "gang_ratio_max": RAMP_GANG_RATIO_MAX,
            "fewer_gang_seconds": summary["ramp_fewer_gang_seconds_ok"],
            "burn_events": auto_arm["burn_events"],
            "burn_fired": burn_fired,
            "burn_cleared": burn_cleared,
            "scaled_up": scaled_up,
            "autoscale_decisions": auto_arm["decisions"],
            "latency_attribution": latency_attribution(
                auto_arm["trace_ids"]
            ),
            "introspection": introspection_view([
                "covalent_tpu_serve_request_seconds",
                "covalent_tpu_serve_replicas",
                "covalent_tpu_slo_burn_rate",
                "covalent_tpu_autoscale_decisions_total",
            ]),
            **spread_stats(auto_arm["latencies"], "ramp_auto_latency"),
        })
    except _PhaseSkipped:
        emit({"phase": "traffic_ramp", "skipped": "BENCH_PHASES"})
    except Exception as error:  # noqa: BLE001
        emit({"phase": "traffic_ramp", "error": repr(error)})

    # ---- phase 3: all accelerator work, ONE electron, ONE backend init ---
    # The whole phase lives under ONE wall-clock deadline (the old
    # 360 s + 120 s two-attempt worst case).  Preflight gates the electron:
    # the big budget is only committed once a throwaway subprocess has
    # proven the tunnel healthy; while it is NOT healthy we burn the
    # deadline in cheap 45 s probes on a short cadence (a relay that
    # recovers mid-window still gets its electron) instead of r3's two
    # monolithic hangs that zeroed the round.
    collected: dict = {}
    progress_path = f"{workdir}/tpu_progress.jsonl"
    os.makedirs(workdir, exist_ok=True)
    stop = asyncio.Event()
    tailer = asyncio.create_task(tail_progress(progress_path, collected, stop))
    phase3_deadline = time.monotonic() + TPU_BUDGET_S + TPU_BUDGET_S / 3

    def phase3_left() -> float:
        return phase3_deadline - time.monotonic()

    try:
        healthy = False
        skipped_tpu = "tpu" not in BENCH_PHASES
        preflight_attempts = 0
        preflight_last_error = ""
        for attempt in range(0 if skipped_tpu else 64):
            ok, took, err = await asyncio.get_event_loop().run_in_executor(
                None, tpu_preflight, min(45.0, max(phase3_left() - 5, 5.0))
            )
            emit({"phase": "tpu.preflight", "attempt": attempt, "ok": ok,
                  "probe_s": round(took, 1), **({"error": err} if err else {})})
            preflight_attempts = attempt + 1
            if err:
                preflight_last_error = err
            if ok:
                healthy = True
                break
            # A host with no TPU hardware will not grow any between
            # attempts: retrying a permanent refusal just burns the
            # deadline the electron could still use.
            if PREFLIGHT_PERMANENT in err:
                break
            # Leave enough deadline for one more probe + a minimal electron.
            if phase3_left() < 90:
                break
            # Exponential backoff: transient tunnel faults (agent restart,
            # libtpu grabbing the chip lock) clear in seconds, real
            # outages in minutes — back off toward 30 s instead of
            # hammering a fixed cadence.
            backoff = min(30.0, 2.0 ** attempt)
            await asyncio.sleep(min(backoff, max(phase3_left() - 60, 1.0)))
        if skipped_tpu:
            emit({"phase": "tpu", "skipped": "BENCH_PHASES"})
        elif not healthy:
            # The failure REASON rides into the summary (and from there
            # the final combined line): the preflight has been silently
            # down since r03, with the stale last_known_good block riding
            # along undiagnosed — an artifact must say WHY its live TPU
            # fields are null, not just that they are.
            reason = (
                preflight_last_error
                or "no probe ran (deadline exhausted before the first "
                "attempt)"
            )
            summary["tpu_preflight_failure"] = {
                "attempts": preflight_attempts,
                "last_error": reason,
            }
            # Promote the reason to a flat top-level summary field: the
            # nested dict is easy to miss when eyeballing the final
            # combined line for why every live TPU field is null.
            summary["tpu_preflight_failure_reason"] = reason
            emit({"phase": "tpu", "error": "preflight never passed; "
                  "electron skipped (tunnel down)",
                  "preflight_attempts": preflight_attempts,
                  "preflight_last_error": preflight_last_error})
            # CI log annotation (GitHub Actions picks these up from any
            # step output and surfaces them on the run summary page).
            # stderr, NOT stdout: the stdout protocol is JSON lines and
            # the driver tails it.
            print(
                f"::warning title=TPU preflight failed::{reason} "
                f"(attempts={preflight_attempts})",
                file=sys.stderr, flush=True,
            )
        attempt = 0
        while healthy:
            # First electron gets the full remaining deadline; a retry only
            # makes sense when the attempt produced NOTHING (if init
            # succeeded, the budget is simply spent) and enough wall
            # remains for a meaningful rerun.
            budget = max(phase3_left() - 10, 30.0)
            try:
                await asyncio.wait_for(
                    executor.run(
                        accelerator_electron,
                        [progress_path, budget - 15.0],
                        {},
                        {"dispatch_id": f"accel{attempt}", "node_id": 0},
                    ),
                    budget,
                )
                break
            except Exception as error:  # noqa: BLE001
                emit({"phase": "tpu", "attempt": attempt, "error": repr(error)})
                try:
                    await asyncio.wait_for(executor.cancel(), 10)
                except Exception:  # noqa: BLE001
                    pass
                await asyncio.sleep(1)  # let the tailer drain partial lines
                if "init" in collected or phase3_left() < 60:
                    break  # backend came up (or no wall left): rerun can't help
                attempt += 1
    finally:
        stop.set()
        try:
            await asyncio.wait_for(tailer, 5)
        except Exception:  # noqa: BLE001
            tailer.cancel()

    try:
        await asyncio.wait_for(executor.close(), 15)
    except Exception:  # noqa: BLE001
        pass

    # Archive the whole trace store when asked (CI sets
    # COVALENT_TPU_TRACE_DUMP so the sampled waterfalls ride the build
    # artifact next to the metrics snapshots).
    dump_path = os.environ.get("COVALENT_TPU_TRACE_DUMP")
    if dump_path:
        try:
            from covalent_tpu_plugin.obs.tracestore import ensure_trace_store

            with open(dump_path, "w") as f:
                json.dump(ensure_trace_store().dump(), f, sort_keys=True)
        except Exception as error:  # noqa: BLE001 - artifact, not a gate
            emit({"phase": "trace_dump", "error": repr(error)})

    # ---- final combined line (must be LAST) ------------------------------
    def sub(phase, key):
        data = collected.get(phase) or {}
        return data.get(key)

    def pick(live, fallback):
        # Explicit None check, NOT ``or``: a legitimate 0.0 (or False)
        # from the TPU subphase must win over the CPU-phase fallback.
        return live if live is not None else fallback

    final = {
        "metric": "dispatch_overhead_s",
        "value": summary.get("dispatch_overhead_s"),
        "unit": "s",
        "vs_baseline": (
            round(2.0 / max(overhead, 1e-9), 2) if overhead else None
        ),
        **{k: v for k, v in summary.items() if k != "dispatch_overhead_s"},
        # fanout8_busy_speedup rides in via summary: 8 electrons x 300 ms
        # of real work — the honest concurrency figure.
        "backend": sub("init", "backend"),
        "device_kind": sub("init", "device_kind"),
        "backend_init_s": sub("init", "init_s"),
        "matmul4k_tflops": sub("matmul", "tflops"),
        "matmul4k_mfu": sub("matmul", "mfu"),
        "matmul4k_unit_ms_stdev": sub("matmul", "unit_ms_stdev"),
        "mnist_steps_per_s": sub("mnist", "steps_per_s"),
        "mnist_n_batches": sub("mnist", "n_batches"),
        "mnist_loss_first": sub("mnist", "loss_first"),
        "mnist_loss_last": sub("mnist", "loss_last"),
        "flash_fwd_4k_speedup": sub("flash_fwd", "speedup"),
        "flash_fwd_4k_ms": sub("flash_fwd", "flash_ms"),
        "flash_bwd_4k_speedup": sub("flash_bwd", "speedup"),
        "flash_16k_fwd_bwd_ms": sub("flash_long", "fwd_bwd_ms"),
        "flash_16k_attn_tflops": sub("flash_long", "attn_tflops"),
        "flash_16k_window1k_ms": sub("flash_window", "fwd_bwd_ms"),
        "flash_16k_window1k_speedup": sub("flash_window", "speedup_vs_full"),
        "flash_16k_window512_speedup": sub(
            "flash_window_512", "speedup_vs_full"
        ),
        "banded_max_err": sub("flash_window", "banded_max_err"),
        "lm125m_step_ms": sub("lm_step", "step_ms"),
        "lm125m_tokens_per_s": sub("lm_step", "tokens_per_s"),
        "lm125m_mfu": sub("lm_step", "mfu"),
        "lm125m_decode_tokens_per_s": sub("lm_decode", "e2e_tokens_per_s"),
        "lm125m_decode_ms_per_token": sub("lm_decode", "e2e_ms_per_new_token"),
        "lm125m_decode_int8_tokens_per_s": sub("lm_decode_int8", "tokens_per_s"),
        "lm125m_decode_int8_speedup_ab": sub(
            "lm_decode_int8", "speedup_vs_bf16_same_phase"
        ),
        "lm125m_decode_kvq_tokens_per_s": sub("lm_decode_kvq", "tokens_per_s"),
        "lm125m_decode_kvq_speedup_ab": sub(
            "lm_decode_kvq", "speedup_vs_bf16_same_phase"
        ),
        "lm125m_decode_fullq_tokens_per_s": sub(
            "lm_decode_fullq", "tokens_per_s"
        ),
        "lm125m_decode_fullq_speedup_ab": sub(
            "lm_decode_fullq", "speedup_vs_bf16_same_phase"
        ),
        # Speculative decoding: the TPU lm_spec subphase's numbers when
        # it ran, else the serve_spec engine phase's (real
        # ContinuousEngine arms on the local backend) — these fields
        # rode along null through every post-r03 tunnel outage.
        "spec_accept_rate": pick(
            sub("lm_spec", "accept_rate"),
            summary.get("serve_spec_accept_rate"),
        ),
        "spec_tokens_per_s": pick(
            sub("lm_spec", "spec_tokens_per_s"),
            summary.get("serve_spec_tokens_per_s"),
        ),
        "spec_plain_tokens_per_s": pick(
            sub("lm_spec", "plain_tokens_per_s"),
            summary.get("serve_spec_tokens_per_s_fp"),
        ),
        "spec_speedup": pick(
            sub("lm_spec", "speedup"), summary.get("serve_spec_speedup")
        ),
        "spec_exact": pick(
            sub("lm_spec", "exact"), summary.get("serve_spec_exact")
        ),
        "spec_quant_speedup": pick(
            sub("lm_spec_quant", "speedup"),
            summary.get("serve_spec_quant_speedup"),
        ),
        "spec_quant_tokens_per_s": pick(
            sub("lm_spec_quant", "spec_tokens_per_s"),
            summary.get("serve_spec_quant_tokens_per_s"),
        ),
        "spec_quant_exact": pick(
            sub("lm_spec_quant", "exact"),
            summary.get("serve_spec_quant_deterministic"),
        ),
    }
    # The serving phase is a beyond-parity bonus that self-skips on tight
    # budgets; merge its fields only when it actually measured, so a
    # skipped run does not re-introduce null TPU fields.
    # Measured-only merges (no new nullable keys on outage/skip paths).
    if sub("lm_step_fused", "step_ms") is not None:
        final.update({
            "lm125m_fused_step_ms": sub("lm_step_fused", "step_ms"),
            "lm125m_fused_mfu": sub("lm_step_fused", "mfu"),
            "lm125m_fused_speedup": sub(
                "lm_step_fused", "speedup_vs_std_step"
            ),
        })
    if sub("lm_serve", "tokens_per_s") is not None:
        final.update({
            "serve_tokens_per_s": sub("lm_serve", "tokens_per_s"),
            "serve_step_reduction_vs_static": sub(
                "lm_serve", "step_reduction_vs_static"
            ),
            "serve_wall_speedup_vs_static_waves": sub(
                "lm_serve", "wall_speedup_vs_static_waves"
            ),
            "serve_complete": sub("lm_serve", "complete"),
        })
    if sub("init", "backend") is None and "tpu" in BENCH_PHASES:
        # Outage path: every accelerator field above is null.  Attach the
        # newest committed self-run under an explicitly-stale key (never
        # backfilled into the live fields) so the artifact self-describes
        # instead of reading as "no evidence exists".  A deliberate
        # BENCH_PHASES deselect (CI smoke) is not an outage: no stale data.
        lkg = load_last_known_good()
        if lkg is not None:
            final["last_known_good"] = lkg
    final["stage_histograms"] = stage_histogram_summary()
    final["metrics_totals"] = metrics_totals()
    emit(final)


def metrics_totals() -> dict:
    """Flat counter/gauge snapshot (the registry's scalar series)."""
    from covalent_tpu_plugin.obs.metrics import REGISTRY

    out: dict = {}
    for name, metric in REGISTRY.snapshot()["metrics"].items():
        if metric["kind"] == "histogram":
            continue
        for series in metric["series"]:
            labels = ",".join(f"{k}={v}" for k, v in series["labels"].items())
            key = f"{name}{{{labels}}}" if labels else name
            out[key] = series["value"]
    return out


def stage_histogram_summary() -> dict:
    """Per-stage dispatch latency distributions from the obs registry.

    Every probe/fanout electron above ran through the instrumented
    TPUExecutor lifecycle, so the span histograms hold the full per-stage
    distribution — count/sum/p50/p95 per ``executor.<stage>`` plus the
    overhead histogram — where the pre-obs bench reported one overhead
    scalar.  Future BENCH_r*.json rounds carry this breakdown.
    """
    from covalent_tpu_plugin.obs.metrics import REGISTRY
    from covalent_tpu_plugin.obs.trace import SPAN_HISTOGRAM

    out: dict = {}
    snap = REGISTRY.snapshot()["metrics"]
    spans = snap.get(SPAN_HISTOGRAM, {}).get("series", [])
    for series in spans:
        name = series["labels"].get("span", "")
        if not name.startswith(("executor.", "pool.", "agent.")):
            continue
        out[name] = {
            "count": series["count"],
            "sum_s": round(series["sum"], 4),
            "p50_s": series["p50"],
            "p95_s": series["p95"],
        }
    overhead = snap.get("covalent_tpu_dispatch_overhead_seconds", {})
    for series in overhead.get("series", []):
        out["dispatch_overhead"] = {
            "count": series["count"],
            "sum_s": round(series["sum"], 4),
            "p50_s": series["p50"],
            "p95_s": series["p95"],
        }
    return out


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--dispatcher-drill":
        # Child modes of the dispatcher_crash phase, not a bench run.
        mode, dwork = sys.argv[2], sys.argv[3]
        if mode == "serve":
            asyncio.run(_drill_serve(dwork))
        else:
            asyncio.run(_drill_recover(dwork))
        sys.stdout.flush()
        os._exit(0)
    asyncio.run(main())
    # Non-daemon helper threads from transport/agent internals must not keep
    # a finished bench alive into the driver's timeout.
    sys.stdout.flush()
    os._exit(0)
