"""Multi-host data-plane bootstrap helpers.

The harness performs the actual ``jax.distributed.initialize`` call from the
task spec (``covalent_tpu_plugin/harness.py``); these helpers cover the two
adjacent needs: electrons inspecting their place in the pod, and executors
constructing the coordinator spec (SURVEY §2.4's "control plane arranges N
processes with consistent coordinator_address/process_id so XLA can do the
rest").
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ProcessInfo:
    process_id: int
    num_processes: int
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


def process_info() -> ProcessInfo:
    """Where am I in the pod?  Callable from inside any electron."""
    import jax

    return ProcessInfo(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )


def coordinator_spec(
    workers: list[str] | None = None,
    port: int = 8476,
    *,
    coordinator_address: str | None = None,
    num_processes: int | None = None,
) -> list[dict]:
    """Per-worker ``distributed`` spec blocks for the task spec files.

    By default worker 0's host is the rendezvous point; addresses may carry
    a ``user@`` prefix on the control plane which is stripped for the data
    plane.  The executor passes an explicit ``coordinator_address`` instead
    when the rendezvous host differs from the dial address (TPU pods dial
    internal IPs; the local transport rendezvouses on 127.0.0.1).
    """
    if coordinator_address is None:
        if not workers:
            raise ValueError("coordinator_spec needs workers or coordinator_address")
        host = workers[0].split("@", 1)[-1]
        # Strip a :ssh-port suffix (host:2222) — the data plane dials its
        # own port; IPv6-style colon-bearing hosts pass through whole.
        front, sep, maybe_port = host.rpartition(":")
        if sep and maybe_port.isdigit() and ":" not in front:
            host = front
        coordinator_address = f"{host}:{port}"
    if num_processes is None:
        num_processes = len(workers or [])
    return [
        {
            "coordinator_address": coordinator_address,
            "num_processes": num_processes,
            "process_id": i,
        }
        for i in range(num_processes)
    ]
