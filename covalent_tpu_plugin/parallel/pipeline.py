"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The TPU-native formulation: stage parameters are the *sharded leading axis*
of a stacked pytree (one slice per device along the ``pipe`` mesh axis),
activations hop stage-to-stage with ``lax.ppermute`` (one ICI neighbor hop
per tick), and the schedule is a ``lax.scan`` over ``M + S - 1`` ticks — at
tick ``t`` stage ``s`` processes microbatch ``t - s`` (the classic GPipe
diagonal; the ``S - 1`` edge ticks are the pipeline bubble).  Reverse-mode
autodiff through the scan + ppermute yields the backward schedule
automatically, so one ``jax.grad`` trains the pipeline.

The reference has no parallelism of any kind (SURVEY §2.4); this completes
the framework's axis set (data/fsdp/tensor/seq/pipe), all expressed through
the same mesh + collectives machinery.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_stages(params: Any, n_stages: int) -> Any:
    """Reshape a stacked-layer pytree (leading axis ``n_layers``) into
    ``(n_stages, layers_per_stage, ...)`` for pipe-axis sharding."""

    def split(leaf):
        if leaf.shape[0] % n_stages:
            raise ValueError(
                f"layer axis {leaf.shape[0]} not divisible by "
                f"{n_stages} pipeline stages"
            )
        return leaf.reshape(n_stages, leaf.shape[0] // n_stages, *leaf.shape[1:])

    return jax.tree_util.tree_map(split, params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    axis_name: str = "pipe",
) -> jax.Array:
    """Per-shard GPipe schedule — call under ``shard_map``.

    ``stage_params`` is ONE stage's slice (the shard_map in_spec consumes
    the stacked leading axis); ``microbatches`` is ``(M, ...)`` and must be
    identical on every stage (replicated over the pipe axis).
    ``stage_fn(stage_params, x) -> y`` must preserve ``x``'s shape.
    Returns this stage's ``(M, ...)`` outputs — only the LAST stage's are
    the pipeline's outputs (the wrapper selects them).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    ticks = n_micro + n_stages - 1

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 draws from the microbatch queue; later stages consume the
        # activation their predecessor pushed last tick.
        feed_idx = jnp.clip(t, 0, n_micro - 1)
        feed = lax.dynamic_index_in_dim(microbatches, feed_idx, keepdims=False)
        x = jnp.where(stage == 0, feed, recv)
        y = stage_fn(stage_params, x)
        # A completed microbatch leaves the last stage at tick t with index
        # t - (S-1); edge ticks (the bubble) write nothing.
        out_idx = t - (n_stages - 1)
        valid = jnp.logical_and(out_idx >= 0, out_idx < n_micro)
        updated = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(out_idx, 0, n_micro - 1), axis=0
        )
        outputs = jnp.where(valid, updated, outputs)
        # One neighbor hop: stage s hands its activation to s+1 (the wrap
        # to stage 0 carries no meaning; stage 0 never reads recv).
        recv = lax.ppermute(
            y, axis_name,
            [(i, (i + 1) % n_stages) for i in range(n_stages)],
        )
        return (recv, outputs), ()

    recv0 = jnp.zeros_like(microbatches[0])
    outputs0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (recv0, outputs0), jnp.arange(ticks))
    return outputs


def pipelined(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    axis_name: str = "pipe",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
) -> Callable[[Any, jax.Array], jax.Array]:
    """Wrap ``stage_fn`` into a pipeline over ``mesh``'s ``axis_name`` axis.

    Returns ``fn(stacked_params, microbatches) -> outputs`` operating on
    global arrays: ``stacked_params`` has a leading ``n_stages`` axis
    (sharded over the pipe axis — each device materialises only its
    stage), ``microbatches`` is ``(M, B, ...)`` with ``B`` sharded over the
    data axes and everything replicated over pipe.  Composes with dp/fsdp
    in the same shard_map.
    """
    batch_axes = tuple(a for a in batch_axes if a in mesh.shape)
    if axis_name not in mesh.shape:
        raise ValueError(f"mesh has no axis {axis_name!r}: {dict(mesh.shape)}")

    def body(stage_params, microbatches):
        # The pipe-sharded in_spec leaves a singleton stage axis on every
        # leaf; stage_fn works with its own stage's params directly.
        stage_params = jax.tree_util.tree_map(
            lambda leaf: jnp.squeeze(leaf, axis=0), stage_params
        )
        outputs = pipeline_apply(
            stage_fn, stage_params, microbatches, axis_name=axis_name
        )
        # Every stage produced an (M, ...) buffer; only the last stage's is
        # the pipeline output.  Broadcast it so the result is replicated
        # over pipe (valid under any later collective or host fetch).
        return _broadcast_from_last(outputs, axis_name)

    def fn(stacked_params, microbatches):
        params_spec = jax.tree_util.tree_map(
            lambda _: P(axis_name), stacked_params
        )
        mb_spec = P(None, batch_axes) if batch_axes else P()
        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(params_spec, mb_spec),
            out_specs=mb_spec,
            check_vma=False,
        )(stacked_params, microbatches)

    return fn


def _broadcast_from_last(x: jax.Array, axis_name: str) -> jax.Array:
    """Every stage gets the last stage's value (psum of a one-hot mask)."""
    n = lax.axis_size(axis_name)
    is_last = (lax.axis_index(axis_name) == n - 1).astype(x.dtype)
    return lax.psum(x * is_last, axis_name)
