"""Device-mesh construction.

Axis convention (order matters: outer axes map to DCN/slower links first,
inner axes to ICI, per the standard TPU scaling recipe):

* ``data``   — pure data parallelism (gradients psum'd)
* ``fsdp``   — data parallelism with parameter sharding (weights gathered
  just-in-time); batch is sharded over ``data × fsdp``
* ``tensor`` — Megatron-style tensor parallelism inside layers
* ``seq``    — sequence/context parallelism (ring attention)
* ``pipe``   — pipeline parallelism (GPipe microbatch schedule, pipeline.py)

A dimension of 1 erases the axis's cost without changing program structure,
so one train-step definition serves every topology from v5e-1 to multi-host
pods.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

AXES = ("data", "fsdp", "tensor", "seq", "pipe")


@dataclass(frozen=True)
class MeshPlan:
    """A named factorisation of the device count over the standard axes."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    pipe: int = 1

    @property
    def sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "tensor": self.tensor,
            "seq": self.seq,
            "pipe": self.pipe,
        }

    def total(self) -> int:
        return self.data * self.fsdp * self.tensor * self.seq * self.pipe


def make_mesh(plan: MeshPlan, devices=None):
    """Build a ``jax.sharding.Mesh`` laid out per ``plan``.

    Device order follows ``jax.devices()`` (XLA already orders a slice so
    that adjacent logical ids are ICI neighbours); the *innermost* mesh axes
    therefore get the tightest links — tensor/seq collectives ride ICI.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if plan.total() > len(devices):
        raise ValueError(
            f"mesh plan {plan.sizes} needs {plan.total()} devices, got {len(devices)}"
        )
    array = np.array(devices[: plan.total()]).reshape(
        plan.data, plan.fsdp, plan.tensor, plan.seq, plan.pipe
    )
    return Mesh(array, AXES)


def make_hybrid_mesh(
    plan: MeshPlan,
    *,
    n_slices: int | None = None,
    dcn_axis: str = "data",
    devices=None,
):
    """Multi-slice mesh: ``dcn_axis`` spans slices (DCN), the rest ICI.

    The analog of ``jax.experimental.mesh_utils.create_hybrid_device_mesh``
    for BASELINE config 5's 2-worker v5e-16 story: collectives on the slow
    inter-slice links should be the infrequent, bandwidth-light ones (the
    data axis's once-per-step gradient psum), while tensor/seq/pipe
    collectives stay inside a slice on ICI.

    Device grouping honours ``device.slice_index`` when the runtime
    exposes it (real multi-slice TPU runtimes do; ``process_index`` is
    deliberately NOT used — it identifies a host, and a multi-host
    single-slice pod would be mis-read as multi-slice).  Without
    topology info — CPU test meshes, single-slice pods — devices split
    into ``n_slices`` equal contiguous groups (``jax.devices()`` orders
    by process, so contiguous groups respect host locality).  The
    ``dcn_axis`` extent must equal the slice count, and every other axis
    must fit inside ONE slice: an axis straddling a slice boundary would
    silently put its collectives on DCN, which is exactly the mistake
    this helper exists to prevent.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())

    def slice_id(d):
        value = getattr(d, "slice_index", None)
        return None if value is None else int(value)

    ids = [slice_id(d) for d in devices]
    if any(i is None for i in ids) or len(set(ids)) == 1:
        # No topology info (or single-slice): carve n_slices contiguous
        # groups — the CPU-mesh test tier's path.
        if n_slices is None:
            raise ValueError(
                "devices expose no slice topology; pass n_slices explicitly"
            )
        if len(devices) % n_slices:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_slices} slices"
            )
        per_slice = len(devices) // n_slices
        groups = [
            devices[i * per_slice:(i + 1) * per_slice]
            for i in range(n_slices)
        ]
    else:
        keys = sorted(set(ids))
        groups = [[d for d, i in zip(devices, ids) if i == k] for k in keys]
        if n_slices is not None and len(groups) != n_slices:
            raise ValueError(
                f"topology shows {len(groups)} slices, caller asked {n_slices}"
            )
        if len({len(g) for g in groups}) != 1:
            raise ValueError(
                f"unequal slice sizes {[len(g) for g in groups]}"
            )

    sizes = plan.sizes
    if dcn_axis not in sizes:
        raise ValueError(f"dcn_axis must be one of {AXES}, got {dcn_axis!r}")
    if sizes[dcn_axis] != len(groups):
        raise ValueError(
            f"dcn axis {dcn_axis!r}={sizes[dcn_axis]} must equal the slice "
            f"count {len(groups)}"
        )
    per_slice_total = plan.total() // len(groups)
    if per_slice_total > len(groups[0]):
        raise ValueError(
            f"plan needs {per_slice_total} devices per slice, "
            f"slices have {len(groups[0])}"
        )

    # Lay devices out slice-major on the DCN axis: reshape each slice's
    # devices over the ICI axes, then stack slices along dcn_axis.
    ici_shape = [sizes[a] if a != dcn_axis else 1 for a in AXES]
    stacked = np.stack(
        [
            np.array(g[:per_slice_total]).reshape(ici_shape)
            for g in groups
        ],
        axis=AXES.index(dcn_axis),
    ).reshape([sizes[a] for a in AXES])
    return Mesh(stacked, AXES)


def auto_mesh(
    n_devices: int | None = None,
    *,
    tensor: int = 1,
    seq: int = 1,
    fsdp: int | None = None,
    devices=None,
):
    """Pick a sensible plan for ``n_devices`` and build the mesh.

    Model-parallel sizes (``tensor``, ``seq``) are explicit choices; the
    remaining factor goes to ``data``, unless an explicit ``fsdp`` size
    carves parameter-sharded data parallelism out of it.  Default —
    everything on ``data`` — matches the MNIST data-parallel BASELINE
    config.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    n = n_devices if n_devices is not None else len(devices)
    devices = devices[:n]
    if n % (tensor * seq) != 0:
        raise ValueError(f"{n} devices not divisible by tensor*seq={tensor * seq}")
    rest = n // (tensor * seq)
    if fsdp is None:
        data, fsdp_size = rest, 1
    else:
        if rest % fsdp != 0:
            raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
        data, fsdp_size = rest // fsdp, fsdp
    plan = MeshPlan(data=data, fsdp=fsdp_size, tensor=tensor, seq=seq)
    return make_mesh(plan, devices)
