"""Device-mesh construction.

Axis convention (order matters: outer axes map to DCN/slower links first,
inner axes to ICI, per the standard TPU scaling recipe):

* ``data``   — pure data parallelism (gradients psum'd)
* ``fsdp``   — data parallelism with parameter sharding (weights gathered
  just-in-time); batch is sharded over ``data × fsdp``
* ``tensor`` — Megatron-style tensor parallelism inside layers
* ``seq``    — sequence/context parallelism (ring attention)
* ``pipe``   — pipeline parallelism (GPipe microbatch schedule, pipeline.py)

A dimension of 1 erases the axis's cost without changing program structure,
so one train-step definition serves every topology from v5e-1 to multi-host
pods.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

AXES = ("data", "fsdp", "tensor", "seq", "pipe")


@dataclass(frozen=True)
class MeshPlan:
    """A named factorisation of the device count over the standard axes."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    pipe: int = 1

    @property
    def sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "tensor": self.tensor,
            "seq": self.seq,
            "pipe": self.pipe,
        }

    def total(self) -> int:
        return self.data * self.fsdp * self.tensor * self.seq * self.pipe


def make_mesh(plan: MeshPlan, devices=None):
    """Build a ``jax.sharding.Mesh`` laid out per ``plan``.

    Device order follows ``jax.devices()`` (XLA already orders a slice so
    that adjacent logical ids are ICI neighbours); the *innermost* mesh axes
    therefore get the tightest links — tensor/seq collectives ride ICI.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if plan.total() > len(devices):
        raise ValueError(
            f"mesh plan {plan.sizes} needs {plan.total()} devices, got {len(devices)}"
        )
    array = np.array(devices[: plan.total()]).reshape(
        plan.data, plan.fsdp, plan.tensor, plan.seq, plan.pipe
    )
    return Mesh(array, AXES)


def auto_mesh(
    n_devices: int | None = None,
    *,
    tensor: int = 1,
    seq: int = 1,
    fsdp: int | None = None,
    devices=None,
):
    """Pick a sensible plan for ``n_devices`` and build the mesh.

    Model-parallel sizes (``tensor``, ``seq``) are explicit choices; the
    remaining factor goes to ``data``, unless an explicit ``fsdp`` size
    carves parameter-sharded data parallelism out of it.  Default —
    everything on ``data`` — matches the MNIST data-parallel BASELINE
    config.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    n = n_devices if n_devices is not None else len(devices)
    devices = devices[:n]
    if n % (tensor * seq) != 0:
        raise ValueError(f"{n} devices not divisible by tensor*seq={tensor * seq}")
    rest = n // (tensor * seq)
    if fsdp is None:
        data, fsdp_size = rest, 1
    else:
        if rest % fsdp != 0:
            raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
        data, fsdp_size = rest // fsdp, fsdp
    plan = MeshPlan(data=data, fsdp=fsdp_size, tensor=tensor, seq=seq)
    return make_mesh(plan, devices)
