"""Collective wrappers for use inside ``shard_map`` regions.

Thin, named-axis-explicit wrappers over the XLA collective primitives (the
data plane the reference entirely lacks — its inter-node communication is
SCP file copies, ``covalent_ssh_plugin/ssh.py:360-361,451``).  Centralising
them keeps axis-name plumbing in one place and gives the simulated-mesh test
tier a single surface to pin down semantics.
"""

from __future__ import annotations

from jax import lax


def psum(x, axis_name: str):
    """Sum across the named mesh axis (rides ICI within a slice)."""
    return lax.psum(x, axis_name)


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` from every member of the mesh axis."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, *, axis: int = 0):
    """Sum-reduce then scatter shards along ``axis`` (ZeRO gradient path)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name: str, *, split_axis: int, concat_axis: int):
    """Transpose shard ownership — the Ulysses-style sequence<->head swap."""
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


def ring_permute(x, axis_name: str, *, shift: int = 1):
    """Rotate shards around the mesh-axis ring (ring attention's K/V hop).

    ``shift=+1`` sends to the next index; on a TPU torus neighbouring
    logical ids are physical ICI neighbours, so each hop is one link.
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return lax.axis_size(axis_name)
