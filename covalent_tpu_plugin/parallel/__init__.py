"""Parallelism layer: device meshes, logical shardings, collectives.

The reference has **no** parallelism components (SURVEY §2.4 — exhaustively
verified: no DP/TP/PP/SP/EP, no collectives; its only distributed dimension
is task-level fan-out over SSH).  This subpackage is the TPU-native
capability the north star adds: electrons scale *within* a task via
``jax.sharding`` meshes + pjit/shard_map, with XLA emitting the ICI/DCN
collectives — never hand-written NCCL-style calls.
"""

# Lazy (PEP 562) re-exports: mesh/sharding/collectives import jax at module
# level (seconds), which the dispatcher control plane — which imports this
# package only for `coordinator_spec` — must not pay.
import importlib

_EXPORTS = {
    "psum": ".collectives",
    "all_gather": ".collectives",
    "all_to_all": ".collectives",
    "reduce_scatter": ".collectives",
    "ring_permute": ".collectives",
    "coordinator_spec": ".distributed",
    "process_info": ".distributed",
    "MeshPlan": ".mesh",
    "auto_mesh": ".mesh",
    "make_mesh": ".mesh",
    "make_hybrid_mesh": ".mesh",
    "DEFAULT_RULES": ".sharding",
    "batch_sharding": ".sharding",
    "logical_sharding": ".sharding",
    "param_shardings": ".sharding",
    "replicated": ".sharding",
    "shard_batch": ".sharding",
    "shard_batch_per_process": ".sharding",
    "process_local_slice": ".sharding",
    "pipelined": ".pipeline",
    "pipeline_apply": ".pipeline",
    "pipeline_stages": ".pipeline",
}


def __getattr__(name):
    if name in _EXPORTS:
        module = importlib.import_module(_EXPORTS[name], __name__)
        value = getattr(module, name)
        globals()[name] = value  # cache: subsequent lookups skip __getattr__
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


__all__ = [
    "MeshPlan",
    "auto_mesh",
    "make_mesh",
    "make_hybrid_mesh",
    "DEFAULT_RULES",
    "logical_sharding",
    "param_shardings",
    "batch_sharding",
    "shard_batch",
    "shard_batch_per_process",
    "process_local_slice",
    "pipelined",
    "pipeline_apply",
    "pipeline_stages",
    "replicated",
    "psum",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "ring_permute",
    "process_info",
    "coordinator_spec",
]
