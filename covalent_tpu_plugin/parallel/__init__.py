"""Parallelism layer: device meshes, logical shardings, collectives.

The reference has **no** parallelism components (SURVEY §2.4 — exhaustively
verified: no DP/TP/PP/SP/EP, no collectives; its only distributed dimension
is task-level fan-out over SSH).  This subpackage is the TPU-native
capability the north star adds: electrons scale *within* a task via
``jax.sharding`` meshes + pjit/shard_map, with XLA emitting the ICI/DCN
collectives — never hand-written NCCL-style calls.
"""

from .collectives import (
    all_gather,
    all_to_all,
    psum,
    reduce_scatter,
    ring_permute,
)
from .distributed import coordinator_spec, process_info
from .mesh import MeshPlan, auto_mesh, make_mesh
from .sharding import (
    DEFAULT_RULES,
    batch_sharding,
    logical_sharding,
    param_shardings,
    replicated,
    shard_batch,
)

__all__ = [
    "MeshPlan",
    "auto_mesh",
    "make_mesh",
    "DEFAULT_RULES",
    "logical_sharding",
    "param_shardings",
    "batch_sharding",
    "shard_batch",
    "replicated",
    "psum",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "ring_permute",
    "process_info",
    "coordinator_spec",
]
