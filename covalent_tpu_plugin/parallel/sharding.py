"""Logical-axis sharding rules and helpers.

Models annotate parameters with *logical* axis names (``"embed"``,
``"heads"``, ...); these rules map them onto the physical mesh axes from
:mod:`.mesh`.  XLA then inserts the all-gathers/psums/reduce-scatters — the
framework never writes a collective for the forward/backward path (the
scaling-book recipe: pick a mesh, annotate shardings, let XLA compile).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: logical axis -> mesh axis (or None = replicated).  t5x/Megatron-flavored:
#: activation batch over the data axes, attention heads + MLP hidden +
#: vocab over tensor, embed over fsdp (ZeRO-style parameter sharding),
#: activation sequence over seq (context parallelism).
DEFAULT_RULES: tuple[tuple[str, Any], ...] = (
    ("batch", ("data", "fsdp")),
    ("seq", "seq"),
    ("embed", "fsdp"),
    ("heads", "tensor"),
    # GQA kv projections: replicated across tensor shards — n_kv_heads is
    # typically smaller than the tensor axis (and kv weights are tiny), so
    # sharding them like "heads" would demand impossible divisibility.
    ("kv_heads", None),
    ("kv", None),
    # MoE: experts shard over tensor (expert parallelism — XLA inserts the
    # all-to-alls from these shardings); the per-expert hidden dim must
    # then stay unsharded, hence a distinct logical name from "mlp".
    ("expert", "tensor"),
    ("expert_mlp", None),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("layers", None),
)


def _mesh_axes_for(logical_name: str | None, rules) -> Any:
    if logical_name is None:
        return None
    for name, mesh_axes in rules:
        if name == logical_name:
            return mesh_axes
    return None


def logical_spec(logical_axes: tuple[str | None, ...], rules=DEFAULT_RULES) -> P:
    """Translate a tuple of logical axis names into a PartitionSpec."""
    return P(*(_mesh_axes_for(name, rules) for name in logical_axes))


def logical_sharding(
    mesh: Mesh, logical_axes: tuple[str | None, ...], rules=DEFAULT_RULES
) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, rules))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, rules=DEFAULT_RULES) -> NamedSharding:
    """Sharding for a leading batch dimension (data×fsdp)."""
    return logical_sharding(mesh, ("batch",), rules)


def shard_batch(batch: Any, mesh: Mesh, rules=DEFAULT_RULES) -> Any:
    """Place a host batch pytree onto the mesh, sharded on dim 0.

    Works for any leaf rank: dim 0 is the batch dim, the rest replicated.
    """

    def place(x):
        x = jax.numpy.asarray(x)
        if x.ndim == 0:  # scalars (step counters, loss weights) replicate
            return jax.device_put(x, replicated(mesh))
        spec = logical_spec(("batch",) + (None,) * (x.ndim - 1), rules)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, batch)


def shard_batch_per_process(
    local_batch: Any, mesh: Mesh, rules=DEFAULT_RULES
) -> Any:
    """Multi-host batch feeding: each process supplies only ITS slice.

    ``shard_batch`` device_puts a host-global array, which requires every
    process to hold the whole batch; on a pod each host instead reads just
    its own shard of the input stream and this helper assembles the global
    array from the per-process pieces
    (``jax.make_array_from_process_local_data``).  Leaves are sharded on
    dim 0 over the data axes; scalars replicate (every process must pass
    the same value).  Single-process meshes degenerate to ``shard_batch``
    semantics.
    """

    def place(x):
        x = np.asarray(x)
        if x.ndim == 0:
            sharding = replicated(mesh)
        else:
            spec = logical_spec(("batch",) + (None,) * (x.ndim - 1), rules)
            sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(place, local_batch)


def process_local_slice(batch: Any, axis: int = 0) -> Any:
    """This process's contiguous shard of a host-global batch (dim ``axis``).

    The slicing contract matching ``shard_batch_per_process``: process ``i``
    of ``N`` owns rows ``[i*B/N, (i+1)*B/N)``.  Useful when a data source
    yields global batches but each pod worker should feed only its share.
    """
    index = jax.process_index()
    count = jax.process_count()

    def cut(x):
        x = np.asarray(x)
        if x.ndim == 0:
            return x
        if x.shape[axis] % count:
            raise ValueError(
                f"batch dim {x.shape[axis]} not divisible by "
                f"process count {count}"
            )
        span = x.shape[axis] // count
        slicer = [slice(None)] * x.ndim
        slicer[axis] = slice(index * span, (index + 1) * span)
        return x[tuple(slicer)]

    return jax.tree_util.tree_map(cut, batch)


def param_shardings(params: Any, mesh: Mesh, rules=DEFAULT_RULES) -> Any:
    """NamedShardings for a pytree of (possibly boxed) parameters.

    Leaves carrying flax logical-axis metadata (``nn.Partitioned`` via
    ``nn.with_partitioning``) shard per the rules; plain leaves replicate.
    Accepts either real params or ``jax.eval_shape`` abstractions.
    """
    import flax.linen as nn

    def to_sharding(leaf):
        names = getattr(leaf, "names", None)
        if names is not None:
            return logical_sharding(mesh, tuple(names), rules)
        return replicated(mesh)

    return jax.tree_util.tree_map(
        to_sharding,
        params,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )


def unbox(params: Any) -> Any:
    """Strip flax Partitioned boxes, returning raw arrays."""
    import flax.linen as nn

    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, nn.Partitioned) else x,
        params,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )
