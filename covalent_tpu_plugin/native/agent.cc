// Resident worker agent: event-driven task lifecycle over one channel.
//
// The stateless-files protocol (reference: covalent_ssh_plugin/ssh.py:363-432,
// one `conn.run` to submit plus a poll loop of `test -f` round-trips) costs a
// control-plane round-trip per status probe.  This agent replaces that with a
// single resident process per worker speaking newline-delimited JSON on
// stdin/stdout: the executor writes one `run` command and *completion is
// pushed* as an `exit` event the instant SIGCHLD fires — zero poll traffic,
// sub-millisecond task turnaround on the control plane.
//
// Protocol (one JSON object per line):
//   -> {"cmd":"ping"}
//   <- {"event":"pong"}
//   -> {"cmd":"run","id":"<op>","argv":["python3","harness.py","spec.json"],
//       "cwd":"/path","env":{"K":"V"},"log":"/path/log.txt"}
//   <- {"event":"started","id":"<op>","pid":1234}
//   <- {"event":"exit","id":"<op>","code":0,"signal":0}        (pushed)
//   -> {"cmd":"kill","id":"<op>","sig":15}
//   <- {"event":"killed","id":"<op>"}   (exit event still follows from reaper)
//   -> {"cmd":"watch","id":"<op>","path":"/path/telemetry.jsonl"}
//   <- {"event":"watching","id":"<op>"}
//   <- {"event":"telemetry","id":"<op>","data":{...}}     (per line, pushed)
//   -> {"cmd":"unwatch","id":"<op>"}
//   <- {"event":"unwatched","id":"<op>"}
//   -> {"cmd":"register_fn","digest":"<sha256>","path":"/cas/<sha256>.pkl",
//       "runner":["python3","/cache/covalent_tpu_harness.py","--rpc-child"]}
//   <- {"event":"registered","digest":"<sha256>"}
//   <- {"event":"register_error","digest":"...","code":"digest_mismatch"|
//       "missing","message":"..."}
//   -> {"cmd":"invoke","id":"<op>","digest":"<sha256>","spec":{...},
//       "args":"<b64>"}
//   <- {"event":"started","id":"<op>", ...}      (emitted by the runner)
//   <- {"event":"result","id":"<op>","ok":true,"data":"<b64>"}  (runner)
//   -> {"cmd":"serve_open","id":"<sid>","digest":"<sha256>","path":"...",
//       "runner":["python3","/cache/covalent_tpu_harness.py",
//       "--serve-child"],"options":{...},"spec":{...}}
//   <- {"event":"serve_opened","id":"<sid>","slots":N}       (runner)
//   -> {"cmd":"serve_request","id":"<sid>","rid":"<rid>",...} (forwarded)
//   <- {"event":"telemetry","id":"<sid>","data":{...}}        (runner)
//   -> {"cmd":"serve_close","id":"<sid>"}                     (forwarded)
//   <- {"event":"serve_closed","id":"<sid>","served":N}       (runner)
//   -> {"cmd":"profile_start","id":"<pid>","dir":"...","sid":"<sid>"}
//   <- {"event":"profile_started","id":"<pid>","pid":123}     (runner)
//   -> {"cmd":"profile_stop","id":"<pid>","artifact_dir":"..."}
//   <- {"event":"profile_stopped","id":"<pid>","path":"...",
//       "digest":"<sha256>","bytes":N}                        (runner)
//   <- {"event":"profile_error","id":"<pid>","code":"...",...}
//   -> {"cmd":"epoch","epoch":N}
//   <- {"event":"epoch_ok","epoch":N} | {"event":"error","id":"",
//       "code":"stale_epoch",...}
//   -> {"cmd":"serve_resume","id":"<sid>","rid":"<rid>","from":N} (forwarded)
//   -> {"cmd":"serve_inventory"}
//   <- {"event":"serve_inventory","pid":N,"epoch":N,"sessions":[...]}
//   -> {"cmd":"task_inventory"}
//   <- {"event":"task_inventory","pid":N,"epoch":N,"tasks":[...]}
//   -> {"cmd":"shutdown"}
//   <- {"event":"bye"}
//   <- {"event":"error","message":"..."}  (malformed input, unknown id, ...)
//
// RPC execute-by-digest: register_fn verifies the CAS artifact's sha256
// IN THIS PROCESS before accepting the registration (a torn or stale
// artifact is refused with code digest_mismatch, which the dispatcher
// classifies permanent), and remembers digest -> {path, runner argv}.
// invoke forks the registered runner (the Python harness in --rpc-child
// mode), pipes the invoke command — args inline, nothing staged to disk —
// to its stdin, and streams the runner's started/telemetry/result events
// back over this channel verbatim.  The resident *interpreter* lives in
// the harness pool server; this native path keeps the protocol uniform
// for workers running only the C++ agent (one interpreter start per
// invocation instead of a warm loop — the dispatcher prefers the pool
// runtime for RPC dispatch when both are available).
//
// The watch side-band tails a task's worker-local JSONL telemetry file
// (heartbeats, worker events) back over the channel in near-real-time.  A
// (re-)watch always starts at offset 0 so lines buffered while the channel
// was down are flushed on reconnect; the dispatcher dedups by `seq`.
//
// Children run in their own sessions (setsid + exec), so they survive an
// agent/channel drop exactly like the fallback path's `nohup` launch — the
// executor can always resume supervision by pid-file polling.  stdout is
// line-buffered JSON only; child output goes to the per-task log file, same
// contract as the polling path.
//
// Single file, C++17, no dependencies beyond POSIX; built on the worker by
// the executor's preflight (g++ -O2 -std=c++17 -o agent agent.cc).

#include <cerrno>
#include <cctype>
#include <csignal>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <poll.h>
#include <string>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

// ---------------------------------------------------------------------------
// Minimal JSON: just the subset this protocol uses (obj/arr/string/int/bool).
// ---------------------------------------------------------------------------

struct Json {
  enum Type { Null, Bool, Int, Str, Arr, Obj } type = Null;
  bool b = false;
  long long i = 0;
  std::string s;
  std::vector<Json> arr;
  std::vector<std::pair<std::string, Json>> obj;

  const Json* get(const std::string& key) const {
    if (type != Obj) return nullptr;
    for (const auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
};

struct JsonParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JsonParser(const std::string& text)
      : p(text.data()), end(text.data() + text.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r' || *p == '\n')) p++;
  }
  bool fail() { ok = false; return false; }

  bool parse_value(Json& out) {
    skip_ws();
    if (p >= end) return fail();
    switch (*p) {
      case '{': return parse_obj(out);
      case '[': return parse_arr(out);
      case '"': out.type = Json::Str; return parse_string(out.s);
      case 't':
        if (end - p >= 4 && !strncmp(p, "true", 4)) {
          out.type = Json::Bool; out.b = true; p += 4; return true;
        }
        return fail();
      case 'f':
        if (end - p >= 5 && !strncmp(p, "false", 5)) {
          out.type = Json::Bool; out.b = false; p += 5; return true;
        }
        return fail();
      case 'n':
        if (end - p >= 4 && !strncmp(p, "null", 4)) {
          out.type = Json::Null; p += 4; return true;
        }
        return fail();
      default: return parse_int(out);
    }
  }

  bool parse_int(Json& out) {
    char* num_end = nullptr;
    errno = 0;
    long long v = strtoll(p, &num_end, 10);
    if (num_end == p || errno == ERANGE) return fail();
    // Skip a fractional/exponent tail (we only ever need integers).
    const char* q = num_end;
    while (q < end && (*q == '.' || *q == 'e' || *q == 'E' || *q == '+' ||
                       *q == '-' || isdigit((unsigned char)*q)))
      q++;
    out.type = Json::Int;
    out.i = v;
    p = q;
    return true;
  }

  bool parse_hex4(unsigned& out) {
    if (end - p < 4) return fail();
    out = 0;
    for (int k = 0; k < 4; k++) {
      char c = p[k];
      out <<= 4;
      if (c >= '0' && c <= '9') out |= (unsigned)(c - '0');
      else if (c >= 'a' && c <= 'f') out |= (unsigned)(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') out |= (unsigned)(c - 'A' + 10);
      else return fail();
    }
    p += 4;
    return true;
  }

  void append_utf8(std::string& s, unsigned cp) {
    if (cp < 0x80) {
      s += (char)cp;
    } else if (cp < 0x800) {
      s += (char)(0xC0 | (cp >> 6));
      s += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      s += (char)(0xE0 | (cp >> 12));
      s += (char)(0x80 | ((cp >> 6) & 0x3F));
      s += (char)(0x80 | (cp & 0x3F));
    } else {
      s += (char)(0xF0 | (cp >> 18));
      s += (char)(0x80 | ((cp >> 12) & 0x3F));
      s += (char)(0x80 | ((cp >> 6) & 0x3F));
      s += (char)(0x80 | (cp & 0x3F));
    }
  }

  bool parse_string(std::string& out) {
    if (*p != '"') return fail();
    p++;
    out.clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        p++;
        if (p >= end) return fail();
        switch (*p) {
          case '"': out += '"'; p++; break;
          case '\\': out += '\\'; p++; break;
          case '/': out += '/'; p++; break;
          case 'b': out += '\b'; p++; break;
          case 'f': out += '\f'; p++; break;
          case 'n': out += '\n'; p++; break;
          case 'r': out += '\r'; p++; break;
          case 't': out += '\t'; p++; break;
          case 'u': {
            p++;
            unsigned cp;
            if (!parse_hex4(cp)) return false;
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
                p[1] == 'u') {
              p += 2;
              unsigned lo;
              if (!parse_hex4(lo)) return false;
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            append_utf8(out, cp);
            break;
          }
          default: return fail();
        }
      } else {
        out += *p++;
      }
    }
    if (p >= end) return fail();
    p++;  // closing quote
    return true;
  }

  bool parse_arr(Json& out) {
    out.type = Json::Arr;
    p++;  // '['
    skip_ws();
    if (p < end && *p == ']') { p++; return true; }
    while (true) {
      Json elem;
      if (!parse_value(elem)) return false;
      out.arr.push_back(std::move(elem));
      skip_ws();
      if (p >= end) return fail();
      if (*p == ',') { p++; continue; }
      if (*p == ']') { p++; return true; }
      return fail();
    }
  }

  bool parse_obj(Json& out) {
    out.type = Json::Obj;
    p++;  // '{'
    skip_ws();
    if (p < end && *p == '}') { p++; return true; }
    while (true) {
      skip_ws();
      std::string key;
      if (p >= end || *p != '"' || !parse_string(key)) return fail();
      skip_ws();
      if (p >= end || *p != ':') return fail();
      p++;
      Json val;
      if (!parse_value(val)) return false;
      out.obj.emplace_back(std::move(key), std::move(val));
      skip_ws();
      if (p >= end) return fail();
      if (*p == ',') { p++; continue; }
      if (*p == '}') { p++; return true; }
      return fail();
    }
  }
};

static bool parse_json(const std::string& line, Json& out) {
  JsonParser parser(line);
  if (!parser.parse_value(out)) return false;
  parser.skip_ws();
  return parser.ok && parser.p == parser.end;
}

static std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  return out;
}

// ---------------------------------------------------------------------------
// Event emission: stdout is protocol-only, one JSON object per line.
// ---------------------------------------------------------------------------

static void emit(const std::string& line) {
  fputs(line.c_str(), stdout);
  fputc('\n', stdout);
  fflush(stdout);
}

static void emit_error(const std::string& message, const std::string& id = "") {
  std::string line = "{\"event\":\"error\",\"message\":\"" + json_escape(message) + "\"";
  if (!id.empty()) line += ",\"id\":\"" + json_escape(id) + "\"";
  emit(line + "}");
}

// ---------------------------------------------------------------------------
// Binary frame protocol (negotiated; JSONL stays the fallback).
//
// Wire layout (mirrors harness.py and transport/frames.py — the three are
// kept byte-compatible by tests/test_frames.py):
//
//   magic(2)=C5 F7  version(1)  verb(1)  flags(1)  hlen(4 BE)  blen(4 BE)
//   header: UTF-8 JSON object   body: raw bytes
//
// This agent holds no Python runtime, so it never encodes or decodes frame
// BODIES: inbound invoke/serve frames forward VERBATIM into the runner
// children (which parse frames natively), and runner output frames —
// binary results, coalesced token batches — pass through the stream pump
// verbatim upstream.  The agent itself only reads frame HEADERS (plain
// JSON) to route by session/registration, plus emits header-only frames
// for its own watch side-band batches.  Negotiation rides the ready
// banner: `"frames":1` advertised, client answers `{"cmd":"frames"}`, ack
// flips the mode; the COVALENT_TPU_AGENT_FRAMES=0 env kill switch keeps
// the agent JSONL-only.  No "codecs" are advertised, so clients never
// compress bodies toward a native agent.
// ---------------------------------------------------------------------------

static const unsigned char kFrameMagic0 = 0xC5;
static const unsigned char kFrameMagic1 = 0xF7;
static const unsigned char kFrameVersion = 1;
static const size_t kFrameHeaderLen = 13;
static const uint64_t kFrameMaxHeader = 16ull * 1024 * 1024;
static const uint64_t kFrameMaxBody = 512ull * 1024 * 1024;
static const uint8_t kVerbTelemetry = 3;

static bool g_frames = false;

static bool frames_env_enabled() {
  const char* env = getenv("COVALENT_TPU_AGENT_FRAMES");
  if (!env) return true;
  std::string v(env);
  return !(v == "0" || v == "off" || v == "false" || v == "no");
}

static uint32_t read_be32(const char* p) {
  return ((uint32_t)(unsigned char)p[0] << 24) |
         ((uint32_t)(unsigned char)p[1] << 16) |
         ((uint32_t)(unsigned char)p[2] << 8) |
         (uint32_t)(unsigned char)p[3];
}

static void emit_raw(const std::string& bytes) {
  fwrite(bytes.data(), 1, bytes.size(), stdout);
  fflush(stdout);
}

static void emit_frame(uint8_t verb, const std::string& header,
                       const std::string& body) {
  unsigned char h[kFrameHeaderLen];
  h[0] = kFrameMagic0; h[1] = kFrameMagic1;
  h[2] = kFrameVersion; h[3] = verb; h[4] = 0;
  uint32_t hl = (uint32_t)header.size(), bl = (uint32_t)body.size();
  h[5] = (unsigned char)(hl >> 24); h[6] = (unsigned char)(hl >> 16);
  h[7] = (unsigned char)(hl >> 8);  h[8] = (unsigned char)hl;
  h[9] = (unsigned char)(bl >> 24); h[10] = (unsigned char)(bl >> 16);
  h[11] = (unsigned char)(bl >> 8); h[12] = (unsigned char)bl;
  fwrite(h, 1, sizeof h, stdout);
  fwrite(header.data(), 1, header.size(), stdout);
  if (!body.empty()) fwrite(body.data(), 1, body.size(), stdout);
  fflush(stdout);
}

// After a bad magic/version/length the stream position is untrusted; the
// next newline is the only honest resync point (valid traffic is
// self-delimiting frames or newline-terminated JSON).
static void frame_resync(std::string& buffer) {
  size_t nl = buffer.find('\n', 1);
  if (nl == std::string::npos) buffer.clear();
  else buffer.erase(0, nl + 1);
}

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4): register_fn digest verification, no dependencies.
// ---------------------------------------------------------------------------

struct Sha256 {
  uint32_t state[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                       0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint64_t bitlen = 0;
  unsigned char block[64];
  size_t blocklen = 0;

  static uint32_t rotr(uint32_t x, unsigned n) {
    return (x >> n) | (x << (32 - n));
  }

  void transform(const unsigned char* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t m[64];
    for (int i = 0; i < 16; i++)
      m[i] = (uint32_t)p[i * 4] << 24 | (uint32_t)p[i * 4 + 1] << 16 |
             (uint32_t)p[i * 4 + 2] << 8 | (uint32_t)p[i * 4 + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(m[i - 15], 7) ^ rotr(m[i - 15], 18) ^ (m[i - 15] >> 3);
      uint32_t s1 = rotr(m[i - 2], 17) ^ rotr(m[i - 2], 19) ^ (m[i - 2] >> 10);
      m[i] = m[i - 16] + s0 + m[i - 7] + s1;
    }
    uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
    uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
    for (int i = 0; i < 64; i++) {
      uint32_t s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = h + s1 + ch + k[i] + m[i];
      uint32_t s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      h = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    state[0] += a; state[1] += b; state[2] += c; state[3] += d;
    state[4] += e; state[5] += f; state[6] += g; state[7] += h;
  }

  void update(const unsigned char* p, size_t len) {
    bitlen += (uint64_t)len * 8;
    while (len > 0) {
      size_t take = 64 - blocklen;
      if (take > len) take = len;
      memcpy(block + blocklen, p, take);
      blocklen += take;
      p += take;
      len -= take;
      if (blocklen == 64) {
        transform(block);
        blocklen = 0;
      }
    }
  }

  std::string hex_digest() {
    block[blocklen++] = 0x80;
    if (blocklen > 56) {
      while (blocklen < 64) block[blocklen++] = 0;
      transform(block);
      blocklen = 0;
    }
    while (blocklen < 56) block[blocklen++] = 0;
    for (int i = 7; i >= 0; i--) block[blocklen++] = (unsigned char)(bitlen >> (i * 8));
    transform(block);
    static const char* hexd = "0123456789abcdef";
    std::string out;
    out.reserve(64);
    for (uint32_t word : state) {
      for (int shift = 28; shift >= 0; shift -= 4)
        out += hexd[(word >> shift) & 0xF];
    }
    return out;
  }
};

static bool sha256_file(const std::string& path, std::string& hex_out) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  Sha256 sha;
  char chunk[65536];
  ssize_t n;
  while ((n = read(fd, chunk, sizeof chunk)) > 0)
    sha.update((const unsigned char*)chunk, (size_t)n);
  bool ok = (n == 0);
  close(fd);
  if (!ok) return false;
  hex_out = sha.hex_digest();
  return true;
}

// ---------------------------------------------------------------------------
// Child management.
// ---------------------------------------------------------------------------

static int g_sigchld_pipe[2] = {-1, -1};

static void on_sigchld(int) {
  // Self-pipe trick: make SIGCHLD poll()-able without signalfd.
  ssize_t ignored = write(g_sigchld_pipe[1], "x", 1);
  (void)ignored;
}

struct Task {
  pid_t pid;
  std::string id;
};

static std::map<pid_t, Task> g_tasks;

static void spawn(const Json& cmd) {
  const Json* id_field = cmd.get("id");
  const Json* argv_field = cmd.get("argv");
  if (!id_field || id_field->type != Json::Str || !argv_field ||
      argv_field->type != Json::Arr || argv_field->arr.empty()) {
    emit_error("run requires string id and non-empty argv array");
    return;
  }
  const std::string& id = id_field->s;
  const Json* cwd = cmd.get("cwd");
  const Json* env = cmd.get("env");
  const Json* log = cmd.get("log");

  pid_t pid = fork();
  if (pid < 0) {
    emit_error(std::string("fork failed: ") + strerror(errno), id);
    return;
  }
  if (pid == 0) {
    // Child: own session so it survives an agent/channel drop, exactly like
    // the polling path's nohup+setsid launch.
    setsid();
    if (cwd && cwd->type == Json::Str && !cwd->s.empty()) {
      if (chdir(cwd->s.c_str()) != 0) _exit(127);
    }
    if (env && env->type == Json::Obj) {
      for (const auto& kv : env->obj)
        if (kv.second.type == Json::Str)
          setenv(kv.first.c_str(), kv.second.s.c_str(), 1);
    }
    int log_fd = -1;
    if (log && log->type == Json::Str && !log->s.empty()) {
      log_fd = open(log->s.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
    }
    if (log_fd < 0) log_fd = open("/dev/null", O_WRONLY);
    int devnull = open("/dev/null", O_RDONLY);
    if (devnull >= 0) dup2(devnull, 0);
    if (log_fd >= 0) {
      dup2(log_fd, 1);
      dup2(log_fd, 2);
    }
    for (int fd = 3; fd < 256; fd++) close(fd);

    std::vector<char*> argv;
    argv.reserve(argv_field->arr.size() + 1);
    for (const auto& a : argv_field->arr)
      if (a.type == Json::Str) argv.push_back(const_cast<char*>(a.s.c_str()));
    argv.push_back(nullptr);
    execvp(argv[0], argv.data());
    _exit(127);
  }
  g_tasks[pid] = Task{pid, id};
  emit("{\"event\":\"started\",\"id\":\"" + json_escape(id) +
       "\",\"pid\":" + std::to_string((long long)pid) + "}");
}

static void kill_task(const Json& cmd) {
  const Json* id_field = cmd.get("id");
  if (!id_field || id_field->type != Json::Str) {
    emit_error("kill requires string id");
    return;
  }
  const Json* sig_field = cmd.get("sig");
  int sig = (sig_field && sig_field->type == Json::Int) ? (int)sig_field->i : SIGTERM;
  for (const auto& kv : g_tasks) {
    if (kv.second.id == id_field->s) {
      // Negative pid: the whole session/process group the child leads.
      kill(-kv.second.pid, sig);
      kill(kv.second.pid, sig);
      emit("{\"event\":\"killed\",\"id\":\"" + json_escape(id_field->s) + "\"}");
      return;
    }
  }
  emit_error("unknown task id", id_field->s);
}

// ---------------------------------------------------------------------------
// RPC execute-by-digest: registry + runner-forked invocations.
// ---------------------------------------------------------------------------

struct Registration {
  std::string path;                 // CAS artifact holding the function
  std::vector<std::string> runner;  // argv forked per invocation
};

static std::map<std::string, Registration> g_registry;

struct RpcStream {
  std::string id;
  std::string buf;
};

//: runner-stdout fd -> stream state; lines are forwarded verbatim.
static std::map<int, RpcStream> g_rpc_streams;

static void register_fn(const Json& cmd) {
  const Json* digest = cmd.get("digest");
  const Json* path = cmd.get("path");
  if (!digest || digest->type != Json::Str || !path ||
      path->type != Json::Str || path->s.empty()) {
    emit_error("register_fn requires digest and path");
    return;
  }
  Registration reg;
  reg.path = path->s;
  const Json* runner = cmd.get("runner");
  if (runner && runner->type == Json::Arr)
    for (const auto& part : runner->arr)
      if (part.type == Json::Str) reg.runner.push_back(part.s);
  std::string hex;
  if (!sha256_file(reg.path, hex)) {
    emit("{\"event\":\"register_error\",\"digest\":\"" +
         json_escape(digest->s) + "\",\"code\":\"missing\",\"message\":\"" +
         json_escape("cannot read " + reg.path) + "\"}");
    return;
  }
  if (hex != digest->s) {
    // Refused, never stored: invoking a payload whose bytes don't match
    // their content address would execute the wrong function.  The
    // dispatcher classifies this permanent (torn or stale CAS artifact).
    emit("{\"event\":\"register_error\",\"digest\":\"" +
         json_escape(digest->s) +
         "\",\"code\":\"digest_mismatch\",\"message\":\"" +
         json_escape(reg.path + " does not match its content digest") +
         "\"}");
    return;
  }
  g_registry[digest->s] = std::move(reg);
  emit("{\"event\":\"registered\",\"digest\":\"" + json_escape(digest->s) +
       "\"}");
}

// `payload` is the exact byte sequence piped to the runner child: the
// invoke line + "\n" on the JSONL path, or the raw invoke FRAME verbatim
// on the negotiated binary path (the Python runner parses both).  With
// frames negotiated, a frames-enable line precedes it so the runner's own
// result events come back framed and pass through the pump untouched.
static void invoke_task(const Json& cmd, const std::string& payload) {
  const Json* id_field = cmd.get("id");
  const Json* digest = cmd.get("digest");
  if (!id_field || id_field->type != Json::Str || !digest ||
      digest->type != Json::Str) {
    emit_error("invoke requires string id and digest");
    return;
  }
  auto it = g_registry.find(digest->s);
  if (it == g_registry.end()) {
    emit("{\"event\":\"error\",\"id\":\"" + json_escape(id_field->s) +
         "\",\"code\":\"unregistered\",\"message\":\"no registered function "
         "for digest\"}");
    return;
  }
  if (it->second.runner.empty()) {
    emit("{\"event\":\"error\",\"id\":\"" + json_escape(id_field->s) +
         "\",\"code\":\"no_runner\",\"message\":\"registration carried no "
         "runner argv\"}");
    return;
  }
  int in_pipe[2] = {-1, -1}, out_pipe[2] = {-1, -1};
  if (pipe(in_pipe) != 0 || pipe(out_pipe) != 0) {
    if (in_pipe[0] >= 0) { close(in_pipe[0]); close(in_pipe[1]); }
    emit_error(std::string("pipe failed: ") + strerror(errno), id_field->s);
    return;
  }
  pid_t pid = fork();
  if (pid < 0) {
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    emit_error(std::string("fork failed: ") + strerror(errno), id_field->s);
    return;
  }
  if (pid == 0) {
    // Runner child: own session (kill -- -pid reaches it), invoke command
    // on stdin, protocol events on stdout, stderr discarded.
    setsid();
    dup2(in_pipe[0], 0);
    dup2(out_pipe[1], 1);
    int devnull = open("/dev/null", O_WRONLY);
    if (devnull >= 0) dup2(devnull, 2);
    for (int fd = 3; fd < 256; fd++) close(fd);
    std::vector<char*> argv;
    argv.reserve(it->second.runner.size() + 1);
    for (const auto& a : it->second.runner)
      argv.push_back(const_cast<char*>(a.c_str()));
    argv.push_back(nullptr);
    execvp(argv[0], argv.data());
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);
  // Feed the invoke command — it carries the CAS path and inline args, so
  // the runner needs no disk staging — then close: exactly one command.
  std::string full = g_frames
      ? std::string("{\"cmd\":\"frames\",\"version\":1}\n") + payload
      : payload;
  size_t off = 0;
  while (off < full.size()) {
    ssize_t n = write(in_pipe[1], full.data() + off, full.size() - off);
    if (n <= 0) break;
    off += (size_t)n;
  }
  close(in_pipe[1]);
  g_tasks[pid] = Task{pid, id_field->s};
  g_rpc_streams[out_pipe[0]] = RpcStream{id_field->s, ""};
  // No `started` from here: the runner emits its own, with the pid that
  // actually executes the function.
}

// ---------------------------------------------------------------------------
// Serving sessions: a resident runner child per session, stdin held open.
//
// Unlike invoke (one command, pipe closed, child exits after one result), a
// session lives for many requests: serve_open forks the provided runner argv
// (the Python harness in --serve-child mode) with its stdin pipe KEPT OPEN,
// and every later serve_request/serve_close line for that sid is forwarded
// verbatim.  The child's stdout rides the same validated pump as RPC
// runners, so serve_opened / telemetry / serve_closed events flow back
// unchanged.  The resident *model* lives in the child; this agent only
// switches lines.
// ---------------------------------------------------------------------------

struct ServeChild {
  pid_t pid;
  int stdin_fd;
};

static std::map<std::string, ServeChild> g_serve_children;

static bool write_all(int fd, const std::string& payload) {
  size_t off = 0;
  while (off < payload.size()) {
    ssize_t n = write(fd, payload.data() + off, payload.size() - off);
    if (n <= 0) return false;
    off += (size_t)n;
  }
  return true;
}

// A serve_open refusal must arrive as serve_error (never a generic
// "error"): the client's open waiter settles only on serve_opened /
// serve_error, so anything else stalls it for the full open timeout.
static void emit_serve_error(const std::string& sid, const std::string& code,
                             const std::string& message, bool permanent) {
  emit("{\"event\":\"serve_error\",\"id\":\"" + json_escape(sid) +
       "\",\"code\":\"" + json_escape(code) + "\",\"message\":\"" +
       json_escape(message) + "\"" +
       (permanent ? ",\"permanent\":true" : "") + "}");
}

static void serve_open(const Json& cmd, const std::string& raw_line) {
  const Json* id_field = cmd.get("id");
  const Json* runner = cmd.get("runner");
  if (!id_field || id_field->type != Json::Str || !runner ||
      runner->type != Json::Arr || runner->arr.empty()) {
    emit_serve_error(
        id_field && id_field->type == Json::Str ? id_field->s : "",
        "bad_request",
        "serve_open requires string id and non-empty runner argv", true);
    return;
  }
  const std::string& sid = id_field->s;
  if (g_serve_children.count(sid)) {
    emit("{\"event\":\"serve_error\",\"id\":\"" + json_escape(sid) +
         "\",\"code\":\"duplicate\",\"message\":\"session already open\","
         "\"permanent\":true}");
    return;
  }
  int in_pipe[2] = {-1, -1}, out_pipe[2] = {-1, -1};
  if (pipe(in_pipe) != 0 || pipe(out_pipe) != 0) {
    if (in_pipe[0] >= 0) { close(in_pipe[0]); close(in_pipe[1]); }
    emit_serve_error(sid, "spawn_failed",
                     std::string("pipe failed: ") + strerror(errno), false);
    return;
  }
  pid_t pid = fork();
  if (pid < 0) {
    close(in_pipe[0]); close(in_pipe[1]);
    close(out_pipe[0]); close(out_pipe[1]);
    emit_serve_error(sid, "spawn_failed",
                     std::string("fork failed: ") + strerror(errno), false);
    return;
  }
  if (pid == 0) {
    setsid();
    dup2(in_pipe[0], 0);
    dup2(out_pipe[1], 1);
    int devnull = open("/dev/null", O_WRONLY);
    if (devnull >= 0) dup2(devnull, 2);
    for (int fd = 3; fd < 256; fd++) close(fd);
    std::vector<char*> argv;
    argv.reserve(runner->arr.size() + 1);
    for (const auto& a : runner->arr)
      if (a.type == Json::Str) argv.push_back(const_cast<char*>(a.s.c_str()));
    argv.push_back(nullptr);
    execvp(argv[0], argv.data());
    _exit(127);
  }
  close(in_pipe[0]);
  close(out_pipe[1]);
  // The serve_open line itself is the child's first command (it carries
  // the CAS path + options); the pipe stays open for the session's life.
  // With frames negotiated upstream, a frames-enable line goes first so
  // the child's token stream comes back as coalesced binary frames.
  std::string first = g_frames
      ? std::string("{\"cmd\":\"frames\",\"version\":1}\n") + raw_line + "\n"
      : raw_line + "\n";
  if (!write_all(in_pipe[1], first)) {
    // Child unreachable at birth: fail the open (transient — a fresh
    // gang can retry), close both pipe ends so the child EOFs out, and
    // register ONLY the pid (the reaper needs it) — a session entry
    // holding this closed fd would make a later serve_request write
    // into whatever descriptor the number gets reused for.
    close(in_pipe[1]);
    close(out_pipe[0]);
    g_tasks[pid] = Task{pid, sid};
    emit_serve_error(sid, "spawn_failed",
                     "serve runner rejected its open command", false);
    return;
  }
  g_tasks[pid] = Task{pid, sid};
  g_serve_children[sid] = ServeChild{pid, in_pipe[1]};
  g_rpc_streams[out_pipe[0]] = RpcStream{sid, ""};
  // serve_opened (or serve_error) comes from the runner once the model
  // factory settles — nothing synthesized here.
}

// `payload` is the exact byte sequence forwarded to the session child —
// a command line + "\n", or a raw binary frame verbatim (the --serve-child
// loop parses both off one stream).
static void serve_forward(const Json& cmd, const std::string& payload,
                          bool is_close) {
  const Json* id_field = cmd.get("id");
  const std::string sid =
      (id_field && id_field->type == Json::Str) ? id_field->s : "";
  auto it = g_serve_children.find(sid);
  if (it == g_serve_children.end()) {
    if (is_close) {
      emit("{\"event\":\"serve_error\",\"id\":\"" + json_escape(sid) +
           "\",\"code\":\"unknown_session\",\"message\":\"no open session\","
           "\"permanent\":true}");
    } else {
      // Per-request reject, streamed like the pool server's: the caller's
      // stream for this rid must fail fast, not hang.
      const Json* rid = cmd.get("rid");
      emit("{\"event\":\"telemetry\",\"id\":\"" + json_escape(sid) +
           "\",\"data\":{\"type\":\"serve.reject\",\"rid\":\"" +
           json_escape(rid && rid->type == Json::Str ? rid->s : "") +
           "\",\"code\":\"unknown_session\",\"message\":\"no open "
           "session\"}}");
    }
    return;
  }
  bool ok = write_all(it->second.stdin_fd, payload);
  if (is_close || !ok) {
    // Close (or a torn pipe): EOF the child's stdin; it drains admitted
    // lanes, emits serve_closed, and exits — the reaper cleans the maps.
    close(it->second.stdin_fd);
    g_serve_children.erase(it);
  }
}

// serve_prefill forwards like serve_request, but its waiter settles on
// serve_kv events only — an unknown session must answer with a serve_kv
// error (not a streamed serve.reject) or the dispatcher's prefill call
// sits out its whole timeout before degrading to a full prefill.
static void serve_prefill_forward(const Json& cmd,
                                  const std::string& payload) {
  const Json* id_field = cmd.get("id");
  const std::string sid =
      (id_field && id_field->type == Json::Str) ? id_field->s : "";
  auto it = g_serve_children.find(sid);
  if (it == g_serve_children.end()) {
    const Json* rid = cmd.get("rid");
    emit("{\"event\":\"serve_kv\",\"id\":\"" + json_escape(sid) +
         "\",\"rid\":\"" +
         json_escape(rid && rid->type == Json::Str ? rid->s : "") +
         "\",\"code\":\"unknown_session\",\"message\":\"no open "
         "session\"}");
    return;
  }
  if (!write_all(it->second.stdin_fd, payload)) {
    close(it->second.stdin_fd);
    g_serve_children.erase(it);
    // A torn pipe means no serve_kv will ever come from the child: the
    // waiter must fail NOW (and degrade to full prefill), not sit out
    // its whole timeout — same rationale as the unknown-session branch.
    const Json* rid = cmd.get("rid");
    emit("{\"event\":\"serve_kv\",\"id\":\"" + json_escape(sid) +
         "\",\"rid\":\"" +
         json_escape(rid && rid->type == Json::Str ? rid->s : "") +
         "\",\"code\":\"runner_exited\",\"message\":\"serve runner pipe "
         "broken\"}");
  }
}

// serve_attach / serve_detach waiters settle on serve_attached /
// serve_detached events keyed (id, adapter) — an unknown session or a
// torn pipe must answer in that shape immediately, same rationale as
// serve_prefill_forward above.
static void serve_attach_forward(const Json& cmd, const std::string& name,
                                 const std::string& payload) {
  const Json* id_field = cmd.get("id");
  const std::string sid =
      (id_field && id_field->type == Json::Str) ? id_field->s : "";
  const Json* a = cmd.get("adapter");
  const std::string adapter =
      (a && a->type == Json::Str) ? a->s : "";
  auto it = g_serve_children.find(sid);
  if (it == g_serve_children.end()) {
    emit("{\"event\":\"" + name + "ed\",\"id\":\"" + json_escape(sid) +
         "\",\"adapter\":\"" + json_escape(adapter) +
         "\",\"code\":\"unknown_session\",\"message\":\"no open session\","
         "\"permanent\":true}");
    return;
  }
  if (!write_all(it->second.stdin_fd, payload)) {
    close(it->second.stdin_fd);
    g_serve_children.erase(it);
    emit("{\"event\":\"" + name + "ed\",\"id\":\"" + json_escape(sid) +
         "\",\"adapter\":\"" + json_escape(adapter) +
         "\",\"code\":\"runner_exited\",\"message\":\"serve runner pipe "
         "broken\"}");
  }
}

// Resident-mode profiling: the native agent holds no Python/jax runtime of
// its own — the resident state worth profiling lives in its serve-child
// session runners.  profile_start/profile_stop forward verbatim into a live
// session child ("sid" pins which one; otherwise any), whose --serve-child
// loop drives jax.profiler and answers profile_started / profile_stopped /
// profile_error back over the same stream pump.  With no live session there
// is nothing to profile: refuse fast so the client's waiter doesn't sit out
// its whole timeout.  The start's target is remembered per profile id so a
// sid-less stop lands on the SAME child — begin() can change between the
// two commands (a new session sorting earlier), and routing the stop
// elsewhere would orphan an active trace in the original child forever.

//: profile id -> sid of the serve child that received its profile_start.
static std::map<std::string, std::string> g_profile_targets;

static void profile_forward(const Json& cmd, const std::string& raw_line,
                            bool is_stop) {
  const Json* id_field = cmd.get("id");
  const std::string profile_id =
      (id_field && id_field->type == Json::Str) ? id_field->s : "";
  const Json* sid_field = cmd.get("sid");
  std::string sid =
      (sid_field && sid_field->type == Json::Str) ? sid_field->s : "";
  if (sid.empty() && is_stop) {
    auto route = g_profile_targets.find(profile_id);
    if (route != g_profile_targets.end()) sid = route->second;
  }
  auto it = sid.empty() ? g_serve_children.begin()
                        : g_serve_children.find(sid);
  if (it == g_serve_children.end()) {
    g_profile_targets.erase(profile_id);
    emit("{\"event\":\"profile_error\",\"id\":\"" + json_escape(profile_id) +
         "\",\"code\":\"unavailable\",\"message\":\"no live serving session "
         "to profile\"}");
    return;
  }
  if (!write_all(it->second.stdin_fd, raw_line + "\n")) {
    close(it->second.stdin_fd);
    g_serve_children.erase(it);
    g_profile_targets.erase(profile_id);
    emit("{\"event\":\"profile_error\",\"id\":\"" + json_escape(profile_id) +
         "\",\"code\":\"unavailable\",\"message\":\"session runner pipe "
         "broken\"}");
    return;
  }
  // The route lives until the child answers terminally (profile_stopped,
  // or any profile_error except the retryable stop_failed) — erasing at
  // stop-forward time would send a RETRIED stop after a stop_failed to
  // begin()'s child instead of the one still holding the active trace.
  // Terminal cleanup happens in pump_rpc_stream; dead children reap
  // their routes in reap_serve_child.
  if (!is_stop) g_profile_targets[profile_id] = it->first;
}

static void reap_serve_child(pid_t pid) {
  for (auto it = g_serve_children.begin(); it != g_serve_children.end(); ++it) {
    if (it->second.pid == pid) {
      // Still registered at death = the child exited WITHOUT a clean
      // serve_close (exec failure before serve_opened, a crash
      // mid-session).  Announce it so a pending open waiter fails fast
      // (transient — a fresh gang can retry) instead of sitting out the
      // whole open timeout on a runner that already _exit(127)ed.
      emit_serve_error(it->first, "runner_exited",
                       "serve runner exited without closing its session",
                       false);
      close(it->second.stdin_fd);
      // Any in-flight profile routed at this child died with it.
      for (auto route = g_profile_targets.begin();
           route != g_profile_targets.end();) {
        if (route->second == it->first) route = g_profile_targets.erase(route);
        else ++route;
      }
      g_serve_children.erase(it);
      return;
    }
  }
}

static void pump_rpc_stream(int fd) {
  auto it = g_rpc_streams.find(fd);
  if (it == g_rpc_streams.end()) return;
  char chunk[65536];
  ssize_t n = read(fd, chunk, sizeof chunk);
  if (n <= 0) {
    close(fd);
    g_rpc_streams.erase(it);
    return;
  }
  RpcStream& s = it->second;
  s.buf.append(chunk, (size_t)n);
  while (!s.buf.empty()) {
    if ((unsigned char)s.buf[0] == kFrameMagic0) {
      // Runner-emitted binary frame (framed result, coalesced token
      // batch): forward VERBATIM — this agent never decodes bodies.
      if (s.buf.size() < kFrameHeaderLen) break;
      if ((unsigned char)s.buf[1] != kFrameMagic1 ||
          (unsigned char)s.buf[2] != kFrameVersion) {
        // Corrupt child output must never desync the upstream channel.
        frame_resync(s.buf);
        continue;
      }
      uint64_t hl = read_be32(s.buf.data() + 5);
      uint64_t bl = read_be32(s.buf.data() + 9);
      if (hl > kFrameMaxHeader || bl > kFrameMaxBody) {
        frame_resync(s.buf);
        continue;
      }
      uint64_t total = kFrameHeaderLen + hl + bl;
      if (s.buf.size() < total) break;
      emit_raw(s.buf.substr(0, (size_t)total));
      s.buf.erase(0, (size_t)total);
      continue;
    }
    size_t nl = s.buf.find('\n');
    if (nl == std::string::npos) break;
    std::string line = s.buf.substr(0, nl);
    s.buf.erase(0, nl + 1);
    if (line.empty()) continue;
    Json parsed;
    // Validate before forwarding; valid runner lines ARE protocol events
    // (started/telemetry/result) and pass through verbatim.
    if (!parse_json(line, parsed) || parsed.type != Json::Obj) continue;
    // Profile route lifecycle: a terminal answer retires the profile
    // id -> serve child mapping profile_forward remembered.  stop_failed
    // keeps it — the trace is still active in THAT child and a retried
    // sid-less stop must land there.
    const Json* ev = parsed.get("event");
    if (ev && ev->type == Json::Str &&
        (ev->s == "profile_stopped" || ev->s == "profile_error")) {
      const Json* code = parsed.get("code");
      const bool retryable = ev->s == "profile_error" && code &&
                             code->type == Json::Str &&
                             code->s == "stop_failed";
      const Json* pid_field = parsed.get("id");
      if (!retryable && pid_field && pid_field->type == Json::Str)
        g_profile_targets.erase(pid_field->s);
    }
    emit(line);
  }
}

// ---------------------------------------------------------------------------
// Telemetry side-band: tail watched JSONL files back over the channel.
// ---------------------------------------------------------------------------

struct Watcher {
  std::string path;
  off_t pos = 0;
  std::string buf;
};

static std::map<std::string, Watcher> g_watchers;

static void watch_task(const Json& cmd) {
  const Json* id_field = cmd.get("id");
  const Json* path_field = cmd.get("path");
  if (!id_field || id_field->type != Json::Str || !path_field ||
      path_field->type != Json::Str || path_field->s.empty()) {
    emit_error("watch requires string id and path");
    return;
  }
  // Offset 0 on every (re-)watch: reconnect flushes the buffered backlog.
  Watcher w;
  w.path = path_field->s;
  g_watchers[id_field->s] = std::move(w);
  emit("{\"event\":\"watching\",\"id\":\"" + json_escape(id_field->s) + "\"}");
}

static void unwatch_task(const Json& cmd) {
  const Json* id_field = cmd.get("id");
  if (!id_field || id_field->type != Json::Str) {
    emit_error("unwatch requires string id");
    return;
  }
  g_watchers.erase(id_field->s);
  emit("{\"event\":\"unwatched\",\"id\":\"" + json_escape(id_field->s) + "\"}");
}

static void pump_watchers() {
  for (auto& kv : g_watchers) {
    Watcher& w = kv.second;
    struct stat st;
    if (stat(w.path.c_str(), &st) != 0) continue;  // not written yet
    if (st.st_size < w.pos) {  // truncated/rotated: start over
      w.pos = 0;
      w.buf.clear();
    }
    if (st.st_size == w.pos) continue;
    int fd = open(w.path.c_str(), O_RDONLY);
    if (fd < 0) continue;
    if (lseek(fd, w.pos, SEEK_SET) >= 0) {
      // One bounded read per pump: a telemetry burst must not starve the
      // command loop.
      char chunk[65536];
      ssize_t n = read(fd, chunk, sizeof chunk);
      if (n > 0) {
        w.pos += n;
        w.buf.append(chunk, (size_t)n);
      }
    }
    close(fd);
    size_t nl;
    std::vector<std::string> records;
    while ((nl = w.buf.find('\n')) != std::string::npos) {
      std::string line = w.buf.substr(0, nl);
      w.buf.erase(0, nl + 1);
      if (line.empty()) continue;
      Json parsed;
      // Validate before forwarding; a valid line embeds verbatim as the
      // data object (it is already JSON).
      if (!parse_json(line, parsed) || parsed.type != Json::Obj) continue;
      records.push_back(line);
    }
    if (records.empty()) continue;
    if (g_frames) {
      // One telemetry_batch frame per pump per task: a heartbeat/event
      // burst costs one write upstream, not one per line.  The body is
      // the JSON array of the validated records.
      std::string body = "[";
      for (size_t r = 0; r < records.size(); r++) {
        if (r) body += ",";
        body += records[r];
      }
      body += "]";
      emit_frame(kVerbTelemetry,
                 "{\"event\":\"telemetry_batch\",\"id\":\"" +
                     json_escape(kv.first) + "\",\"count\":" +
                     std::to_string(records.size()) +
                     ",\"_body\":\"records\"}",
                 body);
    } else {
      for (const auto& line : records)
        emit("{\"event\":\"telemetry\",\"id\":\"" + json_escape(kv.first) +
             "\",\"data\":" + line + "}");
    }
  }
}

static void reap_children() {
  while (true) {
    int status = 0;
    pid_t pid = waitpid(-1, &status, WNOHANG);
    if (pid <= 0) break;
    reap_serve_child(pid);
    auto it = g_tasks.find(pid);
    if (it == g_tasks.end()) continue;
    int code = WIFEXITED(status) ? WEXITSTATUS(status) : -1;
    int sig = WIFSIGNALED(status) ? WTERMSIG(status) : 0;
    if (g_watchers.count(it->second.id)) {
      // Auto-unwatch on exit, after one final pump so the tail of the
      // telemetry file is flushed ahead of the exit event: a long-lived
      // agent must not keep stat()ing files of finished tasks forever.
      pump_watchers();
      g_watchers.erase(it->second.id);
    }
    emit("{\"event\":\"exit\",\"id\":\"" + json_escape(it->second.id) +
         "\",\"code\":" + std::to_string(code) +
         ",\"signal\":" + std::to_string(sig) + "}");
    g_tasks.erase(it);
  }
}

// ---------------------------------------------------------------------------
// Dispatcher epoch fencing + crash-recovery inventories.
//
// Mirrors the pool server's contract (harness.py `_EPOCH`/`_FENCED_CMDS`):
// the worker remembers the highest journal epoch any dispatcher ever
// declared and refuses mutating commands from a channel that declared a
// lower one.  This agent's process dies with its channel (EOF ends the
// pump; orphan mode lives in the Python pool server), so the fence here
// exists for protocol parity and for the degenerate zombie case — a
// channel re-declaring an older epoch after a newer one was seen.
// Inventories are read-only and stay open to any dispatcher: a stale one
// can look, not touch.
// ---------------------------------------------------------------------------

static long long g_epoch_max = 0;
static long long g_epoch_channel = 0;

static void handle_epoch(const Json& cmd) {
  const Json* e = cmd.get("epoch");
  long long declared = (e && e->type == Json::Int) ? e->i : 0;
  g_epoch_channel = declared;
  if (declared >= g_epoch_max) {
    g_epoch_max = declared;
    emit("{\"event\":\"epoch_ok\",\"epoch\":" + std::to_string(declared) +
         "}");
  } else {
    emit("{\"event\":\"error\",\"id\":\"\",\"code\":\"stale_epoch\","
         "\"message\":\"dispatcher epoch " + std::to_string(declared) +
         " is stale (worker has seen " + std::to_string(g_epoch_max) +
         ")\"}");
  }
}

static bool is_fenced_cmd(const std::string& n) {
  return n == "run" || n == "register_fn" || n == "invoke" ||
         n == "serve_open" || n == "serve_request" ||
         n == "serve_prefill" || n == "serve_close" ||
         n == "serve_resume" || n == "serve_cancel" ||
         n == "serve_attach" || n == "serve_detach" || n == "kill";
}

// Refuse a fenced command from a stale channel, in the SHAPE the caller's
// waiter settles on (a generic error would stall a serve_open waiter for
// its whole timeout).  Returns true when the command was consumed.
static bool fence_refuse(const std::string& name, const Json& cmd) {
  if (g_epoch_channel >= g_epoch_max || !is_fenced_cmd(name)) return false;
  const Json* id_field = cmd.get("id");
  const std::string id =
      (id_field && id_field->type == Json::Str) ? id_field->s : "";
  const Json* rid_field = cmd.get("rid");
  const std::string rid =
      (rid_field && rid_field->type == Json::Str) ? rid_field->s : "";
  const std::string message =
      "dispatcher epoch " + std::to_string(g_epoch_channel) +
      " is stale (worker has seen " + std::to_string(g_epoch_max) + ")";
  if (name == "serve_open" || name == "serve_close") {
    emit_serve_error(id, "stale_epoch", message, true);
  } else if (name == "serve_request") {
    emit("{\"event\":\"telemetry\",\"id\":\"" + json_escape(id) +
         "\",\"data\":{\"type\":\"serve.reject\",\"rid\":\"" +
         json_escape(rid) + "\",\"code\":\"stale_epoch\",\"message\":\"" +
         json_escape(message) + "\"}}");
  } else if (name == "serve_prefill") {
    emit("{\"event\":\"serve_kv\",\"id\":\"" + json_escape(id) +
         "\",\"rid\":\"" + json_escape(rid) +
         "\",\"code\":\"stale_epoch\",\"message\":\"" +
         json_escape(message) + "\"}");
  } else if (name == "serve_resume") {
    emit("{\"event\":\"serve_resumed\",\"id\":\"" + json_escape(id) +
         "\",\"rid\":\"" + json_escape(rid) +
         "\",\"state\":\"refused\",\"code\":\"stale_epoch\"}");
  } else if (name == "serve_attach" || name == "serve_detach") {
    const Json* a = cmd.get("adapter");
    emit("{\"event\":\"" + name + "ed\",\"id\":\"" + json_escape(id) +
         "\",\"adapter\":\"" +
         json_escape(a && a->type == Json::Str ? a->s : "") +
         "\",\"code\":\"stale_epoch\",\"message\":\"" +
         json_escape(message) + "\",\"permanent\":true}");
  } else if (name == "register_fn") {
    const Json* d = cmd.get("digest");
    emit("{\"event\":\"register_error\",\"digest\":\"" +
         json_escape(d && d->type == Json::Str ? d->s : "") +
         "\",\"code\":\"stale_epoch\",\"message\":\"" +
         json_escape(message) + "\"}");
  } else {
    emit("{\"event\":\"error\",\"id\":\"" + json_escape(id) +
         "\",\"code\":\"stale_epoch\",\"message\":\"" +
         json_escape(message) + "\"}");
  }
  return true;
}

// What survives in THIS worker: session runner children (sid + pid; the
// stream detail lives in the runner — the recovering dispatcher resumes
// through serve_resume, which is forwarded), and forked task children.
static void serve_inventory_cmd() {
  std::string out =
      "{\"event\":\"serve_inventory\",\"pid\":" + std::to_string(getpid()) +
      ",\"epoch\":" + std::to_string(g_epoch_max) + ",\"sessions\":[";
  bool first = true;
  for (const auto& kv : g_serve_children) {
    if (!first) out += ",";
    first = false;
    out += "{\"sid\":\"" + json_escape(kv.first) +
           "\",\"pid\":" + std::to_string(kv.second.pid) + "}";
  }
  out += "]}";
  emit(out);
}

static void task_inventory_cmd() {
  std::string out =
      "{\"event\":\"task_inventory\",\"pid\":" + std::to_string(getpid()) +
      ",\"epoch\":" + std::to_string(g_epoch_max) + ",\"tasks\":[";
  bool first = true;
  for (const auto& kv : g_tasks) {
    bool is_serve = false;
    for (const auto& sc : g_serve_children)
      if (sc.second.pid == kv.first) { is_serve = true; break; }
    if (is_serve) continue;
    if (!first) out += ",";
    first = false;
    out += "{\"id\":\"" + json_escape(kv.second.id) +
           "\",\"pid\":" + std::to_string(kv.first) + "}";
  }
  out += "]}";
  emit(out);
}

// ---------------------------------------------------------------------------
// Main loop: poll stdin + the SIGCHLD self-pipe.
// ---------------------------------------------------------------------------

static void handle_line(const std::string& line, bool& running) {
  if (line.empty()) return;
  Json cmd;
  if (!parse_json(line, cmd) || cmd.type != Json::Obj) {
    emit_error("malformed command line");
    return;
  }
  const Json* cmd_field = cmd.get("cmd");
  if (!cmd_field || cmd_field->type != Json::Str) {
    emit_error("missing cmd field");
    return;
  }
  const std::string& name = cmd_field->s;
  if (name == "ping") emit("{\"event\":\"pong\"}");
  else if (name == "frames") {
    // Negotiation: ack then flip to frames.  The kill switch answers
    // version 0 so a capable client settles on JSONL immediately.
    if (frames_env_enabled()) {
      emit("{\"event\":\"frames\",\"version\":1}");
      g_frames = true;
    } else {
      emit("{\"event\":\"frames\",\"version\":0}");
    }
  }
  else if (name == "epoch") handle_epoch(cmd);
  else if (name == "serve_inventory") serve_inventory_cmd();
  else if (name == "task_inventory") task_inventory_cmd();
  else if (fence_refuse(name, cmd)) return;
  else if (name == "run") spawn(cmd);
  else if (name == "register_fn") register_fn(cmd);
  else if (name == "invoke") invoke_task(cmd, line + "\n");
  else if (name == "serve_open") serve_open(cmd, line);
  else if (name == "serve_request") serve_forward(cmd, line + "\n", false);
  else if (name == "serve_resume") serve_forward(cmd, line + "\n", false);
  else if (name == "serve_cancel") serve_forward(cmd, line + "\n", false);
  else if (name == "serve_prefill") serve_prefill_forward(cmd, line + "\n");
  else if (name == "serve_attach" || name == "serve_detach")
    serve_attach_forward(cmd, name, line + "\n");
  else if (name == "serve_close") serve_forward(cmd, line + "\n", true);
  else if (name == "profile_start") profile_forward(cmd, line, false);
  else if (name == "profile_stop") profile_forward(cmd, line, true);
  else if (name == "kill") kill_task(cmd);
  else if (name == "watch") watch_task(cmd);
  else if (name == "unwatch") unwatch_task(cmd);
  else if (name == "shutdown") { emit("{\"event\":\"bye\"}"); running = false; }
  else emit_error("unknown cmd: " + name);
}

// One complete inbound FRAME: route by the header's cmd.  Frames whose
// body must reach a runner child (invoke, serve_request/close) forward
// the raw frame bytes verbatim; header-only commands replay through
// handle_line — the header IS the JSON command.  A non-JSON header is a
// consumed, sync-preserving refusal (the lengths were valid).
static void handle_frame(const std::string& header, const std::string& raw,
                         bool& running) {
  Json cmd;
  if (!parse_json(header, cmd) || cmd.type != Json::Obj) {
    emit_error("bad frame header");
    return;
  }
  const Json* cmd_field = cmd.get("cmd");
  const std::string name =
      (cmd_field && cmd_field->type == Json::Str) ? cmd_field->s : "";
  if (fence_refuse(name, cmd)) return;
  if (name == "invoke") {
    invoke_task(cmd, raw);
  } else if (name == "multi_invoke") {
    // Batched invoke needs the resident pool interpreter; this agent
    // forks one runner per invocation.  Clients only batch toward pool
    // runtimes — refuse per op so no waiter sits out its timeout.
    const Json* ops = cmd.get("ops");
    if (ops && ops->type == Json::Arr) {
      for (const auto& op : ops->arr) {
        const Json* id = op.get("id");
        emit("{\"event\":\"error\",\"id\":\"" +
             json_escape(id && id->type == Json::Str ? id->s : "") +
             "\",\"code\":\"unsupported\",\"message\":\"multi_invoke "
             "requires the pool runtime\"}");
      }
    } else {
      emit_error("multi_invoke requires ops");
    }
  } else if (name == "serve_request") {
    serve_forward(cmd, raw, false);
  } else if (name == "serve_prefill") {
    serve_prefill_forward(cmd, raw);
  } else if (name == "serve_attach" || name == "serve_detach") {
    serve_attach_forward(cmd, name, raw);
  } else if (name == "serve_close") {
    serve_forward(cmd, raw, true);
  } else {
    handle_line(header, running);
  }
}

// Extract every complete message (frame or line) from the stdin buffer.
// Malformed frames answer a clean error and resync at the next newline —
// the command loop must keep serving (fuzz contract: fail loud, never
// hang); a frame truncated by channel death simply stays buffered until
// the read loop sees EOF.
static void process_buffer(std::string& buffer, bool& running) {
  while (!buffer.empty()) {
    if ((unsigned char)buffer[0] == kFrameMagic0) {
      if (buffer.size() < kFrameHeaderLen) return;
      if ((unsigned char)buffer[1] != kFrameMagic1 ||
          (unsigned char)buffer[2] != kFrameVersion) {
        emit("{\"event\":\"error\",\"code\":\"bad_frame\",\"message\":"
             "\"bad frame magic/version\"}");
        frame_resync(buffer);
        continue;
      }
      uint64_t hl = read_be32(buffer.data() + 5);
      uint64_t bl = read_be32(buffer.data() + 9);
      if (hl > kFrameMaxHeader || bl > kFrameMaxBody) {
        emit("{\"event\":\"error\",\"code\":\"bad_frame\",\"message\":"
             "\"oversized frame\"}");
        frame_resync(buffer);
        continue;
      }
      uint64_t total = kFrameHeaderLen + hl + bl;
      if (buffer.size() < total) return;
      std::string header = buffer.substr(kFrameHeaderLen, (size_t)hl);
      std::string raw = buffer.substr(0, (size_t)total);
      buffer.erase(0, (size_t)total);
      handle_frame(header, raw, running);
    } else {
      size_t pos = buffer.find('\n');
      if (pos == std::string::npos) return;
      std::string line = buffer.substr(0, pos);
      buffer.erase(0, pos + 1);
      handle_line(line, running);
    }
  }
}

int main() {
  if (pipe(g_sigchld_pipe) != 0) return 1;
  fcntl(g_sigchld_pipe[0], F_SETFL, O_NONBLOCK);
  fcntl(g_sigchld_pipe[1], F_SETFL, O_NONBLOCK);

  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_handler = on_sigchld;
  sa.sa_flags = SA_RESTART | SA_NOCLDSTOP;
  sigaction(SIGCHLD, &sa, nullptr);
  signal(SIGPIPE, SIG_IGN);

  std::string banner =
      "{\"event\":\"ready\",\"pid\":" + std::to_string((long long)getpid());
  if (frames_env_enabled()) banner += ",\"frames\":1";
  emit(banner + "}");

  std::string buffer;
  bool running = true;
  bool stdin_open = true;
  char chunk[4096];

  // Keep serving until shutdown — or, after stdin closes, until every child
  // is reaped AND every RPC runner's stream is drained, so neither an exit
  // event nor a buffered result line is lost on a clean drain.
  while (running && (stdin_open || !g_tasks.empty() || !g_rpc_streams.empty())) {
    std::vector<struct pollfd> fds;
    if (stdin_open) fds.push_back({0, POLLIN, 0});
    fds.push_back({g_sigchld_pipe[0], POLLIN, 0});
    for (const auto& kv : g_rpc_streams) fds.push_back({kv.first, POLLIN, 0});

    // Live watchers wake the loop on a short tick so telemetry flows
    // without inbound traffic; otherwise block until a command/SIGCHLD/
    // runner output.
    int rc = poll(fds.data(), (nfds_t)fds.size(), g_watchers.empty() ? -1 : 250);
    if (rc < 0) {
      if (errno == EINTR) { reap_children(); pump_watchers(); continue; }
      break;
    }
    pump_watchers();

    for (size_t k = 0; k < fds.size(); k++) {
      if (!(fds[k].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      if (fds[k].fd == g_sigchld_pipe[0]) {
        char drain[64];
        while (read(g_sigchld_pipe[0], drain, sizeof drain) > 0) {}
        reap_children();
      } else if (fds[k].fd != 0) {
        // Runner stream (a stream erased earlier this sweep is a no-op
        // inside pump_rpc_stream — never fall through to the stdin read).
        pump_rpc_stream(fds[k].fd);
      } else {
        ssize_t n = read(0, chunk, sizeof chunk);
        if (n <= 0) {
          // Channel dropped: children keep running in their own sessions;
          // the executor resumes supervision via the pid-file polling path.
          // Serving children, by contrast, die with the channel (no client
          // can reach them anymore): EOF their stdin so they drain and
          // exit instead of holding model memory forever.
          stdin_open = false;
          for (auto& kv : g_serve_children) close(kv.second.stdin_fd);
          g_serve_children.clear();
          continue;
        }
        buffer.append(chunk, (size_t)n);
        process_buffer(buffer, running);
      }
    }
  }
  return 0;
}
