"""Process-wide metrics registry: counters, gauges, histograms.

The reference plugin exposes no metrics at all (SURVEY §5); the only number
the TPU build captured before this subsystem was a per-run ``StageTimer``
dict that died with the executor instance.  This module is the durable sink:
every instrumented component (executor lifecycle, workflow runner, agent
RPCs, transport pool) records into one process-wide registry that can be
read back as a JSON snapshot (``Registry.snapshot``) or Prometheus text
exposition (``Registry.prometheus_text``) at any point — zero third-party
dependencies, safe under threads and asyncio tasks alike.

Naming follows Prometheus conventions (``*_total`` counters, ``*_seconds``
histograms); labels are supported with the usual ``metric.labels(k=v)``
child pattern so per-stage/per-outcome series stay cheap to record on the
hot path (one dict lookup + one float add under a lock).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Iterable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "DEFAULT_LATENCY_BUCKETS",
]

#: Fixed histogram buckets for control-plane latencies (seconds).  Spans the
#: north-star range: sub-millisecond local round-trips up to the minutes a
#: cold TPU backend init can take.  Fixed (not configurable per call site)
#: so every stage histogram is directly comparable.
DEFAULT_LATENCY_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def _fmt_label_value(value: Any) -> str:
    text = str(value)
    return text.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_float(value: float) -> str:
    """Prometheus-style float: integers render bare, +Inf stays +Inf."""
    if value == float("inf"):
        return "+Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


class _Metric:
    """Shared parent/child plumbing for labelled metrics.

    A metric with ``label_names`` is a *family*: callers obtain per-series
    children via :meth:`labels` and record on those.  A metric without
    labels records directly on itself (its sole child is keyed by ``()``).
    """

    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str = "",
        label_names: Iterable[str] = (),
        registry: "Registry | None" = None,
    ) -> None:
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._children: dict[tuple[str, ...], Any] = {}
        self._lock = threading.Lock()
        if registry is not None:
            registry.register(self)

    def labels(self, **labels: Any):
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        key = tuple(_fmt_label_value(labels[n]) for n in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _default_child(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} has labels {self.label_names}; use .labels(...)"
            )
        with self._lock:
            child = self._children.get(())
            if child is None:
                child = self._new_child()
                self._children[()] = child
            return child

    def _new_child(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def remove(self, **labels: Any) -> None:
        """Drop one labeled series (no-op when absent).

        Series whose label values are user-derived and unbounded — e.g.
        the per-tenant queue depth gauge — must be removed when their
        owner retires, or the registry (and every /metrics scrape) grows
        monotonically for the process lifetime.
        """
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {tuple(labels)}"
            )
        key = tuple(_fmt_label_value(labels[n]) for n in self.label_names)
        with self._lock:
            self._children.pop(key, None)

    def _series(self) -> list[tuple[dict[str, str], Any]]:
        with self._lock:
            return [
                (dict(zip(self.label_names, key)), child)
                for key, child in sorted(self._children.items())
            ]


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Counter(_Metric):
    """Monotonically increasing count (``*_total``)."""

    kind = "counter"

    def _new_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Metric):
    """Point-in-time value that can go up and down."""

    kind = "gauge"

    def _new_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class _HistogramChild:
    __slots__ = ("buckets", "counts", "sum", "count", "exemplars", "_lock")

    def __init__(self, buckets: tuple[float, ...]) -> None:
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1 for the +Inf bucket
        self.sum = 0.0
        self.count = 0
        #: bucket index -> (value, trace_id, unix ts) of the most recent
        #: exemplar-carrying observation that landed in that bucket.  One
        #: slot per bucket keeps the memory bound independent of traffic.
        self.exemplars: dict[int, tuple[float, str, float]] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, trace_id: str | None = None) -> None:
        value = float(value)
        with self._lock:
            self.sum += value
            self.count += 1
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self.counts[i] += 1
                    break
            else:
                i = len(self.buckets)
                self.counts[-1] += 1
            if trace_id:
                self.exemplars[i] = (value, str(trace_id), time.time())

    def exemplar_snapshot(self) -> dict[int, tuple[float, str, float]]:
        with self._lock:
            return dict(self.exemplars)

    def cumulative(self) -> list[int]:
        """Per-bucket cumulative counts, Prometheus ``le`` semantics."""
        out, running = [], 0
        with self._lock:
            for c in self.counts:
                running += c
                out.append(running)
        return out

    def quantile(self, q: float) -> float | None:
        """Approximate quantile from bucket bounds (upper-bound estimate)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("quantile must be in [0, 1]")
        with self._lock:
            total = self.count
            if total == 0:
                return None
            target = q * total
            running = 0
            for i, c in enumerate(self.counts[:-1]):
                running += c
                if running >= target:
                    return self.buckets[i]
            return self.buckets[-1] if self.buckets else None


class Histogram(_Metric):
    """Fixed-bucket distribution (``*_seconds`` latencies by default)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        label_names: Iterable[str] = (),
        buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
        registry: "Registry | None" = None,
    ) -> None:
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        super().__init__(name, help, label_names, registry)

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float, trace_id: str | None = None) -> None:
        self._default_child().observe(value, trace_id=trace_id)

    def quantile(self, q: float) -> float | None:
        return self._default_child().quantile(q)

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum


class Registry:
    """Keyed set of metrics with snapshot + Prometheus text exposition.

    ``counter``/``gauge``/``histogram`` are get-or-create: the same
    (name, labels) call from any component returns the same metric, so
    instrumentation sites never coordinate registration order.
    """

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _check_compatible(existing: _Metric, name, cls, label_names, kwargs) -> None:
        if type(existing) is not cls or tuple(label_names) != existing.label_names:
            raise ValueError(
                f"metric {name!r} already registered with a different "
                f"type or label set"
            )
        buckets = kwargs.get("buckets")
        if buckets is not None and tuple(
            sorted(float(b) for b in buckets)
        ) != getattr(existing, "buckets", None):
            # Silently returning the existing histogram would put this
            # caller's observations into bounds it never asked for.
            raise ValueError(
                f"histogram {name!r} already registered with different buckets"
            )

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                self._check_compatible(
                    existing, metric.name, type(metric), metric.label_names,
                    {"buckets": getattr(metric, "buckets", None)},
                )
                return existing
            self._metrics[metric.name] = metric
            return metric

    def _get_or_create(self, cls, name, help, label_names, **kwargs) -> Any:
        with self._lock:
            existing = self._metrics.get(name)
        if existing is not None:
            self._check_compatible(existing, name, cls, label_names, kwargs)
            return existing
        return self.register(cls(name, help, label_names, **kwargs))

    def counter(self, name: str, help: str = "", label_names=()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "", label_names=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(
        self, name: str, help: str = "", label_names=(),
        buckets: Iterable[float] = DEFAULT_LATENCY_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, label_names, buckets=buckets
        )

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self) -> None:
        """Drop every metric (tests; a fresh process state)."""
        with self._lock:
            self._metrics.clear()

    # -- exposition --------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-friendly dump of every series' current state."""
        out: dict[str, Any] = {"ts": time.time(), "metrics": {}}
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in sorted(metrics, key=lambda m: m.name):
            series = []
            for labels, child in metric._series():
                entry: dict[str, Any] = {"labels": labels}
                if metric.kind == "histogram":
                    bounds = (*metric.buckets, float("inf"))
                    entry.update(
                        count=child.count,
                        sum=round(child.sum, 9),
                        buckets={
                            _fmt_float(b): c
                            for b, c in zip(bounds, child.cumulative())
                        },
                        p50=child.quantile(0.5),
                        p95=child.quantile(0.95),
                        p99=child.quantile(0.99),
                    )
                    exemplars = child.exemplar_snapshot()
                    if exemplars:
                        entry["exemplars"] = {
                            _fmt_float(bounds[i]): {
                                "value": round(value, 9),
                                "trace_id": trace_id,
                                "ts": round(ts, 6),
                            }
                            for i, (value, trace_id, ts)
                            in sorted(exemplars.items())
                        }
                else:
                    entry["value"] = child.value
                series.append(entry)
            out["metrics"][metric.name] = {
                "kind": metric.kind,
                "help": metric.help,
                "series": series,
            }
        return out

    def snapshot_json(self, indent: int | None = None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def prometheus_text(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition format (version 0.0.4).

        With ``openmetrics=True`` the output follows the OpenMetrics text
        format instead: bucket lines carry ``# {trace_id="..."} value ts``
        exemplar suffixes (when an observation recorded one) and the body
        ends with the mandatory ``# EOF`` terminator, so a p99 bucket
        links straight to a reconstructable ``/traces/<id>`` waterfall.
        Exemplars are invalid in the classic 0.0.4 format, hence the
        explicit opt-in.
        """
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in sorted(metrics, key=lambda m: m.name):
            if metric.help:
                lines.append(f"# HELP {metric.name} {metric.help}")
            lines.append(f"# TYPE {metric.name} {metric.kind}")
            for labels, child in metric._series():
                base = ",".join(f'{k}="{v}"' for k, v in labels.items())
                if metric.kind == "histogram":
                    bounds = (*metric.buckets, float("inf"))
                    exemplars = (
                        child.exemplar_snapshot() if openmetrics else {}
                    )
                    for i, (bound, cum) in enumerate(
                        zip(bounds, child.cumulative())
                    ):
                        le = f'le="{_fmt_float(bound)}"'
                        labelset = f"{base},{le}" if base else le
                        line = f"{metric.name}_bucket{{{labelset}}} {cum}"
                        ex = exemplars.get(i)
                        if ex is not None:
                            value, trace_id, ts = ex
                            line += (
                                f' # {{trace_id="{_fmt_label_value(trace_id)}"}}'
                                f" {_fmt_float(value)} {round(ts, 3)}"
                            )
                        lines.append(line)
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(
                        f"{metric.name}_sum{suffix} {_fmt_float(child.sum)}"
                    )
                    lines.append(f"{metric.name}_count{suffix} {child.count}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(
                        f"{metric.name}{suffix} {_fmt_float(child.value)}"
                    )
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + ("\n" if lines else "")


#: The process-wide default registry every instrumentation site records to.
REGISTRY = Registry()
