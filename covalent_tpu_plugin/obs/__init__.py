"""Observability subsystem: metrics registry, lifecycle spans, event stream.

Three cooperating layers, zero hard third-party dependencies:

* :mod:`.metrics` — process-wide counters/gauges/histograms with JSON
  snapshot and Prometheus text exposition (``REGISTRY``);
* :mod:`.trace` — ``Span`` context managers with trace/span/parent ids
  that instrument every executor lifecycle stage, workflow node, agent
  RPC, and pool acquire;
* :mod:`.events` — a structured JSONL event stream
  (``COVALENT_TPU_EVENTS_PATH``) carrying task-state transitions,
  failures with remote log tails, pool/agent health, and finished spans.

Environment:

``COVALENT_TPU_EVENTS_PATH``
    Path of the JSONL event log; unset disables the stream (size-bounded
    by ``COVALENT_TPU_EVENTS_MAX_BYTES`` / ``COVALENT_TPU_EVENTS_BACKUPS``).
``COVALENT_TPU_METRICS``
    Path to dump the metrics registry to at interpreter exit — JSON
    snapshot by default, Prometheus text when the path ends in ``.prom``;
    ``0``/``off`` explicitly disables the exit dump.
``COVALENT_TPU_OPS_PORT``
    Start the ops HTTP endpoint (``/metrics``, ``/status``, ``/events``)
    on this port; unset disables it (see :mod:`.opsserver`).
"""

from __future__ import annotations

import atexit
import os

from .events import EventSink, configure as configure_events, emit as emit_event
from .events import get_sink
from .flightrec import FLIGHT_RECORDER, FlightRecorder, ensure_flight_recorder
from .heartbeat import MONITOR, HeartbeatMonitor
from .history import HISTORY, MetricsHistory, ensure_history
from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from .opsserver import (
    OpsServer,
    ensure_ops_server,
    register_profile_provider,
    register_status_provider,
    unregister_profile_provider,
    unregister_status_provider,
)
from .slo import (
    DEFAULT_SLOS,
    SLOEngine,
    SLOSpec,
    ensure_slo_engine,
    load_slo_specs,
)
from .trace import (
    SPAN_HISTOGRAM,
    Span,
    context_of,
    current_span,
    extract_context,
    span,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "DEFAULT_LATENCY_BUCKETS",
    "Span",
    "span",
    "current_span",
    "context_of",
    "extract_context",
    "SPAN_HISTOGRAM",
    "EventSink",
    "get_sink",
    "configure_events",
    "emit_event",
    "dump_metrics",
    "HeartbeatMonitor",
    "MONITOR",
    "OpsServer",
    "ensure_ops_server",
    "register_status_provider",
    "unregister_status_provider",
    "register_profile_provider",
    "unregister_profile_provider",
    "MetricsHistory",
    "HISTORY",
    "ensure_history",
    "SLOSpec",
    "SLOEngine",
    "DEFAULT_SLOS",
    "load_slo_specs",
    "ensure_slo_engine",
    "FlightRecorder",
    "FLIGHT_RECORDER",
    "ensure_flight_recorder",
]

_METRICS_ENV = "COVALENT_TPU_METRICS"


def dump_metrics(path: str, registry: Registry = REGISTRY) -> None:
    """Write the registry to ``path``: Prometheus text for ``*.prom``,
    JSON snapshot otherwise."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    if path.endswith(".prom"):
        payload = registry.prometheus_text()
    else:
        payload = registry.snapshot_json(indent=2) + "\n"
    with open(path, "w", encoding="utf-8") as f:
        f.write(payload)


def _dump_at_exit() -> None:  # pragma: no cover - exercised via subprocess test
    path = os.environ.get(_METRICS_ENV)
    if not path or path.strip().lower() in ("0", "off", "false", "none"):
        return
    try:
        dump_metrics(path)
    except OSError:
        pass  # exit hooks must never fail the interpreter


atexit.register(_dump_at_exit)
