"""Span-based tracing for the dispatch control plane.

Subsumes the old ``utils.timing.StageTimer`` (now a shim over this module):
where the timer recorded a flat ``{stage: seconds}`` dict that died with the
executor instance, a :class:`Span` carries trace/span/parent ids, status,
and attributes, propagates through ``contextvars`` (so asyncio tasks nest
correctly without threading a handle through every call), and on close
fans out to both sinks:

* the structured event stream (``obs.events``) as a ``span`` event — the
  JSONL file doubles as a flat trace export with consistent ids;
* the metrics registry, as one observation in the
  ``covalent_tpu_span_duration_seconds{span="<name>"}`` histogram — which
  is exactly the per-stage dispatch-overhead distribution the bench
  report and Prometheus exposition surface.

Usage::

    with span("executor.run", operation_id=op) as root:
        with span("executor.connect"):
            ...
    root.stage_durations   # {"executor.connect": 0.012}

Parent spans accumulate each direct child's duration under the child's
*leaf* name (the part after the last dot), which is what lets the
``StageTimer`` compatibility summary (total/overhead accounting) fall out
of the trace for free.
"""

from __future__ import annotations

import contextvars
import os
import time
from typing import Any

from . import events as _events
from .metrics import REGISTRY

__all__ = [
    "Span", "span", "current_span", "SPAN_HISTOGRAM",
    "context_of", "extract_context", "record_span",
]

#: Name of the histogram every finished span observes into.
SPAN_HISTOGRAM = "covalent_tpu_span_duration_seconds"

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "covalent_tpu_current_span", default=None
)


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def current_span() -> "Span | None":
    """The innermost open span in this task/thread context, if any."""
    return _current.get()


def context_of(span: "Span", **extra: Any) -> dict[str, Any]:
    """Wire-format trace context for propagation across a process boundary.

    The dispatcher stamps this dict into the harness task spec and agent
    RPCs so worker-side events join the dispatch trace: ``trace_id`` is
    the trace to join, ``span_id`` the parent for whatever the remote side
    records.  ``extra`` rides along verbatim (e.g. ``attempt=N``, which
    the retry driver preserves so one trace follows an electron across
    gang re-submissions).
    """
    return {"trace_id": span.trace_id, "span_id": span.span_id, **extra}


def extract_context(carrier: Any) -> tuple[str, str] | None:
    """``(trace_id, parent_span_id)`` from a :func:`context_of` dict.

    Never raises: the carrier crossed a process boundary, so anything —
    a non-dict, ids of the wrong type, a partial dict — may arrive.  Any
    malformed carrier yields ``None`` and the local span falls back to a
    fresh root rather than poisoning the dispatch it instruments.
    """
    if not carrier or not isinstance(carrier, dict):
        return None
    try:
        trace_id = carrier.get("trace_id")
        span_id = carrier.get("span_id")
        if (
            not trace_id
            or not span_id
            or not isinstance(trace_id, (str, int))
            or not isinstance(span_id, (str, int))
        ):
            return None
        return str(trace_id), str(span_id)
    except Exception:  # noqa: BLE001 - carriers come off the wire
        return None


class Span:
    """One timed operation with ids, status, and attributes.

    Use as a context manager (sync ``with`` works inside async code — no
    await happens at enter/exit).  Exceptions mark the span ``ERROR`` with
    the exception repr attached, then propagate.
    """

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attributes",
        "status", "start_ts", "duration_s", "stage_durations",
        "_t0", "_token", "_parent", "_emit", "_activate", "_context",
    )

    def __init__(
        self,
        name: str,
        attributes: dict[str, Any] | None = None,
        emit: bool = True,
        parent: "Span | None" = None,
        activate: bool = True,
        context: tuple[str, str] | None = None,
    ) -> None:
        """``parent`` overrides contextvar lookup; ``activate=False`` keeps
        the span out of the ambient context (long-lived roots that are never
        exited, like the StageTimer shim's, must not capture it).
        ``context`` — a ``(trace_id, parent_span_id)`` pair from
        :func:`extract_context` — adopts a *remote* parent when no local
        one applies, joining a trace that started in another process."""
        self.name = name
        self.attributes: dict[str, Any] = dict(attributes or {})
        self.status = "OK"
        self.parent_id: str | None = None
        self.trace_id: str | None = None
        self.span_id = _new_id(8)
        self.start_ts: float | None = None
        self.duration_s: float | None = None
        #: leaf-name -> accumulated seconds of *direct* child spans; the
        #: StageTimer-compat view of this span's trace subtree.
        self.stage_durations: dict[str, float] = {}
        self._t0: float | None = None
        self._token = None
        self._parent: Span | None = parent
        self._emit = emit
        self._activate = activate
        self._context = context

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self) -> "Span":
        if self._parent is None:
            self._parent = _current.get()
        parent = self._parent
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        elif self._context is not None:
            self.trace_id, self.parent_id = self._context
        else:
            self.trace_id = _new_id(16)
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        if self._activate:
            self._token = _current.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.record_error(exc)
        self.end()
        return False

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def record_error(self, error: BaseException | str) -> None:
        self.status = "ERROR"
        self.attributes["error"] = (
            error if isinstance(error, str) else repr(error)
        )

    @property
    def leaf_name(self) -> str:
        return self.name.rsplit(".", 1)[-1]

    def end(self) -> None:
        if self._t0 is None or self.duration_s is not None:
            return  # never entered, or already ended
        self.duration_s = time.perf_counter() - self._t0
        if self._token is not None:
            try:
                _current.reset(self._token)
            except ValueError:
                # Ended from a different context than it was entered in
                # (e.g. a callback); the var will fall out of scope anyway.
                pass
            self._token = None
        parent = self._parent
        if parent is not None:
            parent.stage_durations[self.leaf_name] = (
                parent.stage_durations.get(self.leaf_name, 0.0)
                + self.duration_s
            )
        REGISTRY.histogram(
            SPAN_HISTOGRAM,
            "Duration of instrumented control-plane spans",
            label_names=("span",),
        ).labels(span=self.name).observe(
            self.duration_s, trace_id=self.trace_id
        )
        if self._emit:
            _events.emit(
                "span",
                name=self.name,
                trace_id=self.trace_id,
                span_id=self.span_id,
                parent_id=self.parent_id,
                start_ts=round(self.start_ts, 6),
                duration_s=round(self.duration_s, 6),
                status=self.status,
                **({"attributes": self.attributes} if self.attributes else {}),
            )

    # -- StageTimer-compat accounting -------------------------------------

    def total(self) -> float:
        if self.duration_s is not None:
            return self.duration_s
        if self._t0 is None:
            return 0.0
        return time.perf_counter() - self._t0

    def overhead(self, exclude: tuple[str, ...] = ("execute",)) -> float:
        """Dispatch overhead = child stages minus the task's own runtime."""
        return sum(
            v for k, v in self.stage_durations.items() if k not in exclude
        )

    def summary(self) -> dict[str, float]:
        out = dict(self.stage_durations)
        out["total"] = self.total()
        out["overhead"] = self.overhead()
        return out


def record_span(
    name: str,
    *,
    trace_id: str | None = None,
    parent_id: str | None = None,
    span_id: str | None = None,
    start_ts: float | None = None,
    duration_s: float,
    status: str = "OK",
    attributes: dict[str, Any] | None = None,
) -> str:
    """Emit one span retrospectively from explicit timings.

    The waterfall instrumentation measures segments with plain monotonic
    stamps on the request object (a :class:`Span` context manager cannot
    wrap code that spans callbacks and reconnects), and remote spans come
    back off the wire already timed; both land here.  Fans out exactly
    like :meth:`Span.end` — one histogram observation (exemplar-linked to
    the trace) plus one ``span`` event — and returns the span id so
    callers can parent further segments under it.
    """
    if span_id is None:
        span_id = _new_id(8)
    if trace_id is None:
        trace_id = _new_id(16)
    duration_s = max(0.0, float(duration_s))
    REGISTRY.histogram(
        SPAN_HISTOGRAM,
        "Duration of instrumented control-plane spans",
        label_names=("span",),
    ).labels(span=name).observe(duration_s, trace_id=trace_id)
    _events.emit(
        "span",
        name=name,
        trace_id=trace_id,
        span_id=span_id,
        parent_id=parent_id,
        start_ts=round(start_ts if start_ts is not None else time.time(), 6),
        duration_s=round(duration_s, 6),
        status=status,
        **({"attributes": dict(attributes)} if attributes else {}),
    )
    return span_id


def span(name: str, **attributes: Any) -> Span:
    """Open a new span as a context manager: ``with span("x", k=v): ...``."""
    return Span(name, attributes)
