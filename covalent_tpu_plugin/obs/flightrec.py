"""Failure flight recorder: a per-task black box of recent telemetry.

When an electron dies after minutes of heartbeats, the question is never
"what was the last error" — the event stream has that — it is "what was
this task *doing* in the run-up".  The flight recorder keeps a bounded
ring of recent records per task (lifecycle events, worker heartbeats,
dispatcher stage transitions), keyed by the task's *base* operation id so
one ring spans the whole retry lineage (``op``, ``op.r1``, ...).  On a
terminal dispatch failure the executor dumps the ring as a black-box JSON
artifact next to its cache, and the ops server serves the live rings at
``GET /tasks`` / ``GET /tasks/<operation_id>`` while the task still runs.

Feeding is passive: :func:`ensure_flight_recorder` registers one listener
on the event stream and files every event that carries an
``operation_id`` — no instrumentation site changes, and the per-event cost
is one dict copy and a deque append.  Oversized string fields (log tails)
are truncated so a single failure report cannot blow the ring's memory
bound.  ``COVALENT_TPU_FLIGHTREC=0`` disables the recorder;
``COVALENT_TPU_FLIGHTREC_EVENTS`` / ``_TASKS`` size the rings (defaults
256 records for each of the 64 most-recently-active tasks).
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Any

from . import events as _events

__all__ = ["FlightRecorder", "FLIGHT_RECORDER", "ensure_flight_recorder"]

_ENABLE_ENV = "COVALENT_TPU_FLIGHTREC"
_EVENTS_ENV = "COVALENT_TPU_FLIGHTREC_EVENTS"
_TASKS_ENV = "COVALENT_TPU_FLIGHTREC_TASKS"
_DEFAULT_EVENTS = 256
_DEFAULT_TASKS = 64
#: Longest string any recorded field keeps (log tails get truncated).
_FIELD_CAP = 2048

_RETRY_SUFFIX = re.compile(r"\.r\d+$")


def base_operation_id(operation_id: str) -> str:
    """Strip the retry suffix so one ring spans the whole lineage."""
    return _RETRY_SUFFIX.sub("", operation_id)


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


def _disabled() -> bool:
    """``COVALENT_TPU_FLIGHTREC=0`` disables recording everywhere.

    Checked per call (one env read), not just at wiring time: the
    executor feeds stage transitions and failure dumps into the
    process-wide recorder directly, and those sites must honor the flag
    too — not only the event-listener registration.
    """
    return os.environ.get(_ENABLE_ENV, "").strip().lower() in (
        "0", "off", "false", "no", "none"
    )


class FlightRecorder:
    """Bounded per-task rings of recent records, LRU-evicted across tasks."""

    def __init__(
        self,
        per_task: int | None = None,
        max_tasks: int | None = None,
    ) -> None:
        self.per_task = (
            _env_int(_EVENTS_ENV, _DEFAULT_EVENTS)
            if per_task is None
            else max(1, int(per_task))
        )
        self.max_tasks = (
            _env_int(_TASKS_ENV, _DEFAULT_TASKS)
            if max_tasks is None
            else max(1, int(max_tasks))
        )
        self._lock = threading.Lock()
        #: base operation id -> deque of compact records (newest last).
        self._rings: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict()
        )
        #: base operation id -> most recent trace id seen on its records,
        #: so ``GET /tasks/<op>`` can cross-link ``GET /traces/<id>``.
        self._trace_ids: dict[str, str] = {}

    # -- feeding -----------------------------------------------------------

    @staticmethod
    def _compact(record: dict[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for key, value in record.items():
            if isinstance(value, str) and len(value) > _FIELD_CAP:
                value = value[:_FIELD_CAP] + "…[truncated]"
            out[key] = value
        return out

    def _ring_for(self, base: str) -> collections.deque:
        ring = self._rings.get(base)
        if ring is None:
            ring = collections.deque(maxlen=self.per_task)
            self._rings[base] = ring
            while len(self._rings) > self.max_tasks:
                evicted, _ = self._rings.popitem(last=False)
                self._trace_ids.pop(evicted, None)
        else:
            self._rings.move_to_end(base)
        return ring

    def record_event(self, event: dict[str, Any]) -> None:
        """Events-stream listener: file anything tied to an operation.

        Never raises (observer contract) and never keeps a reference to
        the caller's dict — listeners share one event object.
        """
        try:
            if _disabled():
                return
            operation_id = event.get("operation_id")
            if not operation_id:
                return
            base = base_operation_id(str(operation_id))
            compact = self._compact(event)
            with self._lock:
                self._ring_for(base).append(compact)
                trace_id = event.get("trace_id")
                if trace_id:
                    self._trace_ids[base] = str(trace_id)
        except Exception:  # noqa: BLE001 - observers must not break flow
            pass

    def record_stage(
        self, operation_id: str, stage: str, trace_id: str | None = None
    ) -> None:
        """Dispatcher stage transition (these are /status state, not
        events — the recorder is where they become history)."""
        if _disabled():
            return
        record = {
            "ts": round(time.time(), 6),
            "type": "stage",
            "operation_id": operation_id,
            "stage": stage,
        }
        if trace_id:
            record["trace_id"] = str(trace_id)
        base = base_operation_id(operation_id)
        with self._lock:
            self._ring_for(base).append(record)
            if trace_id:
                self._trace_ids[base] = str(trace_id)

    def forget(self, operation_id: str) -> None:
        with self._lock:
            base = base_operation_id(operation_id)
            self._rings.pop(base, None)
            self._trace_ids.pop(base, None)

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()
            self._trace_ids.clear()

    # -- views / dumps -----------------------------------------------------

    def tasks(self) -> dict[str, int]:
        """base operation id -> record count (the ``/tasks`` index)."""
        with self._lock:
            return {base: len(ring) for base, ring in self._rings.items()}

    def view(self, operation_id: str) -> dict[str, Any] | None:
        """The live ring for one task, or None (``/tasks/<op>``)."""
        base = base_operation_id(operation_id)
        with self._lock:
            ring = self._rings.get(base)
            if ring is None:
                return None
            records = list(ring)
            trace_id = self._trace_ids.get(base)
        view: dict[str, Any] = {
            "operation_id": base,
            "records": records,
            "count": len(records),
        }
        if trace_id:
            view["trace_id"] = trace_id
            view["trace_url"] = f"/traces/{trace_id}"
        return view

    def dump(self, operation_id: str, reason: str) -> dict[str, Any]:
        """Black-box payload for one task (empty ring still dumps)."""
        view = self.view(operation_id) or {
            "operation_id": base_operation_id(operation_id),
            "records": [],
            "count": 0,
        }
        view["reason"] = reason
        view["dumped_at"] = round(time.time(), 6)
        return view

    def dump_to_file(
        self, operation_id: str, reason: str, directory: str
    ) -> str | None:
        """Write the black box as JSON; returns the path (None on failure).

        Best-effort by contract: a full disk must not turn one failed
        electron into two failures.
        """
        if _disabled():
            return None
        payload = self.dump(operation_id, reason)
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", payload["operation_id"])
        path = os.path.join(directory, f"blackbox_{safe}.json")
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=repr, indent=2)
                # The black box exists BECAUSE something is crashing:
                # fsync before the atomic publish or the dump can vanish
                # with the machine while the rename survives.
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError):
            return None
        return path


#: Process-wide recorder (fed once :func:`ensure_flight_recorder` ran).
FLIGHT_RECORDER = FlightRecorder()

_wired_lock = threading.Lock()
_wired = False


def ensure_flight_recorder() -> FlightRecorder | None:
    """Register the recorder on the event stream once; None if disabled."""
    global _wired
    if _disabled():
        return None
    with _wired_lock:
        if not _wired:
            _events.add_listener(FLIGHT_RECORDER.record_event)
            _wired = True
    return FLIGHT_RECORDER
