"""SLO engine: declarative objectives, multi-window error-budget burn rates.

The serving tier streams latency histograms and the dispatch plane counts
outcomes — but nothing *judges* them.  This module closes the loop with the
standard SRE shape: each SLO names an SLI (a latency histogram with a
threshold, or a good/bad counter ratio), an objective (the fraction of good
events promised), and evaluation windows.  The **burn rate** over a window
is ``bad_fraction / (1 - objective)`` — 1.0 means the error budget is being
spent exactly at the promised pace, >1 means an incident in progress.  An
SLO *fires* only when every configured window burns above its threshold
(the classic multi-window gate: the short window proves it's happening
now, the long window proves it's not a blip).

Specs come from three layers, merged by name (later wins):

* shipped defaults (:data:`DEFAULT_SLOS`) covering serve p95 latency,
  TTFT, the task error rate, and dispatch ``wall_overhead``;
* the ``observability.slos`` config key (a list of spec tables);
* the ``COVALENT_TPU_SLOS`` environment variable — a JSON list of spec
  objects, or ``off`` to disable the engine entirely.

Spec object::

    {"name": "serve_p95",                     # unique id (gauge label)
     "metric": "covalent_tpu_serve_request_seconds",
     "kind": "latency",                       # or "ratio"
     "threshold_s": 2.5,                      # latency: good iff <= this
     "bad": {"outcome": ["failed"]},          # ratio: bad-series filter
     "objective": 0.95,                       # promised good fraction
     "windows": [60, 300],                    # evaluation windows (s)
     "burn_threshold": 1.0}                   # fire above this burn

Each evaluation moves ``covalent_tpu_slo_burn_rate{slo}`` (the max burn
across windows), emits ``slo.burn`` / ``slo.recovered`` events on state
transitions, and calls every registered alert hook — the pluggable seam a
deployment points at its pager.  The engine evaluates after every history
sample (it subscribes to :data:`.history.HISTORY`) and on demand from the
ops server's ``GET /slo`` route.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from . import events as _events
from .history import HISTORY, MetricsHistory, ensure_history
from .metrics import REGISTRY

__all__ = [
    "SLOSpec",
    "SLOEngine",
    "DEFAULT_SLOS",
    "load_slo_specs",
    "ensure_slo_engine",
]

_SLOS_ENV = "COVALENT_TPU_SLOS"

SLO_BURN_RATE = REGISTRY.gauge(
    "covalent_tpu_slo_burn_rate",
    "Error-budget burn rate per SLO (max across its windows; >1 = burning)",
    ("slo",),
)

#: Shipped objectives for the serving + dispatch planes.  Deliberately
#: loose (these are guardrails, not latency targets — the bench asserts
#: the targets); deployments tighten them via config/env.
DEFAULT_SLOS: tuple[dict[str, Any], ...] = (
    {
        "name": "serve_p95_latency",
        "metric": "covalent_tpu_serve_request_seconds",
        "kind": "latency",
        "threshold_s": 2.5,
        "objective": 0.95,
        "windows": [60, 300],
    },
    {
        "name": "serve_ttft",
        "metric": "covalent_tpu_serve_ttft_seconds",
        "kind": "latency",
        "threshold_s": 1.0,
        "objective": 0.95,
        "windows": [60, 300],
    },
    {
        "name": "task_error_rate",
        "metric": "covalent_tpu_tasks_total",
        "kind": "ratio",
        "bad": {"outcome": ["failed", "fallback_local"]},
        "objective": 0.99,
        "windows": [60, 300],
    },
    {
        "name": "dispatch_overhead",
        "metric": "covalent_tpu_wall_overhead_seconds",
        "kind": "latency",
        "threshold_s": 2.0,
        "objective": 0.95,
        "windows": [60, 300],
    },
)


@dataclass
class SLOSpec:
    """One declarative objective over a history-backed SLI."""

    name: str
    metric: str
    kind: str = "latency"  # "latency" (histogram) or "ratio" (counter)
    threshold_s: float = 0.0
    bad: dict[str, Any] = field(default_factory=dict)
    objective: float = 0.99
    windows: tuple[float, ...] = (60.0, 300.0)
    burn_threshold: float = 1.0
    labels: dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.name or not self.metric:
            raise ValueError("SLO spec needs a name and a metric")
        if self.kind not in ("latency", "ratio"):
            raise ValueError(
                f"SLO {self.name}: kind must be 'latency' or 'ratio', "
                f"got {self.kind!r}"
            )
        if self.kind == "latency" and self.threshold_s <= 0:
            raise ValueError(f"SLO {self.name}: latency needs threshold_s > 0")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name}: objective must be in (0, 1), "
                f"got {self.objective}"
            )
        self.windows = tuple(float(w) for w in self.windows) or (60.0,)
        if any(w <= 0 for w in self.windows):
            raise ValueError(f"SLO {self.name}: windows must be > 0 seconds")

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "SLOSpec":
        known = set(cls.__dataclass_fields__)
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"unknown SLO spec field(s) {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        return cls(**data)


def load_slo_specs(env: str | None = None) -> list[SLOSpec]:
    """Defaults <- config ``observability.slos`` <- ``COVALENT_TPU_SLOS``.

    Merged by ``name``, field-level: an override listing only the fields
    it changes tunes the same-name default (new names must be complete
    specs); a spec with ``"disabled": true`` drops that name.  Returns [] when the env
    var is ``off`` (the whole engine then idles).  Malformed layers are
    skipped with a warning — observability config must never take down
    the dispatch it observes.
    """
    raw_env = os.environ.get(_SLOS_ENV) if env is None else env
    if raw_env is not None and raw_env.strip().lower() in (
        "off", "0", "false", "none"
    ):
        return []
    merged: dict[str, dict[str, Any]] = {
        spec["name"]: dict(spec) for spec in DEFAULT_SLOS
    }

    def merge_layer(layer: Any, origin: str) -> None:
        if not isinstance(layer, (list, tuple)):
            raise ValueError(f"expected a list of spec objects, got {layer!r}")
        for entry in layer:
            if not isinstance(entry, dict) or not entry.get("name"):
                raise ValueError(f"spec without a name in {origin}: {entry!r}")
            name = str(entry["name"])
            if entry.get("disabled"):
                merged.pop(name, None)
            else:
                # Field-level merge over a same-name base: tuning one
                # field of a shipped default ({"name": "serve_ttft",
                # "threshold_s": 2.0}) adjusts that field — a whole-spec
                # replace would drop the unnamed required fields and
                # silently DELETE the SLO at from_dict time.
                base = dict(merged.get(name, ()))
                base.update(
                    {k: v for k, v in entry.items() if k != "disabled"}
                )
                merged[name] = base

    from ..utils.config import get_config

    try:
        config_layer = get_config("observability.slos", None)
        if config_layer:
            merge_layer(config_layer, "config observability.slos")
    except Exception as err:  # noqa: BLE001 - bad config never fatal
        from ..utils.log import app_log

        app_log.warning("ignoring observability.slos config: %s", err)
    if raw_env and raw_env.strip():
        try:
            merge_layer(json.loads(raw_env), _SLOS_ENV)
        except (ValueError, TypeError) as err:
            from ..utils.log import app_log

            app_log.warning("ignoring malformed %s: %s", _SLOS_ENV, err)
    specs: list[SLOSpec] = []
    for data in merged.values():
        try:
            specs.append(SLOSpec.from_dict(data))
        except (TypeError, ValueError) as err:
            from ..utils.log import app_log

            app_log.warning("ignoring invalid SLO spec %r: %s", data, err)
    return specs


class SLOEngine:
    """Evaluates SLO specs as burn rates over one history ring.

    Thread-safe; ``clock`` rides the history's clock by default so fake
    clocks in tests drive both windows and evaluations coherently.
    """

    def __init__(
        self,
        history: MetricsHistory,
        specs: list[SLOSpec] | None = None,
        alert_hook: Callable[[str, str, dict], None] | None = None,
    ) -> None:
        self.history = history
        self.specs = list(specs if specs is not None else load_slo_specs())
        self._lock = threading.Lock()
        self._states: dict[str, str] = {}
        self._last: dict[str, Any] = {}
        self._alert_hooks: list[Callable[[str, str, dict], None]] = []
        if alert_hook is not None:
            self._alert_hooks.append(alert_hook)

    def add_alert_hook(
        self, hook: Callable[[str, str, dict], None]
    ) -> None:
        """``hook(slo_name, state, info)`` on every burning/ok transition."""
        if hook not in self._alert_hooks:
            self._alert_hooks.append(hook)

    def remove_alert_hook(
        self, hook: Callable[[str, str, dict], None]
    ) -> None:
        """Detach a hook (no-op when absent) — a closing subscriber
        (e.g. an autoscale controller) must not be kept alive, or kept
        firing, by a process-wide engine."""
        try:
            self._alert_hooks.remove(hook)
        except ValueError:
            pass

    # -- evaluation --------------------------------------------------------

    def _window_burn(
        self, spec: SLOSpec, window_s: float
    ) -> dict[str, Any]:
        """Burn rate + SLI for one spec over one window."""
        if spec.kind == "latency":
            count, good = self.history.good_fraction(
                spec.metric, spec.threshold_s, window_s,
                labels=spec.labels or None,
            )
            if good is None:
                return {"window_s": window_s, "burn": 0.0, "data": False}
            bad_fraction = 1.0 - good
            return {
                "window_s": window_s,
                "burn": bad_fraction / spec.budget,
                "sli": good,
                "count": count,
                "data": True,
            }
        total, bad_fraction = self.history.bad_ratio(
            spec.metric, spec.bad or None, window_s
        )
        if bad_fraction is None:
            return {"window_s": window_s, "burn": 0.0, "data": False}
        return {
            "window_s": window_s,
            "burn": bad_fraction / spec.budget,
            "sli": 1.0 - bad_fraction,
            "count": total,
            "data": True,
        }

    def evaluate(self) -> dict[str, Any]:
        """Evaluate every spec; move gauges, fire transitions, return the
        full view (also served verbatim at ``GET /slo``)."""
        slos: dict[str, Any] = {}
        transitions: list[tuple[str, str, dict]] = []
        with self._lock:
            for spec in self.specs:
                windows = [
                    self._window_burn(spec, w) for w in spec.windows
                ]
                with_data = [w for w in windows if w["data"]]
                max_burn = max((w["burn"] for w in with_data), default=0.0)
                if not with_data:
                    state = "no_data"
                elif all(
                    w["burn"] > spec.burn_threshold for w in with_data
                ):
                    state = "burning"
                else:
                    state = "ok"
                SLO_BURN_RATE.labels(slo=spec.name).set(max_burn)
                info = {
                    "state": state,
                    "burn_rate": round(max_burn, 4),
                    "burn_threshold": spec.burn_threshold,
                    "objective": spec.objective,
                    "kind": spec.kind,
                    "metric": spec.metric,
                    **(
                        {"threshold_s": spec.threshold_s}
                        if spec.kind == "latency"
                        else {"bad": spec.bad}
                    ),
                    "windows": [
                        {
                            k: (round(v, 4) if isinstance(v, float) else v)
                            for k, v in w.items()
                        }
                        for w in windows
                    ],
                }
                slos[spec.name] = info
                previous = self._states.get(spec.name, "ok")
                # no_data is not a recovery — a quiet window after an
                # incident must not clear the alert until good traffic does.
                if state == "burning" and previous != "burning":
                    transitions.append((spec.name, "burning", info))
                    self._states[spec.name] = "burning"
                elif state == "ok" and previous == "burning":
                    transitions.append((spec.name, "ok", info))
                    self._states[spec.name] = "ok"
                elif spec.name not in self._states:
                    self._states[spec.name] = state
            self._last = {
                "evaluated_at": round(time.time(), 3),
                "slos": slos,
            }
        for name, state, info in transitions:
            _events.emit(
                "slo.burn" if state == "burning" else "slo.recovered",
                slo=name,
                **{
                    k: v for k, v in info.items()
                    if k in ("burn_rate", "burn_threshold", "objective",
                             "windows", "state", "metric")
                },
            )
            for hook in list(self._alert_hooks):
                try:
                    hook(name, state, info)
                except Exception:  # noqa: BLE001 - alerting must not break
                    pass
        return dict(self._last)

    def status(self) -> dict[str, Any]:
        """Most recent evaluation (evaluating first if none happened)."""
        with self._lock:
            last = dict(self._last)
        if last:
            return last
        return self.evaluate()


_engine_lock = threading.Lock()
_engine: SLOEngine | None = None


def ensure_slo_engine() -> SLOEngine | None:
    """Start the process-wide engine over :data:`HISTORY` once.

    Subscribes an evaluation to every history sample so burn events fire
    without any scrape; returns None when ``COVALENT_TPU_SLOS=off`` or
    history sampling is disabled.  Idempotent.
    """
    global _engine
    with _engine_lock:
        if _engine is not None:
            return _engine
        specs = load_slo_specs()
        if not specs or ensure_history() is None:
            return None
        engine = SLOEngine(HISTORY, specs=specs)
        HISTORY.add_listener(lambda _ts: engine.evaluate())
        _engine = engine
    return _engine


def get_engine() -> SLOEngine | None:
    """The process-wide engine if one is running (ops ``/slo`` route)."""
    return _engine
