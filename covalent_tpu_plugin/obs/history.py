"""Metrics history: a bounded downsampling ring over registry snapshots.

The registry (:mod:`.metrics`) answers "what is the value *now*"; every
question the fleet plane actually asks during an incident is about *change*
— requests per second over the last minute, p95 latency over the last five,
whether the queue depth is growing.  This module records fixed-interval
samples of the process registry into one bounded ring and answers windowed
rate/percentile queries over it, with zero third-party dependencies and an
injectable clock for deterministic tests.

**Downsampling.**  The ring holds at most ``capacity`` samples.  When it
fills, every other sample is dropped and the recording stride doubles: the
ring then covers twice the wall-clock span at half the resolution.  Memory
stays bounded forever while the observable window keeps growing — recent
data is fine-grained, old data coarse, which is exactly the shape
dashboards and burn-rate queries want.

**Queries.**  :meth:`MetricsHistory.query` is kind-aware:

* *gauge* — the raw timeline plus last/min/max/avg per labelled series;
* *counter* — the increase and per-second rate over the window (counters
  only go up, so ``last - first`` is the windowed delta);
* *histogram* — the windowed distribution (latest cumulative bucket counts
  minus the earliest in-window sample's), yielding p50/p90/p95/p99, count,
  rate and mean *for the window* rather than for process lifetime.

The process-wide :data:`HISTORY` ring is fed by a daemon sampler thread
(:func:`ensure_history`), started automatically with the ops server and
configurable via ``COVALENT_TPU_HISTORY_S`` (sample interval, default 1.0;
``0``/``off`` disables) and ``COVALENT_TPU_HISTORY_SAMPLES`` (ring
capacity, default 512).  The SLO engine (:mod:`.slo`) subscribes to each
recorded sample via :meth:`MetricsHistory.add_listener`.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable

from .metrics import REGISTRY, Registry

__all__ = ["MetricsHistory", "HISTORY", "ensure_history"]

_INTERVAL_ENV = "COVALENT_TPU_HISTORY_S"
_CAPACITY_ENV = "COVALENT_TPU_HISTORY_SAMPLES"
_DEFAULT_INTERVAL_S = 1.0
_DEFAULT_CAPACITY = 512


def _series_key(labels: dict[str, str]) -> str:
    """Stable JSON key for one labelled series ("" for the unlabelled)."""
    if not labels:
        return ""
    return json.dumps(labels, sort_keys=True)


class MetricsHistory:
    """Fixed-interval bounded ring of compact registry samples.

    Thread-safe: the sampler thread records while ops-server request
    threads query.  ``clock`` is injectable so downsampling and windowed
    queries are testable without real sleeps.
    """

    def __init__(
        self,
        registry: Registry = REGISTRY,
        interval_s: float = _DEFAULT_INTERVAL_S,
        capacity: int = _DEFAULT_CAPACITY,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.registry = registry
        self.interval_s = max(0.0, float(interval_s))
        self.capacity = max(8, int(capacity))
        self._clock = clock
        self._lock = threading.Lock()
        #: (ts, {metric: {"kind", "series": {key: payload}}}) samples,
        #: oldest first.  Counter/gauge payloads are floats; histogram
        #: payloads are (count, sum, cumulative-counts tuple).
        self._samples: collections.deque = collections.deque()
        #: effective recording stride multiplier; doubles on each compaction.
        self._stride = 1
        self._ticks_until_record = 0
        self._listeners: list[Callable[[float], None]] = []

    # -- recording ---------------------------------------------------------

    def _capture(self) -> dict[str, Any]:
        """One compact sample of every registered metric's series."""
        out: dict[str, Any] = {}
        with self.registry._lock:
            metrics = list(self.registry._metrics.values())
        for metric in metrics:
            series: dict[str, Any] = {}
            for labels, child in metric._series():
                key = _series_key(labels)
                if metric.kind == "histogram":
                    series[key] = (
                        child.count,
                        child.sum,
                        tuple(child.cumulative()),
                    )
                else:
                    series[key] = float(child.value)
            out[metric.name] = {"kind": metric.kind, "series": series}
        return out

    def sample(self, force: bool = False) -> bool:
        """Record one sample now; returns whether one was recorded.

        The sampler thread calls this once per ``interval_s`` tick; the
        stride counter makes post-compaction ticks record every Nth call
        so the ring's spacing stays uniform.  ``force`` (tests, bench
        phase boundaries) bypasses the stride.
        """
        now = self._clock()
        with self._lock:
            if not force:
                if self._ticks_until_record > 0:
                    self._ticks_until_record -= 1
                    return False
                self._ticks_until_record = self._stride - 1
            self._samples.append((now, self._capture()))
            if len(self._samples) >= self.capacity:
                # Downsample: drop every other sample (keeping the newest)
                # and double the stride — bounded memory, growing span.
                kept = list(self._samples)[::-2][::-1]
                self._samples = collections.deque(kept)
                self._stride *= 2
        for listener in list(self._listeners):
            try:
                listener(now)
            except Exception:  # noqa: BLE001 - observers must not break flow
                pass
        return True

    def add_listener(self, listener: Callable[[float], None]) -> None:
        """Call ``listener(ts)`` after every recorded sample (SLO engine)."""
        if listener not in self._listeners:
            self._listeners.append(listener)

    def remove_listener(self, listener: Callable[[float], None]) -> None:
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()
            self._stride = 1
            self._ticks_until_record = 0

    # -- views -------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    @property
    def stride(self) -> int:
        return self._stride

    def span_s(self) -> float:
        """Wall-clock seconds between the oldest and newest sample."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            return self._samples[-1][0] - self._samples[0][0]

    def metric_names(self) -> list[str]:
        with self._lock:
            if not self._samples:
                return []
            return sorted(self._samples[-1][1])

    def describe(self) -> dict[str, Any]:
        """The ``/history`` index payload (no ``metric`` param)."""
        return {
            "samples": len(self),
            "capacity": self.capacity,
            "interval_s": self.interval_s,
            "stride": self._stride,
            "span_s": round(self.span_s(), 3),
            "metrics": self.metric_names(),
        }

    def _window(self, window_s: float) -> list[tuple[float, dict]]:
        """Samples whose ts falls inside the trailing window, oldest first."""
        cutoff = self._clock() - max(0.0, float(window_s))
        with self._lock:
            return [s for s in self._samples if s[0] >= cutoff]

    # -- queries -----------------------------------------------------------

    def query(
        self,
        metric: str,
        window_s: float = 60.0,
        labels: dict[str, str] | None = None,
        agg: str = "",
    ) -> dict[str, Any]:
        """Windowed, kind-aware view of one metric.

        Returns ``{"metric", "kind", "window_s", "samples", "series"}``
        where ``series`` maps the JSON label key to that series' windowed
        stats + timeline.  ``labels`` (exact match) restricts to one
        series.  An unknown metric or empty window answers with zero
        samples rather than raising — dashboards poll speculatively.

        ``agg="trend"`` swaps the per-series stats for a least-squares
        **slope** over the window — the predictive-autoscaling primitive
        ("is the queue depth growing, and how fast"): gauges report
        ``slope_per_s`` of the raw value, counters and histograms report
        the slope of their per-second *rate* (a positive value means
        traffic is accelerating, not merely flowing).
        """
        window = self._window(window_s)
        out: dict[str, Any] = {
            "metric": metric,
            "window_s": float(window_s),
            "samples": len(window),
            "kind": None,
            "series": {},
        }
        if agg:
            out["agg"] = agg
        if not window:
            return out
        wanted = _series_key(labels) if labels else None
        kind = None
        #: key -> [(ts, payload)] across the window
        timelines: dict[str, list[tuple[float, Any]]] = {}
        for ts, snap in window:
            entry = snap.get(metric)
            if entry is None:
                continue
            kind = entry["kind"]
            for key, payload in entry["series"].items():
                if wanted is not None and key != wanted:
                    continue
                timelines.setdefault(key, []).append((ts, payload))
        out["kind"] = kind
        # Cumulative series (counters, histograms) absent from the
        # window's FIRST sample were born mid-window; registry children
        # start at zero, so their true baseline is a zero at the window
        # edge — using their first captured value instead would swallow
        # every observation that landed between two sampler ticks.
        first_entry = window[0][1].get(metric) or {}
        first_series = first_entry.get("series", {})
        window_start = window[0][0]
        for key, points in timelines.items():
            if key not in first_series and kind == "histogram":
                zeros = (0, 0.0, (0,) * len(points[-1][1][2]))
                points = [(window_start, zeros)] + points
            elif key not in first_series and kind == "counter":
                points = [(window_start, 0.0)] + points
            if agg == "trend":
                out["series"][key] = self._trend_stats(kind, points)
            elif kind == "histogram":
                out["series"][key] = self._histogram_stats(metric, points)
            elif kind == "counter":
                out["series"][key] = self._counter_stats(points)
            else:
                out["series"][key] = self._gauge_stats(points)
        return out

    # -- trend (agg="trend") -------------------------------------------------

    @staticmethod
    def _slope_of(points: list[tuple[float, float]]) -> float:
        """Least-squares slope (units per second) over ``(ts, value)``.

        Fewer than two points — or a degenerate time axis — has no
        trend; the answer is 0.0, never an exception (the controller
        polls this every tick, including on freshly started rings).
        """
        n = len(points)
        if n < 2:
            return 0.0
        t0 = points[0][0]
        ts = [t - t0 for t, _ in points]
        vs = [float(v) for _, v in points]
        mean_t = sum(ts) / n
        mean_v = sum(vs) / n
        var_t = sum((t - mean_t) ** 2 for t in ts)
        if var_t <= 0:
            return 0.0
        cov = sum(
            (t - mean_t) * (v - mean_v) for t, v in zip(ts, vs)
        )
        return cov / var_t

    @classmethod
    def _trend_stats(
        cls, kind: str | None, points: list[tuple[float, Any]]
    ) -> dict[str, Any]:
        """Per-series trend: value slope for gauges, rate slope for
        cumulative kinds (counters by value delta, histograms by
        observation-count delta).  Counter resets (value decreasing)
        drop the torn interval instead of reporting a negative burst."""
        if kind == "gauge" or kind is None:
            values = [(ts, float(v)) for ts, v in points]
            return {
                "last": values[-1][1] if values else 0.0,
                "slope_per_s": cls._slope_of(values),
            }
        # Cumulative kinds: build the per-interval rate series at
        # interval midpoints, then fit the slope of THAT — "is the rate
        # itself rising" is the question predictive scaling asks.
        rates: list[tuple[float, float]] = []
        increase = 0.0
        for (t_a, p_a), (t_b, p_b) in zip(points, points[1:]):
            dt = t_b - t_a
            if dt <= 0:
                continue
            v_a = p_a[0] if kind == "histogram" else float(p_a)
            v_b = p_b[0] if kind == "histogram" else float(p_b)
            if v_b < v_a:  # reset between samples: skip the torn interval
                continue
            increase += v_b - v_a
            rates.append(((t_a + t_b) / 2.0, (v_b - v_a) / dt))
        span = max(points[-1][0] - points[0][0], 1e-9)
        return {
            "increase": increase,
            "rate_per_s": (
                increase / span if len(points) > 1 else 0.0
            ),
            "slope_per_s": cls._slope_of(rates),
        }

    @staticmethod
    def _gauge_stats(points: list[tuple[float, float]]) -> dict[str, Any]:
        values = [v for _, v in points]
        return {
            "points": [[round(ts, 3), v] for ts, v in points],
            "last": values[-1],
            "min": min(values),
            "max": max(values),
            "avg": sum(values) / len(values),
        }

    @staticmethod
    def _counter_stats(points: list[tuple[float, float]]) -> dict[str, Any]:
        t0, first = points[0]
        t1, last = points[-1]
        increase = max(0.0, last - first)
        dt = max(t1 - t0, 1e-9)
        return {
            "points": [[round(ts, 3), v] for ts, v in points],
            "last": last,
            "increase": increase,
            # A single in-window sample has no baseline: rate is 0, not a
            # division of the full lifetime count by epsilon.
            "rate_per_s": increase / dt if len(points) > 1 else 0.0,
        }

    def _histogram_stats(
        self, metric: str, points: list[tuple[float, Any]]
    ) -> dict[str, Any]:
        t0, (count0, sum0, cum0) = points[0]
        t1, (count1, sum1, cum1) = points[-1]
        count = max(0, count1 - count0)
        total = max(0.0, sum1 - sum0)
        # Bucket-shape changes across a registry reset make the delta
        # meaningless; fall back to the latest cumulative state.
        if len(cum0) != len(cum1) or count1 < count0:
            count, total, delta = count1, sum1, list(cum1)
        else:
            delta = [max(0, b - a) for a, b in zip(cum0, cum1)]
        hist = self.registry.get(metric)
        bounds = list(getattr(hist, "buckets", ())) + [float("inf")]
        dt = max(t1 - t0, 1e-9)
        stats: dict[str, Any] = {
            "count": count,
            "sum": round(total, 9),
            "rate_per_s": count / dt if len(points) > 1 else 0.0,
            "mean": (total / count) if count else None,
        }
        for q in (0.5, 0.9, 0.95, 0.99):
            stats[f"p{int(q * 100)}"] = self._quantile_from(
                delta, bounds, count, q
            )
        return stats

    @staticmethod
    def _quantile_from(
        cumulative: list[int], bounds: list[float], total: int, q: float
    ) -> float | None:
        """Upper-bound quantile estimate from windowed cumulative counts
        (same semantics as ``metrics._HistogramChild.quantile``)."""
        if total <= 0 or len(cumulative) != len(bounds):
            return None
        target = q * total
        for cum, bound in zip(cumulative, bounds):
            if cum >= target:
                return bound if bound != float("inf") else (
                    bounds[-2] if len(bounds) > 1 else None
                )
        return bounds[-2] if len(bounds) > 1 else None

    def good_fraction(
        self,
        metric: str,
        threshold: float,
        window_s: float,
        labels: dict[str, str] | None = None,
    ) -> tuple[int, float | None]:
        """``(windowed count, fraction of observations <= threshold)``.

        The latency-SLI primitive: how many of the window's observations
        landed at or under the threshold bucket.  ``threshold`` snaps to
        the smallest bucket bound >= itself (Prometheus ``le``
        semantics); fraction is None when the window holds no data.
        """
        window = self._window(window_s)
        wanted = _series_key(labels) if labels else None
        firsts: dict[str, Any] = {}
        lasts: dict[str, Any] = {}
        first_series = (
            (window[0][1].get(metric) or {}).get("series", {})
            if window
            else {}
        )
        for _, snap in window:
            entry = snap.get(metric)
            if entry is None or entry["kind"] != "histogram":
                continue
            for key, payload in entry["series"].items():
                if wanted is not None and key != wanted:
                    continue
                firsts.setdefault(key, payload)
                lasts[key] = payload
        hist = self.registry.get(metric)
        bounds = list(getattr(hist, "buckets", ()))
        if not bounds or not lasts:
            return 0, None
        for key, (_c1, _s1, cum1) in lasts.items():
            if key not in first_series:
                # Born mid-window: zero baseline (see query()).
                firsts[key] = (0, 0.0, (0,) * len(cum1))
        # Index of the threshold bucket (first bound >= threshold).  A
        # threshold above every finite bound snaps to +Inf — the bucket
        # resolution cannot observe a violation there, so everything
        # counts good rather than everything bad (a false "all bad"
        # would page on a service meeting its objective).
        le_index = next(
            (i for i, b in enumerate(bounds) if b >= threshold),
            len(bounds),  # cumulative() carries a trailing +Inf entry
        )
        count = good = 0
        for key, (count1, _sum1, cum1) in lasts.items():
            count0, _sum0, cum0 = firsts[key]
            if len(cum0) != len(cum1) or count1 < count0:
                count0, cum0 = 0, (0,) * len(cum1)
            count += max(0, count1 - count0)
            if le_index >= len(cum1):
                # Defensive: a snapshot without the +Inf entry.
                good += max(0, count1 - count0)
            else:
                good += max(0, cum1[le_index] - cum0[le_index])
        if count <= 0:
            return 0, None
        return count, min(1.0, good / count)

    def bad_ratio(
        self,
        metric: str,
        bad: dict[str, Any] | None,
        window_s: float,
    ) -> tuple[float, float | None]:
        """``(windowed total, bad fraction)`` across a counter family.

        ``bad`` filters series by label values (each value may be a
        scalar or a list of acceptable values); None/empty marks EVERY
        series bad — useful for "this counter should not move at all"
        specs (retries, faults).  For those, the denominator is the
        window's elapsed time in ticks — the fraction is then a rate
        normalized into [0, 1] by min().
        """
        window = self._window(window_s)
        firsts: dict[str, float] = {}
        lasts: dict[str, float] = {}
        first_series = (
            (window[0][1].get(metric) or {}).get("series", {})
            if window
            else {}
        )
        for _, snap in window:
            entry = snap.get(metric)
            if entry is None or entry["kind"] != "counter":
                continue
            for key, payload in entry["series"].items():
                firsts.setdefault(key, payload)
                lasts[key] = payload
        if not lasts:
            return 0.0, None
        for key in lasts:
            if key not in first_series:
                # Born mid-window: zero baseline (see query()).
                firsts[key] = 0.0

        def matches(key: str) -> bool:
            if not bad:
                return True
            labels = json.loads(key) if key else {}
            for name, accept in bad.items():
                values = accept if isinstance(accept, (list, tuple)) else [accept]
                if str(labels.get(name)) not in [str(v) for v in values]:
                    return False
            return True

        total = bad_count = 0.0
        for key, last in lasts.items():
            delta = max(0.0, last - firsts[key])
            total += delta
            if matches(key):
                bad_count += delta
        if bad is None or not bad:
            # Denominatorless spec ("this counter should not move"): the
            # denominator is the window's elapsed sample ticks, so one
            # lone increment in a wide window reads as a small rate —
            # not an instantly-saturated burn.
            if not window:
                return bad_count, None
            ticks = max(1.0, float(len(window) - 1))
            return bad_count, min(1.0, bad_count / ticks)
        if total <= 0:
            return 0.0, None
        return total, bad_count / total


#: Process-wide history ring (fed by :func:`ensure_history`'s sampler).
HISTORY = MetricsHistory()

_thread_lock = threading.Lock()
_thread: threading.Thread | None = None


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    if raw in ("0", "off", "false", "no", "none"):
        return 0.0
    try:
        return float(raw)
    except ValueError:
        return default


def ensure_history(interval_s: float | None = None) -> MetricsHistory | None:
    """Start the process-wide sampler thread once; returns the ring.

    ``interval_s`` overrides ``COVALENT_TPU_HISTORY_S`` (default 1.0
    second); 0 disables sampling and returns None.  Idempotent — the ops
    server, executors, and the bench all call this freely.
    """
    global _thread
    interval = (
        _env_float(_INTERVAL_ENV, _DEFAULT_INTERVAL_S)
        if interval_s is None
        else float(interval_s)
    )
    if interval <= 0:
        return None
    with _thread_lock:
        if _thread is not None and _thread.is_alive():
            if interval_s is not None and interval < HISTORY.interval_s:
                # An explicit finer interval wins even after the sampler
                # started (the loop re-reads interval_s every tick).
                # Tighten only — coarsening would silently degrade a
                # timeline some other caller is already relying on.
                HISTORY.interval_s = interval
            return HISTORY
        HISTORY.interval_s = interval
        try:
            HISTORY.capacity = max(
                8, int(os.environ.get(_CAPACITY_ENV, "") or _DEFAULT_CAPACITY)
            )
        except ValueError:
            pass

        def loop() -> None:
            while True:
                time.sleep(HISTORY.interval_s)
                try:
                    HISTORY.sample()
                except Exception:  # noqa: BLE001 - sampler must never die
                    pass

        _thread = threading.Thread(
            target=loop, name="covalent-tpu-history", daemon=True
        )
        _thread.start()
    return HISTORY
