"""Ops status endpoint: a stdlib HTTP thread serving the fleet's live view.

Opt-in via one environment variable::

    COVALENT_TPU_OPS_PORT=9464 python my_workflow.py
    curl localhost:9464/metrics   # Prometheus text exposition (scrapable)
    curl localhost:9464/status    # JSON: in-flight electrons, heartbeats,
                                  #       circuit breakers, dispatches
    curl localhost:9464/events    # bounded tail of the structured stream

Port 0 binds an ephemeral port (tests); the bound port is readable from
``OpsServer.port`` and logged in the ``ops.server_started`` event.  The
server binds ``COVALENT_TPU_OPS_HOST`` (default loopback — exposing an
unauthenticated ops port beyond the host is an operator decision, not a
default) and runs entirely on daemon threads: it can never hold the
dispatcher open at exit.

``/status`` is assembled from *status providers*: components register a
zero-argument callable (``TPUExecutor`` its in-flight/breaker view, the
workflow runner its dispatch table) and the handler merges their dicts at
request time.  Providers are held weakly by convention — register a
closure over a weakref, return ``{}`` when the owner is gone — so a
forgotten executor cannot be kept alive by its ops registration.

``/events`` is fed by an in-process event listener into a bounded ring
buffer (``COVALENT_TPU_EVENTS_TAIL`` entries, default 256), so the tail
works even when no JSONL path is configured.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from . import events as _events
from . import flightrec as _flightrec
from . import history as _history
from . import slo as _slo
from . import tracestore as _tracestore
from .heartbeat import MONITOR
from .metrics import REGISTRY

__all__ = [
    "OpsServer",
    "ensure_ops_server",
    "register_status_provider",
    "unregister_status_provider",
    "register_profile_provider",
    "unregister_profile_provider",
]

_PORT_ENV = "COVALENT_TPU_OPS_PORT"
_HOST_ENV = "COVALENT_TPU_OPS_HOST"
_TAIL_ENV = "COVALENT_TPU_EVENTS_TAIL"

_providers_lock = threading.Lock()
_providers: dict[str, Callable[[], dict]] = {}
_profile_providers: dict[str, Callable[[dict], "dict | None"]] = {}


def register_status_provider(name: str, provider: Callable[[], dict]) -> None:
    """Contribute a dict to ``/status`` under ``name`` (last write wins)."""
    with _providers_lock:
        _providers[name] = provider


def unregister_status_provider(name: str) -> None:
    with _providers_lock:
        _providers.pop(name, None)


def register_profile_provider(
    name: str, provider: Callable[[dict], "dict | None"]
) -> None:
    """Contribute a ``POST /profile`` target.

    ``provider(params)`` runs on the HTTP request thread and returns the
    capture's artifact info (path, digest, bytes), or None when its owner
    currently has no resident runtime to profile (the handler then tries
    the next provider).  Same weakref-by-convention contract as status
    providers: return None forever once the owner is gone.
    """
    with _providers_lock:
        _profile_providers[name] = provider


def unregister_profile_provider(name: str) -> None:
    with _providers_lock:
        _profile_providers.pop(name, None)


def _tail_size() -> int:
    try:
        return max(16, int(os.environ.get(_TAIL_ENV, "256")))
    except ValueError:
        return 256


class OpsServer:
    """One HTTP thread serving /metrics, /status, /events, /healthz."""

    def __init__(self, port: int, host: str | None = None) -> None:
        self.host = host or os.environ.get(_HOST_ENV) or "127.0.0.1"
        self.started_at = time.time()
        self._tail: collections.deque = collections.deque(
            maxlen=_tail_size()
        )
        self._listener = self._tail.append
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # protocol-only stdout stays clean
                pass

            def _send(self, code: int, body: bytes, content_type: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, payload, code: int = 200) -> None:
                self._send(
                    code,
                    json.dumps(payload, default=repr, indent=2).encode(),
                    "application/json",
                )

            def do_GET(self) -> None:  # noqa: N802 - http.server contract
                try:
                    url = urlparse(self.path)
                    route = url.path.rstrip("/") or "/"
                    params = parse_qs(url.query)
                    if route == "/metrics":
                        # OpenMetrics (exemplar-carrying) exposition on
                        # content negotiation or ?format=openmetrics; the
                        # classic 0.0.4 format cannot legally carry
                        # exemplars, so it stays the default.
                        accept = self.headers.get("Accept") or ""
                        fmt = (params.get("format") or [""])[0]
                        openmetrics = (
                            fmt == "openmetrics"
                            or "application/openmetrics-text" in accept
                        )
                        if openmetrics:
                            self._send(
                                200,
                                REGISTRY.prometheus_text(
                                    openmetrics=True
                                ).encode(),
                                "application/openmetrics-text; "
                                "version=1.0.0; charset=utf-8",
                            )
                        else:
                            self._send(
                                200, REGISTRY.prometheus_text().encode(),
                                "text/plain; version=0.0.4",
                            )
                    elif route == "/status":
                        self._send_json(server.status())
                    elif route == "/events":
                        try:
                            n = int(params.get("n", ["0"])[0])
                        except ValueError:
                            n = 0
                        self._send(
                            200, server.events_tail(n).encode(),
                            "application/x-ndjson",
                        )
                    elif route == "/history":
                        self._send_json(server.history(params))
                    elif route == "/slo":
                        self._send_json(server.slo())
                    elif route == "/tasks":
                        self._send_json(
                            {"tasks": _flightrec.FLIGHT_RECORDER.tasks()}
                        )
                    elif route.startswith("/tasks/"):
                        view = _flightrec.FLIGHT_RECORDER.view(
                            route[len("/tasks/"):]
                        )
                        if view is None:
                            self._send_json(
                                {"error": "no flight record"}, 404
                            )
                        else:
                            self._send_json(view)
                    elif route == "/traces":
                        self._send_json(_tracestore.TRACE_STORE.index())
                    elif route.startswith("/traces/"):
                        waterfall = _tracestore.TRACE_STORE.waterfall(
                            route[len("/traces/"):]
                        )
                        if waterfall is None:
                            self._send_json(
                                {"error": "no such trace"}, 404
                            )
                        else:
                            self._send_json(waterfall)
                    elif route in ("/", "/healthz"):
                        self._send(200, b"ok\n", "text/plain")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:  # client went away mid-write
                    pass
                except Exception as err:  # noqa: BLE001 - ops must not crash
                    try:
                        self._send(
                            500, f"error: {err!r}\n".encode(), "text/plain"
                        )
                    except Exception:  # noqa: BLE001
                        pass

            def do_POST(self) -> None:  # noqa: N802 - http.server contract
                try:
                    url = urlparse(self.path)
                    route = url.path.rstrip("/") or "/"
                    if route != "/profile":
                        self._send(404, b"not found\n", "text/plain")
                        return
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    params: dict = {}
                    if body.strip():
                        try:
                            parsed = json.loads(body)
                            if isinstance(parsed, dict):
                                params = parsed
                        except ValueError:
                            self._send_json(
                                {"error": "body must be a JSON object"}, 400
                            )
                            return
                    for key, values in parse_qs(url.query).items():
                        params.setdefault(key, values[0])
                    self._send_json(*server.profile(params))
                except BrokenPipeError:
                    pass
                except Exception as err:  # noqa: BLE001 - ops must not crash
                    try:
                        self._send(
                            500, f"error: {err!r}\n".encode(), "text/plain"
                        )
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((self.host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        # A live ops endpoint implies the whole introspection plane: the
        # history sampler (backing /history and the SLO windows), the SLO
        # engine (evaluating per sample), and the flight recorder (backing
        # /tasks).  Each is individually env-disableable and idempotent.
        _history.ensure_history()
        _slo.ensure_slo_engine()
        _flightrec.ensure_flight_recorder()
        _tracestore.ensure_trace_store()
        # Only after the bind succeeded: a failed construction must not
        # leave an orphaned listener on the event stream (ensure_ops_server
        # retries on every executor init, which would accumulate them).
        _events.add_listener(self._listener)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="covalent-tpu-ops",
            daemon=True,
        )
        self._thread.start()

    # -- payload assembly --------------------------------------------------

    def status(self) -> dict[str, Any]:
        """The /status JSON: merged provider views + heartbeat snapshot."""
        out: dict[str, Any] = {
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self.started_at, 3),
            "heartbeats": MONITOR.snapshot(),
            "in_flight": {},
        }
        with _providers_lock:
            providers = dict(_providers)
        fleet_views: dict[str, Any] = {}
        autoscale_views: dict[str, Any] = {}
        for name, provider in providers.items():
            try:
                view = provider()
            except Exception as err:  # noqa: BLE001 - one bad provider
                view = {"error": repr(err)}
            if view is None:
                # Provider's owner was garbage collected: prune the entry.
                unregister_status_provider(name)
                continue
            # Aggregate every provider's in-flight map at the top level so
            # "is electron X running" is one key lookup for operators/CI.
            in_flight = view.get("in_flight")
            if isinstance(in_flight, dict):
                out["in_flight"].update(in_flight)
            # Same for live serving sessions: "is session X open" must be
            # one lookup whichever executor holds it (sids are uuid-unique
            # across executors, so a flat merge cannot collide).
            serving = view.get("serving")
            if isinstance(serving, dict) and serving:
                out.setdefault("serving", {}).update(serving)
            if name.partition(":")[0] == "fleet" and view:
                # The scheduler's live view (queue depth, per-tenant
                # backlog, per-pool capacity/in-use/breakers) is a
                # first-class /status section, not buried in providers.
                # One scheduler (the common case) IS the section; several
                # live ones nest by provider name instead of silently
                # overwriting each other.
                fleet_views[name] = view
            elif name.partition(":")[0] == "autoscale" and view:
                # The autoscale controller's live view (targets, last
                # decisions, cooldown state) gets the same first-class
                # treatment as the fleet section.
                autoscale_views[name] = view
            elif view:
                out.setdefault("providers", {})[name] = view
        if len(fleet_views) == 1:
            out["fleet"] = next(iter(fleet_views.values()))
        elif fleet_views:
            out["fleet"] = fleet_views
        if len(autoscale_views) == 1:
            out["autoscaler"] = next(iter(autoscale_views.values()))
        elif autoscale_views:
            out["autoscaler"] = autoscale_views
        return out

    def history(self, params: dict) -> dict[str, Any]:
        """The /history payload: ring index, or one metric's windowed view.

        ``?metric=<name>&window=<seconds>`` answers the kind-aware query
        (rates for counters, percentiles for histograms, timelines for
        gauges); ``&agg=trend`` swaps the stats for per-window
        least-squares slopes (the autoscale controller's question);
        without ``metric`` the ring describes itself so dashboards can
        discover what is queryable.
        """
        metric = (params.get("metric") or [""])[0]
        if not metric:
            return _history.HISTORY.describe()
        try:
            window_s = float((params.get("window") or ["60"])[0])
        except ValueError:
            window_s = 60.0
        agg = (params.get("agg") or [""])[0]
        return _history.HISTORY.query(metric, window_s=window_s, agg=agg)

    def slo(self) -> dict[str, Any]:
        """The /slo payload: a fresh evaluation of every configured SLO."""
        engine = _slo.get_engine() or _slo.ensure_slo_engine()
        if engine is None:
            return {"disabled": True, "slos": {}}
        return engine.evaluate()

    def profile(self, params: dict) -> "tuple[dict[str, Any], int]":
        """The POST /profile action: capture a resident-runtime trace.

        Tries every registered profile provider (each owns one executor's
        resident runtimes) until one captures; 503 when none can — no
        executor alive, or none with a warm resident runtime.
        """
        with _providers_lock:
            providers = dict(_profile_providers)
        errors: dict[str, str] = {}
        for name, provider in providers.items():
            try:
                info = provider(dict(params))
            except Exception as err:  # noqa: BLE001 - one bad provider
                errors[name] = repr(err)
                continue
            if info:
                return {"provider": name, **info}, 200
        return (
            {
                "error": "no resident runtime available to profile",
                "providers": len(providers),
                **({"failures": errors} if errors else {}),
            },
            503,
        )

    def events_tail(self, n: int = 0) -> str:
        """Last ``n`` (default: all buffered) events as JSONL."""
        events = list(self._tail)
        if n > 0:
            events = events[-n:]
        return "".join(
            json.dumps(event, default=repr) + "\n" for event in events
        )

    def close(self) -> None:
        _events.remove_listener(self._listener)
        self._httpd.shutdown()
        self._httpd.server_close()


_server_lock = threading.Lock()
_server: OpsServer | None = None


def ensure_ops_server(port: int | None = None) -> OpsServer | None:
    """Start the process-wide ops server once; None when not configured.

    ``port`` overrides the environment (tests/embedders); with neither an
    explicit port nor ``COVALENT_TPU_OPS_PORT`` this is a no-op, so the
    call is safe on every executor/runner startup path.
    """
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        if port is None:
            raw = os.environ.get(_PORT_ENV, "").strip()
            if not raw:
                return None
            try:
                port = int(raw)
            except ValueError:
                from ..utils.log import app_log

                app_log.warning("ignoring non-integer %s=%r", _PORT_ENV, raw)
                return None
        try:
            _server = OpsServer(port)
        except OSError as err:
            from ..utils.log import app_log

            app_log.warning("ops server failed to bind port %s: %s", port, err)
            return None
    _events.emit(
        "ops.server_started", host=_server.host, port=_server.port
    )
    return _server


def shutdown_ops_server() -> None:
    """Stop and forget the process-wide server (tests)."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.close()
            _server = None
