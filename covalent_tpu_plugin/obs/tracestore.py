"""Bounded in-memory trace store with tail-based sampling.

Spans already fan out as ``span`` events on the structured event stream
(``obs.events``) — dispatcher-side from :class:`~.trace.Span`, worker- and
agent-side re-emitted off the telemetry backhaul with their original ids.
This module is the queryable half: one process-wide listener groups those
events by ``trace_id`` into bounded per-trace buffers, and when a trace's
root span closes it makes the *tail-based* keep/drop decision — by then
the whole trace is known, so the decision can look at what head-based
sampling cannot:

* **errors** — any span with ``status != OK`` keeps the trace;
* **SLO burn** — traces that overlapped a burning SLO window (the store
  listens for ``slo.burn`` / ``slo.recovered``) are always kept;
* **p99 outliers** — a root whose duration lands at or above the p99 of
  recent same-named roots is kept (that is exactly the trace an operator
  wants when a histogram exemplar points here);
* everything else survives with probability ``COVALENT_TPU_TRACE_SAMPLE``
  (default 0.1).

The ops server serves ``GET /traces`` (index) and ``GET /traces/<id>``
(waterfall JSON: spans with offsets/depths plus per-segment aggregation
and end-to-end coverage).  Bounds: ``COVALENT_TPU_TRACE_STORE_TRACES``
kept traces (LRU, default 256), ``COVALENT_TPU_TRACE_SPANS`` spans per
trace (default 512), ``COVALENT_TPU_TRACE_PENDING`` open traces
(default 512).  Everything degrades by dropping records, never by
raising into the instrumented path.
"""

from __future__ import annotations

import collections
import os
import random
import threading
import time
from typing import Any

from . import events as _events

__all__ = ["TraceStore", "TRACE_STORE", "ensure_trace_store", "get_store"]

_SAMPLE_ENV = "COVALENT_TPU_TRACE_SAMPLE"
_TRACES_ENV = "COVALENT_TPU_TRACE_STORE_TRACES"
_SPANS_ENV = "COVALENT_TPU_TRACE_SPANS"
_PENDING_ENV = "COVALENT_TPU_TRACE_PENDING"
_DEFAULT_SAMPLE = 0.1
_DEFAULT_TRACES = 256
_DEFAULT_SPANS = 512
_DEFAULT_PENDING = 512
#: Minimum same-named root durations seen before the p99-outlier rule
#: activates (a fresh process would otherwise keep its first N traces as
#: trivial "outliers" of a one-element distribution).
_OUTLIER_MIN_HISTORY = 20
#: Recently dropped trace ids remembered so a straggler span (a worker
#: record that crossed the wire after the root closed) cannot resurrect a
#: sampled-out trace as a new pending entry.
_DROPPED_MEMORY = 1024

_SPAN_FIELDS = (
    "name", "span_id", "parent_id", "start_ts", "duration_s", "status",
    "attributes",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


class TraceStore:
    """Groups ``span`` events into traces; keeps the interesting tails."""

    def __init__(
        self,
        max_traces: int | None = None,
        max_spans: int | None = None,
        max_pending: int | None = None,
        sample: float | None = None,
    ) -> None:
        self.max_traces = (
            _env_int(_TRACES_ENV, _DEFAULT_TRACES)
            if max_traces is None
            else max(1, int(max_traces))
        )
        self.max_spans = (
            _env_int(_SPANS_ENV, _DEFAULT_SPANS)
            if max_spans is None
            else max(1, int(max_spans))
        )
        self.max_pending = (
            _env_int(_PENDING_ENV, _DEFAULT_PENDING)
            if max_pending is None
            else max(1, int(max_pending))
        )
        self._sample_override = None if sample is None else float(sample)
        self._lock = threading.Lock()
        #: trace_id -> open trace being assembled (root not yet seen).
        self._pending: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        #: trace_id -> finalized kept trace, LRU-evicted.
        self._kept: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        #: root span name -> recent durations (the p99-outlier baseline).
        self._durations: dict[str, collections.deque] = {}
        self._dropped: "collections.OrderedDict[str, None]" = (
            collections.OrderedDict()
        )
        self._slo_burning: set[str] = set()
        self.finalized = 0
        self.kept_total = 0

    @property
    def sample(self) -> float:
        """Keep probability for unremarkable traces.

        Reads ``COVALENT_TPU_TRACE_SAMPLE`` live (unless constructed with
        an explicit rate) so the bench and tests can retune the
        process-wide store after import.
        """
        if self._sample_override is not None:
            return min(1.0, max(0.0, self._sample_override))
        return min(1.0, max(0.0, _env_float(_SAMPLE_ENV, _DEFAULT_SAMPLE)))

    @sample.setter
    def sample(self, value: float) -> None:
        self._sample_override = float(value)

    # -- feeding -----------------------------------------------------------

    def record_event(self, event: dict[str, Any]) -> None:
        """Events-stream listener; never raises (observer contract)."""
        try:
            etype = event.get("type")
            if etype == "span":
                self._record_span(event)
            elif etype == "slo.burn":
                with self._lock:
                    self._slo_burning.add(str(event.get("slo")))
            elif etype == "slo.recovered":
                with self._lock:
                    self._slo_burning.discard(str(event.get("slo")))
        except Exception:  # noqa: BLE001 - observers must not break flow
            pass

    def _record_span(self, event: dict[str, Any]) -> None:
        trace_id = event.get("trace_id")
        if not trace_id:
            return
        trace_id = str(trace_id)
        span = {k: event[k] for k in _SPAN_FIELDS if k in event}
        with self._lock:
            kept = self._kept.get(trace_id)
            if kept is not None:
                # Straggler from a remote worker: the root already closed
                # and the trace was kept — splice the span in so the
                # waterfall stays complete.
                if len(kept["spans"]) < self.max_spans:
                    kept["spans"].append(span)
                    kept["span_count"] = len(kept["spans"])
                else:
                    kept["dropped_spans"] = kept.get("dropped_spans", 0) + 1
                return
            if trace_id in self._dropped:
                return
            trace = self._pending.get(trace_id)
            if trace is None:
                trace = {
                    "trace_id": trace_id,
                    "first_ts": event.get("ts") or time.time(),
                    "spans": [],
                    "dropped_spans": 0,
                    "slo_burn": False,
                }
                self._pending[trace_id] = trace
                while len(self._pending) > self.max_pending:
                    stale_id, stale = self._pending.popitem(last=False)
                    self._finalize_locked(stale_id, stale, root=None)
            else:
                self._pending.move_to_end(trace_id)
            if self._slo_burning:
                trace["slo_burn"] = True
            if len(trace["spans"]) >= self.max_spans:
                trace["dropped_spans"] += 1
                return
            trace["spans"].append(span)
            if span.get("parent_id") is None:
                # Root closed: the whole trace is now known — decide.
                del self._pending[trace_id]
                self._finalize_locked(trace_id, trace, root=span)

    # -- tail-based decision ----------------------------------------------

    def _outlier_threshold(self, name: str) -> float | None:
        history = self._durations.get(name)
        if history is None or len(history) < _OUTLIER_MIN_HISTORY:
            return None
        ordered = sorted(history)
        return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]

    def _finalize_locked(
        self, trace_id: str, trace: dict, root: dict | None
    ) -> None:
        self.finalized += 1
        reason = None
        duration = float((root or {}).get("duration_s") or 0.0)
        if root is not None:
            name = str(root.get("name") or "")
            threshold = self._outlier_threshold(name)
            history = self._durations.get(name)
            if history is None:
                history = collections.deque(maxlen=512)
                self._durations[name] = history
            history.append(duration)
            if threshold is not None and duration >= threshold:
                reason = "p99_outlier"
        if any(s.get("status") not in (None, "OK") for s in trace["spans"]):
            reason = "error"
        elif trace["slo_burn"]:
            reason = "slo_burn"
        if reason is None:
            if root is None:
                reason = "evicted"  # pending overflow: sample like the rest
            if random.random() >= self.sample:
                self._dropped[trace_id] = None
                while len(self._dropped) > _DROPPED_MEMORY:
                    self._dropped.popitem(last=False)
                return
            reason = reason or "sampled"
        self.kept_total += 1
        trace["keep_reason"] = reason
        trace["root"] = (root or {}).get("name")
        trace["duration_s"] = duration if root is not None else None
        trace["span_count"] = len(trace["spans"])
        self._kept[trace_id] = trace
        while len(self._kept) > self.max_traces:
            self._kept.popitem(last=False)

    # -- views -------------------------------------------------------------

    def index(self) -> dict[str, Any]:
        """The ``GET /traces`` payload: newest-first trace summaries."""
        with self._lock:
            kept = [
                {
                    "trace_id": t["trace_id"],
                    "root": t.get("root"),
                    "duration_s": t.get("duration_s"),
                    "start_ts": t.get("first_ts"),
                    "span_count": t.get("span_count", len(t["spans"])),
                    "keep_reason": t.get("keep_reason"),
                }
                for t in reversed(self._kept.values())
            ]
            pending = len(self._pending)
            finalized = self.finalized
            kept_total = self.kept_total
        return {
            "traces": kept,
            "count": len(kept),
            "pending": pending,
            "finalized": finalized,
            "kept_total": kept_total,
            "sample": self.sample,
        }

    def waterfall(self, trace_id: str) -> dict[str, Any] | None:
        """The ``GET /traces/<id>`` payload: one trace as a waterfall.

        Spans come back start-ordered with ``offset_s`` (from the earliest
        span start), ``depth`` (parent chain length), and ``orphan``
        (parent id set but absent from the trace).  ``segments``
        aggregates the spans that carry a ``segment`` attribute — the
        waterfall tiling the serving path records — and ``coverage`` is
        their summed share of the root duration, which is how the bench
        asserts the segments account for the measured end-to-end latency.
        """
        with self._lock:
            trace = self._kept.get(trace_id) or self._pending.get(trace_id)
            if trace is None:
                return None
            spans = [dict(s) for s in trace["spans"]]
            out = {
                "trace_id": trace_id,
                "root": trace.get("root"),
                "duration_s": trace.get("duration_s"),
                "keep_reason": trace.get("keep_reason", "open"),
                "dropped_spans": trace.get("dropped_spans", 0),
            }
        by_id = {s.get("span_id"): s for s in spans if s.get("span_id")}
        starts = [
            s["start_ts"] for s in spans if s.get("start_ts") is not None
        ]
        t0 = min(starts) if starts else 0.0
        root_duration = out.get("duration_s")
        segments: dict[str, dict[str, Any]] = {}
        for span in spans:
            parent = span.get("parent_id")
            depth, seen, node = 0, set(), span
            while node is not None and node.get("parent_id") in by_id:
                pid = node["parent_id"]
                if pid in seen:
                    break  # defensive: a cycle off the wire must not hang
                seen.add(pid)
                node = by_id[pid]
                depth += 1
            span["depth"] = depth
            span["orphan"] = bool(parent) and parent not in by_id
            if span.get("start_ts") is not None:
                span["offset_s"] = round(span["start_ts"] - t0, 6)
            segment = (span.get("attributes") or {}).get("segment")
            if segment:
                agg = segments.setdefault(
                    str(segment), {"duration_s": 0.0, "count": 0}
                )
                agg["duration_s"] = round(
                    agg["duration_s"] + float(span.get("duration_s") or 0.0),
                    6,
                )
                agg["count"] += 1
        spans.sort(key=lambda s: (s.get("start_ts") or 0.0, s["depth"]))
        out["spans"] = spans
        out["span_count"] = len(spans)
        out["start_ts"] = t0 or None
        out["segments"] = segments
        if segments and root_duration:
            out["coverage"] = round(
                sum(s["duration_s"] for s in segments.values())
                / root_duration,
                4,
            )
        return out

    def dump(self) -> dict[str, Any]:
        """Everything, for the CI trace-store artifact."""
        with self._lock:
            kept_ids = list(self._kept)
        waterfalls = []
        for trace_id in kept_ids:
            wf = self.waterfall(trace_id)
            if wf is not None:
                waterfalls.append(wf)
        return {
            "ts": round(time.time(), 6),
            "index": self.index(),
            "traces": waterfalls,
        }

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._kept.clear()
            self._durations.clear()
            self._dropped.clear()
            self._slo_burning.clear()
            self.finalized = 0
            self.kept_total = 0


#: Process-wide store (fed once :func:`ensure_trace_store` ran).
TRACE_STORE = TraceStore()

_wired_lock = threading.Lock()
_wired = False


def ensure_trace_store() -> TraceStore:
    """Register the store on the event stream once; returns it."""
    global _wired
    with _wired_lock:
        if not _wired:
            _events.add_listener(TRACE_STORE.record_event)
            _wired = True
    return TRACE_STORE


def get_store() -> TraceStore | None:
    """The live store, or None when never wired (no listener overhead)."""
    return TRACE_STORE if _wired else None
