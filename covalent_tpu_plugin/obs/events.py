"""Structured event stream: one JSON object per line, append-only.

The reference's only record of a dispatch is transient ``app_log.debug``
breadcrumbs (SURVEY §5) — nothing machine-readable survives the process.
This sink gives every lifecycle edge a durable line: task-state
transitions, retries, dispatch failures (with the remote log tail
attached), pool/agent health, and completed spans all land in one JSONL
file that CI uploads as a build artifact and operators can grep or feed
to any log pipeline.

Configuration is one environment variable::

    COVALENT_TPU_EVENTS_PATH=/path/to/events.jsonl

Unset (the default) the stream is disabled and ``emit`` is a cheap no-op —
a single attribute check — so instrumented hot paths cost nothing in
production runs that don't ask for events.  ``configure(path)`` overrides
the environment for the current process (tests, embedding apps).

The stream is size-bounded for long-running fleets: once the file exceeds
``COVALENT_TPU_EVENTS_MAX_BYTES`` (default 64 MiB) it rotates shift-style
(``events.jsonl`` -> ``events.jsonl.1`` -> ``.2`` ...), keeping
``COVALENT_TPU_EVENTS_BACKUPS`` rotated files (default 2) so a dispatcher
that streams heartbeats for weeks cannot grow its event log without
limit.  Setting the byte bound to 0 disables rotation.

Every event carries ``ts`` (unix seconds), ``pid``, and ``type``; span
events additionally carry trace/span/parent ids so the JSONL doubles as a
flat trace export.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable

__all__ = ["EventSink", "get_sink", "configure", "emit", "add_listener",
           "remove_listener"]

_ENV_VAR = "COVALENT_TPU_EVENTS_PATH"
_MAX_BYTES_ENV = "COVALENT_TPU_EVENTS_MAX_BYTES"
_BACKUPS_ENV = "COVALENT_TPU_EVENTS_BACKUPS"
_DEFAULT_MAX_BYTES = 64 * 1024 * 1024
_DEFAULT_BACKUPS = 2


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


#: Event types that must survive a SIGKILL landing right after the emit:
#: flushed to the OS *and* fsynced to disk inline.  Everything else stays
#: flush-only — a killed process loses nothing (the page cache survives
#: it), and per-event fsync on the hot path would throttle dispatch.
_DURABLE_TYPES = frozenset(("slo.burn", "recovery.complete"))

#: ``task.state`` values that are progress edges, not terminal outcomes.
#: Any other state (completed/failed/cached/retried/fallback_local/...)
#: is a terminal record an operator must find on disk after ANY crash.
_PROGRESS_STATES = frozenset(("starting", "submitted", "running", "polling"))


def _durable_event(type: str, fields: dict) -> bool:
    if type in _DURABLE_TYPES:
        return True
    return type == "task.state" and (
        str(fields.get("state") or "") not in _PROGRESS_STATES
    )


class EventSink:
    """Thread-safe JSONL appender bound to one path (or disabled)."""

    def __init__(
        self,
        path: str | None,
        max_bytes: int | None = None,
        backups: int | None = None,
    ) -> None:
        self.path = path or None
        #: rotate once the file exceeds this many bytes (0 = never).
        self.max_bytes = (
            _env_int(_MAX_BYTES_ENV, _DEFAULT_MAX_BYTES)
            if max_bytes is None
            else int(max_bytes)
        )
        #: rotated generations kept (``path.1`` .. ``path.N``).
        self.backups = max(
            0,
            _env_int(_BACKUPS_ENV, _DEFAULT_BACKUPS)
            if backups is None
            else int(backups),
        )
        self._lock = threading.Lock()
        self._fh = None
        self._failed = False

    @property
    def enabled(self) -> bool:
        return self.path is not None and not self._failed

    def emit(self, type: str, **fields: Any) -> dict | None:
        """Append one event; returns the event dict, or None when disabled.

        Never raises: an unwritable path disables the sink after one
        warning rather than failing the dispatch it was observing.
        """
        if not self.enabled and not _listeners:
            return None  # disabled and unobserved: build nothing
        event = {"ts": round(time.time(), 6), "pid": os.getpid(),
                 "type": type, **fields}
        for listener in list(_listeners):
            try:
                listener(event)
            except Exception:  # noqa: BLE001 - observers must not break flow
                pass
        if not self.enabled:
            return event if _listeners else None
        try:
            line = json.dumps(event, default=repr) + "\n"
        except (TypeError, ValueError):
            line = json.dumps({"ts": event["ts"], "pid": event["pid"],
                               "type": type, "repr": repr(fields)}) + "\n"
        with self._lock:
            try:
                if self._fh is None:
                    parent = os.path.dirname(self.path)
                    if parent:
                        os.makedirs(parent, exist_ok=True)
                    self._fh = open(self.path, "a", encoding="utf-8")
                self._fh.write(line)
                self._fh.flush()
                if _durable_event(type, fields):
                    os.fsync(self._fh.fileno())
                if self.max_bytes > 0 and self._fh.tell() >= self.max_bytes:
                    self._rotate_locked()
            except OSError as err:
                self._failed = True
                from ..utils.log import app_log

                app_log.warning(
                    "event sink %s unwritable (%s); events disabled", self.path, err
                )
                return None
        return event

    def _rotate_locked(self) -> None:
        """Shift-rotate ``path`` -> ``path.1`` -> ... (caller holds _lock).

        With ``backups == 0`` the file is simply truncated: bounded either
        way.  A rotation failure is swallowed — the stream keeps appending
        to the (oversized) live file rather than dying mid-dispatch.
        """
        self._fh.close()
        self._fh = None
        try:
            for i in range(self.backups - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            if self.backups > 0:
                os.replace(self.path, f"{self.path}.1")
            else:
                os.truncate(self.path, 0)
        except OSError:
            pass
        self._fh = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


_sink_lock = threading.Lock()
_sink: EventSink | None = None
#: In-process observers (tests, bench live tailers): called with every
#: event dict even when no JSONL path is configured.
_listeners: list[Callable[[dict], None]] = []


def get_sink() -> EventSink:
    """The process-wide sink, built lazily from the environment."""
    global _sink
    sink = _sink
    if sink is not None:
        return sink
    with _sink_lock:
        if _sink is None:
            _sink = EventSink(os.environ.get(_ENV_VAR) or None)
        return _sink


def configure(path: str | None) -> EventSink:
    """Re-point the process-wide sink (None disables).  Returns the sink."""
    global _sink
    with _sink_lock:
        if _sink is not None:
            _sink.close()
        _sink = EventSink(path)
        return _sink


def reset() -> EventSink:
    """Rebuild the sink from the environment, undoing any configure().

    Callers that temporarily re-point the stream (tests, embedders) use
    this on teardown so a process-wide ``COVALENT_TPU_EVENTS_PATH`` —
    e.g. CI's telemetry artifact — resumes collecting afterwards.
    """
    global _sink
    with _sink_lock:
        if _sink is not None:
            _sink.close()
        _sink = None
    return get_sink()


def emit(type: str, **fields: Any) -> dict | None:
    """Module-level shorthand: ``events.emit("task.state", op=..., to=...)``.

    The disabled-and-unobserved case is the production default, so it
    short-circuits on one cached-global read — no lock, no dict build.
    """
    sink = _sink
    if sink is None:
        sink = get_sink()
    if not sink.enabled and not _listeners:
        return None
    return sink.emit(type, **fields)


def add_listener(listener: Callable[[dict], None]) -> None:
    _listeners.append(listener)


def remove_listener(listener: Callable[[dict], None]) -> None:
    try:
        _listeners.remove(listener)
    except ValueError:
        pass
