"""Worker liveness from streamed heartbeats: the fleet's pulse.

Each worker harness beats on a fixed cadence (``heartbeat_s`` in the task
spec): a ``worker.heartbeat`` event carrying a monotonically increasing
``seq``, the process RSS, optional jax device-memory stats, and whatever
progress metrics the user function published (step counter, tokens/s).
Those beats reach the dispatcher by two roads — the agent channel's
telemetry side-band (push, near-real-time) or a heartbeat snapshot file
piggybacked on the status-probe round trip (poll path) — and both feed the
process-wide :data:`MONITOR` here.

The monitor answers the two questions the fleet plane needs:

* **liveness** — :meth:`HeartbeatMonitor.stalled` names workers that have
  beaten at least once and then fallen silent past their stall threshold,
  which the executor classifies as a ``worker_stalled`` transient (gang
  teardown + retry) *before* the hard ``task_timeout`` fires;
* **visibility** — :meth:`HeartbeatMonitor.snapshot` is the per-worker
  last-heartbeat view the ops ``/status`` endpoint serves while an
  electron runs.

Dedup is by ``seq``: the poll path re-reads the same snapshot file every
probe and the agent path re-tails the telemetry file from offset 0 after a
reconnect, so :meth:`record` reports whether a beat was *fresh* and only
fresh beats move the metrics below.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from .metrics import REGISTRY

__all__ = ["HeartbeatMonitor", "MONITOR"]

HEARTBEATS_TOTAL = REGISTRY.counter(
    "covalent_tpu_worker_heartbeats_total",
    "Fresh worker heartbeats received by the dispatcher",
    ("worker",),
)
_WORKER_STEP = REGISTRY.gauge(
    "covalent_tpu_worker_step",
    "Latest step counter a worker's heartbeat reported",
    ("worker",),
)
_WORKER_RSS = REGISTRY.gauge(
    "covalent_tpu_worker_rss_bytes",
    "Latest resident-set size a worker's heartbeat reported",
    ("worker",),
)
STALLS_TOTAL = REGISTRY.counter(
    "covalent_tpu_worker_stalls_total",
    "Workers declared stalled after missing their heartbeat deadline",
    ("worker",),
)


class HeartbeatMonitor:
    """Last-heartbeat bookkeeping per (operation, worker).

    Thread-safe: beats arrive on the dispatcher event loop (agent
    telemetry, status probes) while the ops server thread reads snapshots.
    ``clock`` is injectable for deterministic stall tests.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        #: operation_id -> {"stall_after": s, "started": clock()}
        self._ops: dict[str, dict[str, Any]] = {}
        #: (operation_id, worker) -> {"at": clock(), "seq": n, "hb": {...}}
        self._beats: dict[tuple[str, str], dict[str, Any]] = {}

    # -- lifecycle ---------------------------------------------------------

    #: Floor on the never-beat deadline: a cold harness pays interpreter
    #: startup + imports before its first beat, and that launch window must
    #: never read as a stall however tight the configured threshold is.
    LAUNCH_SLACK_S = 10.0

    def watch(
        self,
        operation_id: str,
        stall_after: float,
        workers: "tuple[str, ...] | list[str]" = (),
        interval: float = 0.0,
        launch_slack: float | None = None,
    ) -> None:
        """Start liveness bookkeeping for one dispatch attempt.

        ``stall_after`` is the silence (seconds since the last beat) after
        which a worker that has beaten before counts as stalled; <= 0
        disables stall detection for the operation (beats still record for
        the ``/status`` view).  ``workers`` names the processes EXPECTED
        to beat: one that never beats at all within
        ``max(stall_after + interval, launch_slack)`` of this call is
        equally stalled — a harness can wedge before its first beat lands
        (e.g. frozen mid-write), and silence-from-birth must not be
        blindness.
        """
        slack = self.LAUNCH_SLACK_S if launch_slack is None else launch_slack
        with self._lock:
            self._ops[operation_id] = {
                "stall_after": float(stall_after),
                "nobeat_after": max(
                    float(stall_after) + float(interval), float(slack)
                ),
                "workers": tuple(workers),
                "started": self._clock(),
            }

    def forget(self, operation_id: str) -> None:
        with self._lock:
            self._ops.pop(operation_id, None)
            for key in [k for k in self._beats if k[0] == operation_id]:
                del self._beats[key]

    # -- recording ---------------------------------------------------------

    def record(
        self, operation_id: str, worker: str, heartbeat: dict
    ) -> bool:
        """File one heartbeat; returns True when it is *fresh* (new seq).

        Duplicate deliveries (snapshot-file re-reads, telemetry re-tails
        after reconnect) are identified by ``seq`` and do not refresh the
        liveness clock — a stalled worker whose stale snapshot keeps being
        re-read must still go stale here.
        """
        seq = heartbeat.get("seq")
        key = (operation_id, worker)
        with self._lock:
            last = self._beats.get(key)
            if last is not None and seq is not None and seq <= last["seq"]:
                return False
            now = self._clock()
            # Inter-arrival statistics (EWMA mean + variance) feed the
            # jitter-adaptive stall threshold in :meth:`stalled` — a noisy
            # scheduler that delivers beats erratically widens its own
            # deadline instead of tripping a false stall.
            gap_mean = gap_var = 0.0
            gap_n = 0
            if last is not None:
                gap = now - last["at"]
                gap_mean = last.get("gap_mean", 0.0)
                gap_var = last.get("gap_var", 0.0)
                gap_n = last.get("gap_n", 0)
                if gap_n == 0:
                    gap_mean = gap
                else:
                    dev = gap - gap_mean
                    gap_mean += 0.3 * dev
                    gap_var += 0.3 * (dev * dev - gap_var)
                gap_n += 1
            self._beats[key] = {
                "at": now,
                "seq": seq if seq is not None else -1,
                "hb": dict(heartbeat),
                "gap_mean": gap_mean,
                "gap_var": gap_var,
                "gap_n": gap_n,
            }
        HEARTBEATS_TOTAL.labels(worker=worker).inc()
        step = heartbeat.get("step")
        if isinstance(step, (int, float)):
            _WORKER_STEP.labels(worker=worker).set(step)
        rss = heartbeat.get("rss_bytes")
        if isinstance(rss, (int, float)):
            _WORKER_RSS.labels(worker=worker).set(rss)
        return True

    # -- queries -----------------------------------------------------------

    def last(self, operation_id: str) -> dict[str, dict[str, Any]]:
        """worker -> {"age_s", "seq", **last heartbeat} for one operation."""
        now = self._clock()
        with self._lock:
            return {
                worker: {
                    "age_s": round(now - entry["at"], 3),
                    "seq": entry["seq"],
                    **entry["hb"],
                }
                for (op, worker), entry in self._beats.items()
                if op == operation_id
            }

    #: Beats observed before the adaptive threshold kicks in; below this
    #: the configured ``stall_after`` applies unmodified.
    ADAPTIVE_MIN_BEATS = 3
    #: Standard deviations of inter-arrival jitter tolerated on top of
    #: the observed cadence before silence reads as a stall.
    ADAPTIVE_K = 4.0

    def effective_stall_after(
        self, operation_id: str, worker: str
    ) -> float:
        """Jitter-adaptive stall threshold for one worker.

        The configured ``stall_after`` (3x the heartbeat interval by
        default) is a *floor*, never a ceiling: once a worker has beaten
        enough times to characterize its own cadence, the threshold grows
        to ``3 x observed-mean-gap + K x observed-std`` so a worker whose
        beats arrive erratically — loaded host, noisy scheduler, CI
        machine — widens its own deadline instead of tripping a false
        stall.  A genuinely wedged worker still trips: its silence keeps
        growing while the learned statistics stay frozen.
        """
        with self._lock:
            op = self._ops.get(operation_id)
            configured = float(op["stall_after"]) if op else 0.0
            entry = self._beats.get((operation_id, worker))
        if configured <= 0:
            return configured
        if not entry or entry.get("gap_n", 0) < self.ADAPTIVE_MIN_BEATS:
            return configured
        std = max(0.0, entry.get("gap_var", 0.0)) ** 0.5
        adaptive = 3.0 * entry.get("gap_mean", 0.0) + self.ADAPTIVE_K * std
        return max(configured, adaptive)

    def stalled(self, operation_id: str) -> list[tuple[str, float]]:
        """``(worker, silence_s)`` for workers past their stall deadline.

        Two ways to stall: a worker that beat and went silent for its
        jitter-adaptive threshold (:meth:`effective_stall_after` — floored
        at the configured ``stall_after``); and an *expected* worker
        (named in :meth:`watch`) that never beat at all within the
        no-beat deadline (``max(stall_after + interval, launch_slack)``).
        An operation whose expected set was not declared only gets the
        first kind, so a task with heartbeats disabled is never killed by
        a detector it cannot feed.

        This is a *suspicion*, not a verdict: the executor confirms
        against the worker's snapshot file before acting (and counts
        ``covalent_tpu_worker_stalls_total`` only for confirmed stalls).
        """
        now = self._clock()
        with self._lock:
            op = self._ops.get(operation_id)
            if op is None or op["stall_after"] <= 0:
                return []
            out = []
            beaten = set()
            for (o, worker), entry in self._beats.items():
                if o != operation_id:
                    continue
                beaten.add(worker)
                threshold = op["stall_after"]
                if entry.get("gap_n", 0) >= self.ADAPTIVE_MIN_BEATS:
                    std = max(0.0, entry.get("gap_var", 0.0)) ** 0.5
                    threshold = max(
                        threshold,
                        3.0 * entry.get("gap_mean", 0.0)
                        + self.ADAPTIVE_K * std,
                    )
                if now - entry["at"] < threshold:
                    continue
                out.append((worker, round(now - entry["at"], 3)))
            silence = now - op["started"]
            if silence >= op.get("nobeat_after", float("inf")):
                for worker in op.get("workers", ()):
                    if worker not in beaten:
                        out.append((worker, round(silence, 3)))
        return out

    def snapshot(self) -> dict[str, dict[str, dict[str, Any]]]:
        """operation_id -> worker -> last-heartbeat view (ops ``/status``)."""
        with self._lock:
            ops = set(self._ops) | {op for op, _ in self._beats}
        return {op: self.last(op) for op in sorted(ops)}


#: Process-wide monitor every dispatch path records into.
MONITOR = HeartbeatMonitor()
