"""Remote execution harness — runs ON each TPU-VM worker.

TPU-native counterpart of the reference's ``covalent_ssh_plugin/exec.py``
template.  Two structural changes:

* The reference ``.format()``-instantiates its script per task
  (``ssh.py:160-171``), which forbids literal braces anywhere in the file
  (``exec.py`` header comment).  This harness is instead a *static* module
  copied verbatim to the worker and invoked as
  ``python harness.py <task_spec.json>`` — all per-task parameters travel in
  a small JSON spec, so one upload is reusable and the brace constraint
  disappears.
* Before touching the pickled function it wires up the multi-host data
  plane: ``jax.distributed.initialize(coordinator_address, num_processes,
  process_id)`` (SURVEY §2.4), then after the task it materialises device
  arrays to host memory and lets only process 0 write the result pickle.

The file protocol is otherwise the reference's: read ``(fn, args, kwargs)``
(``exec.py:29-30``), chdir into the task workdir (``exec.py:33-35``), run the
function catching any exception (``exec.py:37-40``), always write the
``(result, exception)`` pair (``exec.py:45-46``) — written atomically via a
temp file + rename so the dispatcher's status probe never sees a torn file.

MUST remain standalone: stdlib + cloudpickle (+ jax when present) only, since
it runs on workers where this package is not installed.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


#: Per-process worker-event sequence + write-failure accounting.  The seq
#: lets the dispatcher dedup re-delivered lines (the telemetry side-band
#: re-tails from offset 0 after a reconnect) and is shared by EVERY worker
#: record — lifecycle events, streamed heartbeats, and the heartbeat
#: snapshot file all draw from one locked counter, so the dispatcher's
#: seq-based dedup compares a single monotonic domain whichever road a
#: record arrives by.  The failure counter backs the swallow-and-count
#: contract — an unwritable/ENOSPC events path must never take down the
#: task it was observing, but the first failure leaves one line on stderr
#: so the silence is diagnosable from the task log.
_worker_event_seq = 0
_worker_event_lock = threading.Lock()
_worker_event_failures = 0


def _build_worker_event(spec: dict, type: str, **fields) -> dict:
    """One worker record: ts/pid/seq envelope + trace context + fields.

    The single assembly point for every worker-side record (events and
    heartbeat snapshots alike), so the schema cannot drift between sinks
    and the seq counter stays atomic under the heartbeat thread.
    """
    global _worker_event_seq
    with _worker_event_lock:
        _worker_event_seq += 1
        seq = _worker_event_seq
    trace = spec.get("trace") or {}
    event = {
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "seq": seq,
        "type": type,
        "operation_id": spec.get("operation_id"),
    }
    if trace.get("trace_id"):
        event["trace_id"] = trace.get("trace_id")
        event["parent_id"] = trace.get("span_id")
        if trace.get("attempt") is not None:
            event["attempt"] = trace.get("attempt")
    event.update(fields)
    return event


def _append_event_line(event: dict, paths: list) -> None:
    """Swallow-and-count JSONL append of one event to every sink path."""
    global _worker_event_failures
    try:
        line = json.dumps(event, default=repr) + "\n"
    except (TypeError, ValueError):
        return
    for path in paths:
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(line)
        except OSError as err:
            _worker_event_failures += 1
            if _worker_event_failures == 1:
                print(
                    f"worker events unwritable ({path}: {err}); "
                    "further failures swallowed",
                    file=sys.stderr,
                )


def _worker_event_paths(spec: dict) -> list:
    """Every sink one worker event lands in (deduped, order-stable).

    ``events_file`` is the dispatcher's own stream (shared filesystem);
    ``telemetry_file`` is the per-task side-band the resident agent tails
    back over its channel.  Heartbeats go to the telemetry file only (see
    ``_start_heartbeat``); lifecycle events go to both.
    """
    paths = []
    for key in ("events_file", "telemetry_file"):
        path = spec.get(key)
        if path and path not in paths:
            paths.append(path)
    env_path = os.environ.get("COVALENT_TPU_EVENTS_PATH")
    if not paths and env_path:
        paths.append(env_path)
    return paths


def _emit_worker_event(spec: dict, type: str, _paths=None, **fields) -> None:
    """Append one structured JSONL event from the worker side.

    Mirrors the dispatcher's ``obs.events`` line format (ts/pid/type) but
    stays stdlib-only — this file runs on workers where the plugin is not
    installed.  Sink paths come from the spec (``events_file`` /
    ``telemetry_file``, set by the stager) or the worker's own
    ``COVALENT_TPU_EVENTS_PATH``; no path means no-op.  Trace context from
    the spec (``trace``: trace/parent span ids + attempt) is stamped on
    every event so worker-side records join the dispatch trace.

    Never raises: write failures are swallowed and counted, with a single
    stderr note on the first one — an ENOSPC events disk must not fail the
    electron it was observing.
    """
    paths = _worker_event_paths(spec) if _paths is None else _paths
    if not paths:
        return
    _append_event_line(_build_worker_event(spec, type, **fields), paths)


def _heartbeat_payload(metrics_file: str) -> dict:
    """One heartbeat's body: process vitals + user-published progress.

    Everything best-effort and stdlib-only.  The user function publishes
    progress (step counter, examples/s, tokens/s, ...) by writing a small
    JSON object to ``$COVALENT_TPU_WORKER_METRICS_PATH``; the beat folds it
    in verbatim.  jax device-memory stats are read ONLY when the task
    already imported jax AND a backend is live — the heartbeat thread must
    never be the thing that triggers (or races) backend initialization.
    """
    payload: dict = {}
    try:
        import resource

        usage = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS.
        scale = 1 if sys.platform == "darwin" else 1024
        payload["rss_bytes"] = int(usage.ru_maxrss) * scale
        payload["cpu_s"] = round(usage.ru_utime + usage.ru_stime, 3)
    except Exception:  # noqa: BLE001 - vitals are best-effort
        pass
    if metrics_file:
        try:
            with open(metrics_file, encoding="utf-8") as f:
                user = json.load(f)
            if isinstance(user, dict):
                step = user.pop("step", None)
                if isinstance(step, (int, float)):
                    payload["step"] = step
                if user:
                    payload["metrics"] = user
        except (OSError, ValueError):
            pass
    if "jax" in sys.modules:
        try:
            import jax

            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                device = jax.local_devices()[0]
                stats = device.memory_stats() or {}
                mem = {
                    k: stats[k]
                    for k in ("bytes_in_use", "peak_bytes_in_use")
                    if k in stats
                }
                if mem:
                    payload["device_mem"] = mem
        except Exception:  # noqa: BLE001 - absent on CPU backends
            pass
    return payload


def _start_heartbeat(spec: dict):
    """Launch the heartbeat thread; returns a stop Event (or None).

    Cadence comes from the spec's ``heartbeat_s`` (0/absent disables).
    Each beat does two things:

    * emits a ``worker.heartbeat`` event into the *telemetry* side-band
      file (never the shared lifecycle stream — beats are high-volume
      plumbing, not dispatch history), which the resident agent tails
      back to the dispatcher in near-real-time;
    * atomically refreshes a tiny snapshot file (``<pid_file>.hb``) that
      the dispatcher's status probe reads piggybacked on its existing
      round trip, so the poll path gets liveness for free.

    The first beat fires immediately so even sub-second electrons leave
    one, and the dispatcher's stall detector has a baseline to age.
    """
    try:
        interval = float(spec.get("heartbeat_s") or 0)
    except (TypeError, ValueError):
        interval = 0.0
    if interval <= 0:
        return None
    # Resolve every side-band path to absolute BEFORE the task chdirs into
    # its workdir: the beat thread runs concurrently with the chdir'd
    # function, and a relative remote_cache would otherwise scatter
    # snapshots across working directories.
    pid_file = spec.get("pid_file")
    hb_file = os.path.abspath(f"{pid_file}.hb") if pid_file else None
    metrics_file = (
        os.path.abspath(f"{pid_file}.metrics") if pid_file else ""
    )
    if metrics_file:
        # The user function's progress-publishing hook.
        os.environ["COVALENT_TPU_WORKER_METRICS_PATH"] = metrics_file
    telemetry_paths = [
        os.path.abspath(p) for p in (spec.get("telemetry_file"),) if p
    ]
    stop = threading.Event()

    def beat_loop() -> None:
        hb_seq = 0
        while True:
            hb_seq += 1
            # ONE event, one seq, two sinks: the streamed telemetry line
            # and the probe-read snapshot must be the same record so the
            # dispatcher's seq dedup works across delivery roads (e.g. an
            # agent-channel death downgrading to the polling path).
            event = _build_worker_event(
                spec, "worker.heartbeat",
                hb_seq=hb_seq, interval_s=interval,
                **_heartbeat_payload(metrics_file),
            )
            if telemetry_paths:
                _append_event_line(event, telemetry_paths)
            if hb_file:
                try:
                    tmp = f"{hb_file}.tmp.{os.getpid()}"
                    with open(tmp, "w", encoding="utf-8") as f:
                        f.write(json.dumps(event, default=repr))
                    os.replace(tmp, hb_file)
                except (OSError, TypeError, ValueError):
                    pass  # liveness reporting must never fail the task
            if stop.wait(interval):
                return

    thread = threading.Thread(
        target=beat_loop, name="covalent-tpu-heartbeat", daemon=True
    )
    thread.start()
    return stop


def install_pip_deps(pip_deps: list) -> None:
    """Install an electron's pip dependencies; raise RuntimeError on failure.

    Shared contract between this worker harness and the in-process
    LocalExecutor (reference ct.DepsPip, svm_workflow.py:6,19).  The
    command is overridable via ``COVALENT_TPU_PIP_CMD`` for sandboxed test
    environments.
    """
    import shlex
    import subprocess

    pip_cmd = shlex.split(
        os.environ.get("COVALENT_TPU_PIP_CMD", "")
    ) or [sys.executable, "-m", "pip", "install"]
    proc = subprocess.run(
        pip_cmd + list(pip_deps), capture_output=True, text=True
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"pip dependency install failed "
            f"({' '.join(pip_deps)}): {proc.stderr.strip()}"
        )


def _fallback_result(result_file: str, error: BaseException) -> None:
    """Best-effort ``(None, error)`` write with stdlib pickle, mirroring the
    reference's cloudpickle-ImportError path (``exec.py:16-24``)."""
    import pickle

    tmp = result_file + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump((None, error), f)
    os.replace(tmp, result_file)


def _to_host(tree):
    """Materialise jax arrays onto the host before pickling."""
    # If the task never imported jax there can be no device arrays in the
    # result — skip the (multi-second) jax import entirely.
    if "jax" not in sys.modules:
        return tree
    try:
        import jax
    except Exception:
        return tree
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return jax.tree_util.tree_map(
        lambda x: jax.device_get(x) if hasattr(x, "devices") else x, tree
    )


def _apply_spec_env(spec: dict) -> None:
    """Apply the task's env contract to THIS process.

    os.environ entries, a sys.path mirror for PYTHONPATH, and the jax
    platform pin.  Shared by the per-task harness (``run_task``) and RPC
    invocations executing inside the resident server — one server serves
    one executor, so ``task_env`` is constant across its invocations and
    the process-wide mutation is idempotent by construction.
    """
    env = spec.get("env") or {}
    for key, value in env.items():
        os.environ[key] = str(value)
    if "PYTHONPATH" in env:
        # The interpreter already started; os.environ alone no longer affects
        # import resolution.  Mirror the entries into sys.path so task_env
        # PYTHONPATH means what users expect.
        for entry in reversed(str(env["PYTHONPATH"]).split(os.pathsep)):
            if entry and entry not in sys.path:
                sys.path.insert(0, entry)
    # Env alone can lose to a site-level PJRT plugin registration that
    # re-pins the platform after interpreter start; jax.config wins if set
    # before first backend use.  Pin from the spec env always (explicit user
    # intent, worth the jax import), and from the inherited process env only
    # when a sitecustomize already imported jax — then the pin is free and
    # protects every subprocess on hosts whose site hook overrides the env.
    if "JAX_PLATFORMS" in env:
        platforms = env["JAX_PLATFORMS"]  # explicit, even "" = auto-select
    elif "jax" in sys.modules:
        platforms = os.environ.get("JAX_PLATFORMS")
    else:
        platforms = None
    if platforms is not None:
        try:
            import jax

            jax.config.update("jax_platforms", str(platforms))
        except Exception:
            pass


def run_task(spec: dict) -> int:
    """Execute one staged task described by ``spec``.  Returns the exit code."""
    result_file = spec["result_file"]

    pid_file = spec.get("pid_file")
    if pid_file:
        # First thing, before any failure mode: the dispatcher's orphan
        # cleanup kills by this pid when a launch channel dies mid-submit
        # (a pool fork keeps the server's cmdline, so pkill can't find it).
        # Atomic write: a reader must never observe an empty pid file.
        tmp_pid = f"{pid_file}.tmp.{os.getpid()}"
        with open(tmp_pid, "w") as f:
            f.write(str(os.getpid()))
        os.replace(tmp_pid, pid_file)

    _apply_spec_env(spec)

    distributed = spec.get("distributed")
    process_id = int(distributed["process_id"]) if distributed else 0
    _emit_worker_event(spec, "worker.task_started", process_id=process_id)
    # Liveness starts before any blocking stage (pip install, distributed
    # barrier, the task itself): a worker hung anywhere keeps beating —
    # and one that goes silent is genuinely wedged.
    heartbeat_stop = _start_heartbeat(spec)

    pip_deps = spec.get("pip_deps") or []
    if pip_deps:
        # Install BEFORE loading the function pickle — unpickling may import
        # the dependency (reference ct.DepsPip, svm_workflow.py:6,19).  A
        # non-zero process that fails here exits 1 *before* the distributed
        # barrier; the dispatcher's poller watches every worker's liveness
        # and fails the task fast instead of letting process 0 hang in
        # jax.distributed.initialize.
        try:
            install_pip_deps(pip_deps)
        except RuntimeError as pip_error:
            _emit_worker_event(
                spec, "worker.task_finished", process_id=process_id,
                ok=False, error=repr(pip_error),
            )
            if process_id == 0:
                _fallback_result(result_file, pip_error)
            return 1

    try:
        import cloudpickle as pickle
    except ImportError as import_error:
        if process_id == 0:
            _fallback_result(result_file, import_error)
        return 1

    expected_digest = spec.get("function_digest")
    if expected_digest:
        # The function file is a content-addressed (CAS) artifact: verify
        # its bytes against the digest the dispatcher staged before
        # unpickling, so a torn upload or stale cache entry fails loud
        # instead of executing the wrong payload.  Runs BEFORE the
        # distributed barrier so a bad artifact on any worker fails fast
        # with correct blame instead of hanging process 0 in initialize.
        import hashlib

        sha = hashlib.sha256()
        with open(spec["function_file"], "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                sha.update(chunk)
        if sha.hexdigest() != expected_digest:
            digest_error = RuntimeError(
                f"staged function {spec['function_file']} does not match "
                f"its content digest (torn or stale CAS artifact)"
            )
            _emit_worker_event(
                spec, "worker.task_finished", process_id=process_id,
                ok=False, error=repr(digest_error),
            )
            if process_id == 0:
                _fallback_result(result_file, digest_error)
            return 1

    if distributed:
        # Data-plane bootstrap: after this, in-electron jax code sees every
        # chip in the slice and XLA collectives ride ICI/DCN (SURVEY §2.4).
        import jax

        jax.distributed.initialize(
            coordinator_address=distributed["coordinator_address"],
            num_processes=int(distributed["num_processes"]),
            process_id=process_id,
        )

    with open(spec["function_file"], "rb") as f:
        fn, args, kwargs = pickle.load(f)

    # Optional device-level tracing (SURVEY §5: the reference captures no
    # timings at all; this surfaces the XLA/TPU view of the electron).  The
    # trace lands in the task workdir/cache so the dispatcher can scp it.
    profile_dir = spec.get("profile_dir")
    profiling = False
    if profile_dir:
        try:
            import jax

            jax.profiler.start_trace(profile_dir)
            profiling = True
        except Exception as profile_error:  # pragma: no cover - best effort
            print(f"profiler unavailable: {profile_error}", file=sys.stderr)

    workdir = spec.get("workdir")
    current_dir = os.getcwd()
    result, exception = None, None
    try:
        if workdir:
            os.makedirs(workdir, exist_ok=True)
            os.chdir(workdir)
        result = fn(*args, **kwargs)
        result = _to_host(result)
    except Exception as task_error:  # noqa: BLE001 - transported to dispatcher
        exception = task_error
    finally:
        os.chdir(current_dir)
        if profiling:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass

    # Replicated outputs: one writer suffices (process 0); the others emit a
    # done-marker the control plane can watch for all-workers-finished.
    if process_id == 0:
        tmp = result_file + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump((result, exception), f)
        os.replace(tmp, result_file)
    else:
        done = f"{result_file}.done.{process_id}"
        with open(done, "w") as f:
            f.write("done\n")

    if heartbeat_stop is not None:
        heartbeat_stop.set()
    _emit_worker_event(
        spec, "worker.task_finished", process_id=process_id,
        ok=exception is None,
        **({"error": repr(exception)} if exception is not None else {}),
    )
    return 0


# --------------------------------------------------------------------------
# Pool (forkserver) mode: `python harness.py --serve`
#
# One resident interpreter per worker, heavy imports preloaded ONCE, then a
# fork per task — the per-electron cost collapses from interpreter startup +
# imports (seconds) to a fork (milliseconds).  Speaks the same newline-JSON
# protocol as the native C++ agent (native/agent.cc) so the dispatcher
# drives both through one client, with `spec` instead of `argv`:
#
#   -> {"cmd":"run","id":"op","spec":"/path/spec.json","log":"/path/log"}
#   <- {"event":"started","id":"op","pid":123}
#   <- {"event":"exit","id":"op","code":0,"signal":0}
#
# Telemetry side-band: the dispatcher asks the server to tail a task's
# worker-local JSONL file (heartbeats + worker events) back over the same
# channel, turning post-mortem log files into a near-real-time stream:
#
#   -> {"cmd":"watch","id":"op","path":"/path/telemetry.jsonl"}
#   <- {"event":"watching","id":"op"}
#   <- {"event":"telemetry","id":"op","data":{...}}        (per line, pushed)
#   -> {"cmd":"unwatch","id":"op"}
#   <- {"event":"unwatched","id":"op"}
#
# A watch always starts from offset 0, so events buffered in the file while
# a channel was down are flushed on the reconnecting client's re-watch; the
# dispatcher dedups by each event's `seq`.
#
# Fork-safety: the parent preloads modules (cloudpickle, jax, ...) but never
# initializes an XLA backend or runs a computation — backend init happens in
# each child, which is the documented-safe pattern (import before fork, use
# after).  Children setsid into their own sessions, so they survive a pool/
# channel death exactly like the other launch paths, and the dispatcher can
# fall back to pid polling.
# --------------------------------------------------------------------------


#: Serializes protocol writes: the serve loop, RPC invocation threads, and
#: their heartbeat threads all share one stdout channel, and an interleaved
#: write would corrupt the line protocol.
_EMIT_LOCK = threading.Lock()


def _emit(obj: dict) -> None:
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()


def _spawn_task(command: dict, children: dict) -> None:
    task_id = command.get("id")
    spec_path = command.get("spec")
    if not task_id or not spec_path:
        _emit({"event": "error", "id": task_id or "",
               "message": "run requires id and spec"})
        return
    sys.stdout.flush()
    pid = os.fork()
    if pid == 0:
        rc = 1
        try:
            # Fork-safety: an RPC invocation/heartbeat thread may hold the
            # event or emit lock at fork time, and the child inherits the
            # locked state with no thread to ever release it — fresh locks
            # make the child's own event writes deadlock-free.
            global _worker_event_lock, _EMIT_LOCK
            _worker_event_lock = threading.Lock()
            _EMIT_LOCK = threading.Lock()
            import signal as _signal

            _signal.set_wakeup_fd(-1)
            _signal.signal(_signal.SIGCHLD, _signal.SIG_DFL)
            os.setsid()
            log_fd = os.open(
                command.get("log") or os.devnull,
                os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                0o644,
            )
            devnull = os.open(os.devnull, os.O_RDONLY)
            os.dup2(devnull, 0)
            os.dup2(log_fd, 1)
            os.dup2(log_fd, 2)
            with open(spec_path) as f:
                spec = json.load(f)
            rc = run_task(spec)
        except BaseException:  # noqa: BLE001 - child must never return
            import traceback

            traceback.print_exc()
        finally:
            os._exit(rc)
    children[pid] = task_id
    _emit({"event": "started", "id": task_id, "pid": pid})


# --------------------------------------------------------------------------
# RPC execute-by-digest: the resident executor loop.
#
# Launch mode (above) pays a fork + interpreter state per electron and
# stages args/results through remote disk.  RPC mode keeps the *work* in
# the resident interpreter too: the dispatcher ships the cloudpickled
# function ONCE per connection into the CAS, registers it by digest, and
# thereafter invokes by digest with args inline on the channel — results
# stream back base64-pickled over the same channel.  No per-electron
# process, no pid file, no poll loop, no result file:
#
#   -> {"cmd":"register_fn","digest":"<sha256>","path":"/cas/<sha256>.pkl"}
#   <- {"event":"registered","digest":"<sha256>"}
#   <- {"event":"register_error","digest":"...","code":"digest_mismatch"|
#       "missing"|"load_failed","message":"..."}           (on failure)
#   -> {"cmd":"invoke","id":"<op>","digest":"<sha256>","spec":{...},
#       "args":"<b64 cloudpickle (args, kwargs)>"}            (inline)
#       ... or "args_path"/"args_digest" for oversized args staged in the
#       CAS (digest verified before unpickling, like the function itself)
#   <- {"event":"started","id":"<op>","pid":<server pid>,"rpc":true}
#   <- {"event":"telemetry","id":"<op>","data":{...}}   (task events +
#       heartbeats, same schema/trace contract as launch-mode workers)
#   <- {"event":"result","id":"<op>","ok":true,"data":"<b64 pickle of
#       (result, exception)>"}
#
# Registration digest-verifies the CAS artifact BEFORE unpickling (the
# same torn-payload guard run_task applies) and unpickles once; each
# invocation runs on a daemon thread so the command loop stays live and
# concurrent invocations share the warm imports.  A crash that takes the
# resident process down surfaces to the dispatcher as a channel death —
# classified transient, gang retried, function re-registered.
# --------------------------------------------------------------------------


def _load_fn_payload(path: str, digest: str):
    """``(code, fn_or_error)``: digest-verified CAS bytes -> callable."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as err:
        return "missing", err
    import hashlib

    if hashlib.sha256(data).hexdigest() != digest:
        return "digest_mismatch", RuntimeError(
            f"registered function {path} does not match its content digest "
            "(torn or stale CAS artifact)"
        )
    try:
        import cloudpickle

        return "", cloudpickle.loads(data)
    except BaseException as err:  # noqa: BLE001 - arbitrary user payloads
        return "load_failed", err


def _rpc_register(command: dict, registry: dict) -> None:
    digest = command.get("digest")
    path = command.get("path")
    if not digest or not path:
        _emit({"event": "error", "message": "register_fn requires digest and path"})
        return
    if digest in registry:  # idempotent: re-register is a no-op ack
        _emit({"event": "registered", "digest": digest})
        return
    code, loaded = _load_fn_payload(path, digest)
    if code:
        _emit({
            "event": "register_error", "digest": digest,
            "code": code, "message": repr(loaded),
        })
        return
    registry[digest] = loaded
    _emit({"event": "registered", "digest": digest})


def _decode_rpc_args(command: dict) -> tuple:
    """``(args, kwargs)`` from the invoke command (inline b64 or CAS path).

    CAS-staged args are digest-verified before unpickling — oversized
    payloads keep the same torn-artifact guard inline ones get for free
    (the channel delivered the exact bytes the dispatcher encoded).
    """
    import base64

    import cloudpickle

    b64 = command.get("args")
    if b64 is not None:
        data = base64.b64decode(b64)
    else:
        path = command.get("args_path")
        if not path:
            return (), {}
        with open(path, "rb") as f:
            data = f.read()
        expected = command.get("args_digest")
        if expected:
            import hashlib

            if hashlib.sha256(data).hexdigest() != expected:
                raise RuntimeError(
                    f"staged RPC args {path} do not match their content "
                    "digest (torn or stale CAS artifact)"
                )
    args, kwargs = cloudpickle.loads(data)
    return tuple(args), dict(kwargs)


def _encode_rpc_result(result, exception) -> str:
    """Base64 of the ``(result, exception)`` pickle — byte-identical layout
    to the result file launch mode writes, just streamed instead of
    staged."""
    import base64

    try:
        import cloudpickle as pick
    except ImportError:
        import pickle as pick
    try:
        data = pick.dumps((result, exception))
    except BaseException as err:  # noqa: BLE001 - unpicklable user results
        import pickle

        data = pickle.dumps(
            (None, RuntimeError(f"RPC result not picklable: {err!r}"))
        )
    return base64.b64encode(data).decode("ascii")


def _emit_rpc_event(spec: dict, task_id: str, type: str, **fields) -> None:
    """One worker-side record pushed straight over the channel.

    Same envelope (`_build_worker_event`: ts/pid/seq/trace) as launch-mode
    workers write to their telemetry files — the dispatcher's backhaul
    handler can't tell the transports apart, which is the point.  The
    ``rpc`` marker tells the dispatcher these events did NOT also land in
    a shared-filesystem sink, so they re-emit even on the local transport.
    """
    _emit({
        "event": "telemetry", "id": task_id,
        "data": _build_worker_event(spec, type, rpc=True, **fields),
    })


def _start_rpc_heartbeat(spec: dict, task_id: str):
    """Channel-streamed heartbeats for one invocation (no snapshot files)."""
    try:
        interval = float(spec.get("heartbeat_s") or 0)
    except (TypeError, ValueError):
        interval = 0.0
    if interval <= 0:
        return None
    stop = threading.Event()

    def beat_loop() -> None:
        hb_seq = 0
        while True:
            hb_seq += 1
            _emit_rpc_event(
                spec, task_id, "worker.heartbeat",
                hb_seq=hb_seq, interval_s=interval,
                **_heartbeat_payload(""),
            )
            if stop.wait(interval):
                return

    threading.Thread(
        target=beat_loop, name="covalent-tpu-rpc-heartbeat", daemon=True
    ).start()
    return stop


def _run_rpc_task(command: dict, fn) -> None:
    """Execute one registered function in-process and stream the result.

    The launch-mode contract, minus the process: task_started /
    heartbeats / task_finished events (trace-stamped from the spec), user
    exceptions transported — never raised — and device arrays materialised
    to host before pickling.
    """
    task_id = command.get("id") or ""
    spec = dict(command.get("spec") or {})
    spec.setdefault("operation_id", task_id)
    # Same env contract as a launch-mode harness child (os.environ +
    # PYTHONPATH sys.path mirror + jax platform pin): task_env must mean
    # the same thing whichever runtime executes the function.
    _apply_spec_env(spec)
    result, exception = None, None
    try:
        args, kwargs = _decode_rpc_args(command)
    except BaseException as err:  # noqa: BLE001 - torn args fail the task
        args, kwargs, exception = (), {}, err
    _emit_rpc_event(spec, task_id, "worker.task_started", process_id=0)
    heartbeat_stop = _start_rpc_heartbeat(spec, task_id)
    try:
        if exception is None:
            try:
                result = fn(*args, **kwargs)
                result = _to_host(result)
            except Exception as task_error:  # noqa: BLE001 - transported
                exception = task_error
    finally:
        if heartbeat_stop is not None:
            heartbeat_stop.set()
    _emit({
        "event": "result", "id": task_id,
        "ok": exception is None,
        "data": _encode_rpc_result(result, exception),
    })
    _emit_rpc_event(
        spec, task_id, "worker.task_finished", process_id=0,
        ok=exception is None,
        **({"error": repr(exception)} if exception is not None else {}),
    )


def _rpc_invoke(command: dict, registry: dict, sync: bool = False) -> None:
    task_id = command.get("id")
    digest = command.get("digest")
    if not task_id or not digest:
        _emit({"event": "error", "id": task_id or "",
               "message": "invoke requires id and digest"})
        return
    fn = registry.get(digest)
    if fn is None and command.get("path"):
        # Self-heal a lost registration (agent restarted between the
        # dispatcher's register and invoke) and serve the --rpc-child
        # one-shot mode: load from the CAS path, digest verified.
        code, loaded = _load_fn_payload(command["path"], digest)
        if not code:
            registry[digest] = fn = loaded
    if fn is None:
        _emit({"event": "error", "id": task_id, "code": "unregistered",
               "message": f"no registered function for digest {digest[:12]}"})
        return
    _emit({"event": "started", "id": task_id, "pid": os.getpid(),
           "rpc": True})
    if sync:
        _run_rpc_task(command, fn)
        return
    threading.Thread(
        target=_run_rpc_task, args=(command, fn),
        name=f"covalent-tpu-rpc-{task_id}", daemon=True,
    ).start()


def rpc_child() -> int:
    """``harness.py --rpc-child``: one invocation, command on stdin.

    The native C++ agent's invoke support: it forks this runner per
    invocation, pipes the invoke command (which carries the CAS ``path``)
    to stdin, and streams the started/telemetry/result events from stdout
    back over its channel.  Slower than the resident pool loop (one
    interpreter start per call) but keeps the protocol — and the
    no-disk-for-args/results property — uniform across both runtimes.
    """
    line = sys.stdin.readline()
    if not line.strip():
        print("usage: harness.py --rpc-child  (invoke command on stdin)",
              file=sys.stderr)
        return 2
    try:
        command = json.loads(line)
    except ValueError:
        _emit({"event": "error", "message": "malformed invoke command"})
        return 1
    _rpc_invoke(command, {}, sync=True)
    return 0


#: Per-pump read ceiling: one oversized telemetry burst must not wedge the
#: command loop behind a single giant read.
_WATCH_READ_LIMIT = 256 * 1024


def _pump_watchers(watchers: dict) -> None:
    """Forward new complete JSONL lines from every watched file.

    Each watcher tracks a byte offset; partial trailing lines wait in a
    buffer for the next pump.  Unparsable lines are dropped (the side-band
    forwards structured events only), and a missing file just means the
    task hasn't emitted yet.
    """
    for task_id, w in list(watchers.items()):
        try:
            size = os.path.getsize(w["path"])
        except OSError:
            continue
        if size < w["pos"]:
            w["pos"], w["buf"] = 0, ""  # truncated/rotated: start over
        if size == w["pos"]:
            continue
        try:
            with open(w["path"], "r", encoding="utf-8", errors="replace") as f:
                f.seek(w["pos"])
                chunk = f.read(_WATCH_READ_LIMIT)
                w["pos"] = f.tell()
        except OSError:
            continue
        w["buf"] += chunk
        while "\n" in w["buf"]:
            line, w["buf"] = w["buf"].split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                data = json.loads(line)
            except ValueError:
                continue
            if isinstance(data, dict):
                _emit({"event": "telemetry", "id": task_id, "data": data})


def _reap(children: dict, watchers: dict | None = None) -> None:
    while True:
        try:
            pid, status = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid <= 0:
            return
        task_id = children.pop(pid, None)
        if task_id is None:
            continue
        code = os.waitstatus_to_exitcode(status)
        if watchers is not None and task_id in watchers:
            # Auto-unwatch on exit (after one final pump so the tail of
            # the telemetry file is flushed): a long-lived server must not
            # keep stat()ing files of finished tasks forever.
            _pump_watchers({task_id: watchers[task_id]})
            del watchers[task_id]
        _emit({
            "event": "exit",
            "id": task_id,
            "code": code if code >= 0 else -1,
            "signal": -code if code < 0 else 0,
        })


def serve() -> int:
    """Forkserver main loop: poll stdin commands + a SIGCHLD wakeup pipe."""
    import selectors
    import signal

    for mod in filter(None, os.environ.get(
        "COVALENT_TPU_POOL_PRELOAD", "cloudpickle"
    ).split(",")):
        try:
            __import__(mod.strip())
        except Exception as preload_error:  # noqa: BLE001 - children retry
            print(f"preload {mod} failed: {preload_error}", file=sys.stderr)

    rpipe, wpipe = os.pipe()
    os.set_blocking(rpipe, False)
    os.set_blocking(wpipe, False)
    signal.set_wakeup_fd(wpipe)
    signal.signal(signal.SIGCHLD, lambda *_: None)
    signal.signal(signal.SIGPIPE, signal.SIG_IGN)

    sel = selectors.DefaultSelector()
    sel.register(0, selectors.EVENT_READ, "stdin")
    sel.register(rpipe, selectors.EVENT_READ, "sigchld")

    children: dict = {}
    #: task id -> {"path", "pos", "buf"} telemetry tails (watch cmd).
    watchers: dict = {}
    #: digest -> unpickled callable (register_fn cmd); dies with the
    #: process, which is exactly the lifetime the dispatcher's
    #: per-connection registered-set mirrors.
    rpc_registry: dict = {}
    buffer = ""
    running = True
    stdin_open = True
    _emit({"event": "ready", "pid": os.getpid(), "mode": "pool"})

    while running and (stdin_open or children):
        # With live watchers the select wakes on a short tick so telemetry
        # lines flow without any inbound traffic; otherwise block freely.
        for key, _ in sel.select(timeout=0.25 if watchers else None):
            if key.data == "sigchld":
                try:
                    while os.read(rpipe, 512):
                        pass
                except BlockingIOError:
                    pass
                _reap(children, watchers)
                continue
            data = os.read(0, 65536)
            if not data:
                # Channel dropped: children keep running in their own
                # sessions; serve until they are all reaped, then exit.
                stdin_open = False
                sel.unregister(0)
                continue
            buffer += data.decode(errors="replace")
            while "\n" in buffer:
                line, buffer = buffer.split("\n", 1)
                if not line.strip():
                    continue
                try:
                    command = json.loads(line)
                except ValueError:
                    _emit({"event": "error", "message": "malformed command"})
                    continue
                name = command.get("cmd")
                if name == "ping":
                    _emit({"event": "pong"})
                elif name == "run":
                    _spawn_task(command, children)
                elif name == "register_fn":
                    _rpc_register(command, rpc_registry)
                elif name == "invoke":
                    _rpc_invoke(command, rpc_registry)
                elif name == "kill":
                    target = command.get("id")
                    sig = int(command.get("sig", 15))
                    for pid, task_id in list(children.items()):
                        if task_id == target:
                            # Group AND direct pid: a kill racing the child's
                            # setsid() would otherwise no-op (same guard as
                            # native/agent.cc kill_task).
                            try:
                                os.killpg(pid, sig)
                            except ProcessLookupError:
                                pass
                            try:
                                os.kill(pid, sig)
                            except ProcessLookupError:
                                pass
                            _emit({"event": "killed", "id": target})
                            break
                    else:
                        _emit({"event": "error", "id": target or "",
                               "message": "unknown task id"})
                elif name == "watch":
                    task_id = command.get("id")
                    path = command.get("path")
                    if not task_id or not path:
                        _emit({"event": "error", "id": task_id or "",
                               "message": "watch requires id and path"})
                    else:
                        # Offset 0 on every (re-)watch: a reconnecting
                        # dispatcher gets the buffered backlog flushed.
                        watchers[task_id] = {"path": path, "pos": 0,
                                             "buf": ""}
                        _emit({"event": "watching", "id": task_id})
                elif name == "unwatch":
                    task_id = command.get("id")
                    watchers.pop(task_id, None)
                    _emit({"event": "unwatched", "id": task_id or ""})
                elif name == "shutdown":
                    _emit({"event": "bye"})
                    running = False
                else:
                    _emit({"event": "error",
                           "message": f"unknown cmd: {name}"})
        _pump_watchers(watchers)
        _reap(children, watchers)  # belt-and-braces against missed wakeups
    return 0


def main(argv: list[str]) -> int:
    if len(argv) == 2 and argv[1] == "--serve":
        return serve()
    if len(argv) >= 2 and argv[1] == "--rpc-child":
        return rpc_child()
    if len(argv) != 2:
        print(
            "usage: harness.py <task_spec.json> | --serve | --rpc-child",
            file=sys.stderr,
        )
        return 2
    # Become a session/process-group leader (pool-mode children already do
    # this in _spawn_task): the dispatcher's cancel and timeout-escalation
    # paths kill `-- -pid`, and only a group leader pid makes that reach
    # the user function's own subprocesses — no orphans on billed TPU time.
    try:
        os.setsid()
    except OSError:
        pass  # already a leader (or platform without sessions)
    with open(argv[1]) as f:
        spec = json.load(f)
    return run_task(spec)


if __name__ == "__main__":
    sys.exit(main(sys.argv))
