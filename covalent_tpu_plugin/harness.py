"""Remote execution harness — runs ON each TPU-VM worker.

TPU-native counterpart of the reference's ``covalent_ssh_plugin/exec.py``
template.  Two structural changes:

* The reference ``.format()``-instantiates its script per task
  (``ssh.py:160-171``), which forbids literal braces anywhere in the file
  (``exec.py`` header comment).  This harness is instead a *static* module
  copied verbatim to the worker and invoked as
  ``python harness.py <task_spec.json>`` — all per-task parameters travel in
  a small JSON spec, so one upload is reusable and the brace constraint
  disappears.
* Before touching the pickled function it wires up the multi-host data
  plane: ``jax.distributed.initialize(coordinator_address, num_processes,
  process_id)`` (SURVEY §2.4), then after the task it materialises device
  arrays to host memory and lets only process 0 write the result pickle.

The file protocol is otherwise the reference's: read ``(fn, args, kwargs)``
(``exec.py:29-30``), chdir into the task workdir (``exec.py:33-35``), run the
function catching any exception (``exec.py:37-40``), always write the
``(result, exception)`` pair (``exec.py:45-46``) — written atomically via a
temp file + rename so the dispatcher's status probe never sees a torn file.

MUST remain standalone: stdlib + cloudpickle (+ jax when present) only, since
it runs on workers where this package is not installed.
"""

from __future__ import annotations

import json
import os
import struct
import sys
import threading
import time
import zlib


#: Per-process worker-event sequence + write-failure accounting.  The seq
#: lets the dispatcher dedup re-delivered lines (the telemetry side-band
#: re-tails from offset 0 after a reconnect) and is shared by EVERY worker
#: record — lifecycle events, streamed heartbeats, and the heartbeat
#: snapshot file all draw from one locked counter, so the dispatcher's
#: seq-based dedup compares a single monotonic domain whichever road a
#: record arrives by.  The failure counter backs the swallow-and-count
#: contract — an unwritable/ENOSPC events path must never take down the
#: task it was observing, but the first failure leaves one line on stderr
#: so the silence is diagnosable from the task log.
_worker_event_seq = 0
_worker_event_lock = threading.Lock()
_worker_event_failures = 0


def _build_worker_event(spec: dict, type: str, **fields) -> dict:
    """One worker record: ts/pid/seq envelope + trace context + fields.

    The single assembly point for every worker-side record (events and
    heartbeat snapshots alike), so the schema cannot drift between sinks
    and the seq counter stays atomic under the heartbeat thread.
    """
    global _worker_event_seq
    with _worker_event_lock:
        _worker_event_seq += 1
        seq = _worker_event_seq
    trace = spec.get("trace") or {}
    event = {
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "seq": seq,
        "type": type,
        "operation_id": spec.get("operation_id"),
    }
    if trace.get("trace_id"):
        event["trace_id"] = trace.get("trace_id")
        event["parent_id"] = trace.get("span_id")
        if trace.get("attempt") is not None:
            event["attempt"] = trace.get("attempt")
    event.update(fields)
    return event


def _append_event_line(event: dict, paths: list) -> None:
    """Swallow-and-count JSONL append of one event to every sink path."""
    global _worker_event_failures
    try:
        line = json.dumps(event, default=repr) + "\n"
    except (TypeError, ValueError):
        return
    for path in paths:
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(line)
        except OSError as err:
            _worker_event_failures += 1
            if _worker_event_failures == 1:
                print(
                    f"worker events unwritable ({path}: {err}); "
                    "further failures swallowed",
                    file=sys.stderr,
                )


def _worker_event_paths(spec: dict) -> list:
    """Every sink one worker event lands in (deduped, order-stable).

    ``events_file`` is the dispatcher's own stream (shared filesystem);
    ``telemetry_file`` is the per-task side-band the resident agent tails
    back over its channel.  Heartbeats go to the telemetry file only (see
    ``_start_heartbeat``); lifecycle events go to both.
    """
    paths = []
    for key in ("events_file", "telemetry_file"):
        path = spec.get(key)
        if path and path not in paths:
            paths.append(path)
    env_path = os.environ.get("COVALENT_TPU_EVENTS_PATH")
    if not paths and env_path:
        paths.append(env_path)
    return paths


def _emit_worker_event(spec: dict, type: str, _paths=None, **fields) -> None:
    """Append one structured JSONL event from the worker side.

    Mirrors the dispatcher's ``obs.events`` line format (ts/pid/type) but
    stays stdlib-only — this file runs on workers where the plugin is not
    installed.  Sink paths come from the spec (``events_file`` /
    ``telemetry_file``, set by the stager) or the worker's own
    ``COVALENT_TPU_EVENTS_PATH``; no path means no-op.  Trace context from
    the spec (``trace``: trace/parent span ids + attempt) is stamped on
    every event so worker-side records join the dispatch trace.

    Never raises: write failures are swallowed and counted, with a single
    stderr note on the first one — an ENOSPC events disk must not fail the
    electron it was observing.
    """
    paths = _worker_event_paths(spec) if _paths is None else _paths
    if not paths:
        return
    _append_event_line(_build_worker_event(spec, type, **fields), paths)


def _heartbeat_payload(metrics_file: str) -> dict:
    """One heartbeat's body: process vitals + user-published progress.

    Everything best-effort and stdlib-only.  The user function publishes
    progress (step counter, examples/s, tokens/s, ...) by writing a small
    JSON object to ``$COVALENT_TPU_WORKER_METRICS_PATH``; the beat folds it
    in verbatim.  jax device-memory stats are read ONLY when the task
    already imported jax AND a backend is live — the heartbeat thread must
    never be the thing that triggers (or races) backend initialization.
    """
    payload: dict = {}
    try:
        import resource

        usage = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS.
        scale = 1 if sys.platform == "darwin" else 1024
        payload["rss_bytes"] = int(usage.ru_maxrss) * scale
        payload["cpu_s"] = round(usage.ru_utime + usage.ru_stime, 3)
    except Exception:  # noqa: BLE001 - vitals are best-effort
        pass
    if metrics_file:
        try:
            with open(metrics_file, encoding="utf-8") as f:
                user = json.load(f)
            if isinstance(user, dict):
                step = user.pop("step", None)
                if isinstance(step, (int, float)):
                    payload["step"] = step
                if user:
                    payload["metrics"] = user
        except (OSError, ValueError):
            pass
    serve = _serve_occupancy()
    if serve:
        # Resident serving sessions (if any) fold their slot occupancy into
        # every beat, so a serving worker's liveness stream doubles as its
        # load report on the dispatcher side.
        payload["serve"] = serve
    if "jax" in sys.modules:
        try:
            import jax

            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                device = jax.local_devices()[0]
                stats = device.memory_stats() or {}
                mem = {
                    k: stats[k]
                    for k in ("bytes_in_use", "peak_bytes_in_use")
                    if k in stats
                }
                if mem:
                    payload["device_mem"] = mem
        except Exception:  # noqa: BLE001 - absent on CPU backends
            pass
    return payload


def _start_heartbeat(spec: dict):
    """Launch the heartbeat thread; returns a stop Event (or None).

    Cadence comes from the spec's ``heartbeat_s`` (0/absent disables).
    Each beat does two things:

    * emits a ``worker.heartbeat`` event into the *telemetry* side-band
      file (never the shared lifecycle stream — beats are high-volume
      plumbing, not dispatch history), which the resident agent tails
      back to the dispatcher in near-real-time;
    * atomically refreshes a tiny snapshot file (``<pid_file>.hb``) that
      the dispatcher's status probe reads piggybacked on its existing
      round trip, so the poll path gets liveness for free.

    The first beat fires immediately so even sub-second electrons leave
    one, and the dispatcher's stall detector has a baseline to age.
    """
    try:
        interval = float(spec.get("heartbeat_s") or 0)
    except (TypeError, ValueError):
        interval = 0.0
    if interval <= 0:
        return None
    # Resolve every side-band path to absolute BEFORE the task chdirs into
    # its workdir: the beat thread runs concurrently with the chdir'd
    # function, and a relative remote_cache would otherwise scatter
    # snapshots across working directories.
    pid_file = spec.get("pid_file")
    hb_file = os.path.abspath(f"{pid_file}.hb") if pid_file else None
    metrics_file = (
        os.path.abspath(f"{pid_file}.metrics") if pid_file else ""
    )
    if metrics_file:
        # The user function's progress-publishing hook.
        os.environ["COVALENT_TPU_WORKER_METRICS_PATH"] = metrics_file
    telemetry_paths = [
        os.path.abspath(p) for p in (spec.get("telemetry_file"),) if p
    ]
    stop = threading.Event()

    def beat_loop() -> None:
        hb_seq = 0
        while True:
            hb_seq += 1
            # ONE event, one seq, two sinks: the streamed telemetry line
            # and the probe-read snapshot must be the same record so the
            # dispatcher's seq dedup works across delivery roads (e.g. an
            # agent-channel death downgrading to the polling path).
            event = _build_worker_event(
                spec, "worker.heartbeat",
                hb_seq=hb_seq, interval_s=interval,
                **_heartbeat_payload(metrics_file),
            )
            if telemetry_paths:
                _append_event_line(event, telemetry_paths)
            if hb_file:
                try:
                    tmp = f"{hb_file}.tmp.{os.getpid()}"
                    with open(tmp, "w", encoding="utf-8") as f:
                        f.write(json.dumps(event, default=repr))
                    os.replace(tmp, hb_file)
                except (OSError, TypeError, ValueError):
                    pass  # liveness reporting must never fail the task
            if stop.wait(interval):
                return

    thread = threading.Thread(
        target=beat_loop, name="covalent-tpu-heartbeat", daemon=True
    )
    thread.start()
    return stop


# --------------------------------------------------------------------------
# Cooperative checkpointing (elastic gangs).
#
# A training electron registers a snapshot hook via
# ``covalent_tpu_plugin.utils.checkpoint.register_snapshot``; this harness
# (stdlib-only — the package is looked up through sys.modules, never
# imported) calls it on the configured interval and on SIGTERM (the spot
# preemption notice), publishing each snapshot as a sha256-named bundle in
# the worker's remote CAS plus an atomically-replaced per-lineage manifest.
# A kill mid-save can never tear the "latest": bundles publish tmp+replace
# and the manifest only ever references fully-written files, so the
# dispatcher's resume discovery (which digest-verifies every candidate)
# either finds a complete checkpoint or falls back to the previous one.
# --------------------------------------------------------------------------

def _sanitize_lineage(lineage: str) -> str:
    import re

    return re.sub(r"[^A-Za-z0-9._-]", "_", str(lineage))


def _ckpt_manifest_path(directory: str, lineage: str) -> str:
    return os.path.join(directory, f"ckpt_{_sanitize_lineage(lineage)}.json")


def _write_checkpoint_bundle(
    directory: str, lineage: str, step: int, tree, keep_n: int
) -> tuple:
    """Publish one checkpoint bundle atomically; returns (path, digest, n).

    Bundle = pickled ``{"v", "lineage", "step", "tree", "meta"}`` named by
    the sha256 of its bytes (a CAS artifact: the dispatcher re-stages it to
    replacement workers through the ordinary content-addressed upload
    path).  The manifest keeps a newest-first ``history`` of the last
    ``keep_n`` complete steps; bundles that fall off it are unlinked, so
    checkpoint output is bounded however long the task runs.
    """
    import hashlib

    try:
        import cloudpickle as pickler
    except ImportError:
        import pickle as pickler
    payload = pickler.dumps({
        "v": 1,
        "lineage": lineage,
        "step": int(step),
        "tree": _to_host(tree),
        "meta": {"saved_at": time.time(), "pid": os.getpid()},
    })
    digest = hashlib.sha256(payload).hexdigest()
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{digest}.ckpt")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)

    manifest_path = _ckpt_manifest_path(directory, lineage)
    # The manifest update is a read-modify-write: two process-0 writers
    # CAN coexist on a shared filesystem (a straggling old gang inside
    # its preemption grace window and the resumed replacement), and the
    # loser of an unlocked race would silently drop the other's newest
    # entry — both costing recompute on the next resume and leaking its
    # bundle past the keep_n GC forever.  flock serializes them; hosts
    # without fcntl (or filesystems without lock support) degrade to the
    # unlocked behavior.
    lock_fd = None
    try:
        import fcntl

        lock_fd = os.open(
            f"{manifest_path}.lock", os.O_CREAT | os.O_RDWR, 0o644
        )
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
    except (ImportError, OSError):
        if lock_fd is not None:
            os.close(lock_fd)
        lock_fd = None
    try:
        return _publish_manifest(
            manifest_path, lineage, path, digest, payload, step, keep_n
        )
    finally:
        if lock_fd is not None:
            os.close(lock_fd)  # closing releases the flock


def _publish_manifest(
    manifest_path: str, lineage: str, path: str, digest: str,
    payload: bytes, step: int, keep_n: int,
) -> tuple:
    """Manifest read-modify-write + GC (under the caller's flock)."""
    history = []
    try:
        with open(manifest_path, encoding="utf-8") as f:
            manifest = json.load(f)
        if isinstance(manifest, dict) and isinstance(
            manifest.get("history"), list
        ):
            history = [
                h for h in manifest["history"]
                if isinstance(h, dict) and h.get("step") != int(step)
            ]
    except (OSError, ValueError):
        pass  # missing or torn manifest: rebuild from this save
    history.insert(
        0, {"step": int(step), "digest": digest, "file": path,
            "bytes": len(payload)},
    )
    # Highest step first, not insertion order: a straggling old gang and
    # a resumed replacement can interleave saves on a shared filesystem,
    # and resume discovery must always see the furthest-trained state at
    # the head.
    history.sort(
        key=lambda h: h.get("step", -1)
        if isinstance(h.get("step"), int) else -1,
        reverse=True,
    )
    keep_n = max(1, int(keep_n or 1))
    dropped, history = history[keep_n:], history[:keep_n]
    tmp_manifest = f"{manifest_path}.tmp.{os.getpid()}"
    with open(tmp_manifest, "w", encoding="utf-8") as f:
        json.dump(
            {"lineage": lineage, "updated": time.time(),
             "history": history},
            f,
        )
    os.replace(tmp_manifest, manifest_path)
    live = {h["digest"] for h in history}
    for old in dropped:
        if old.get("digest") in live:
            continue
        try:
            os.unlink(old.get("file") or "")
        except OSError:
            pass
    return path, digest, len(payload)


def _start_checkpointer(spec: dict):
    """Interval checkpointer for one task; returns ``(stop, save_now)``.

    ``save_now(trigger)`` takes one snapshot synchronously (used by both
    the interval thread and the SIGTERM handler; a shared lock + step
    high-water mark make concurrent calls safe and idempotent).  Only
    process 0 checkpoints — the snapshot hook's train state is replicated
    across the gang (the same single-writer contract as the result file).
    """
    cfg = spec.get("checkpoint") or {}
    try:
        interval = float(cfg.get("interval_s") or 0)
    except (TypeError, ValueError):
        interval = 0.0
    distributed = spec.get("distributed") or {}
    process_id = int(distributed.get("process_id") or 0)
    if interval <= 0 or not cfg.get("dir") or process_id != 0:
        return None, None
    directory = os.path.abspath(str(cfg["dir"]))
    lineage = str(cfg.get("lineage") or spec.get("operation_id") or "task")
    keep_n = int(cfg.get("keep_n") or 3)
    state = {"last_step": None, "failures": 0}
    lock = threading.Lock()

    def save_now(trigger: str):
        module = sys.modules.get("covalent_tpu_plugin.utils.checkpoint")
        take = getattr(module, "take_snapshot", None)
        if take is None:
            return None  # electron never registered a hook
        try:
            snap = take()
        except Exception as err:  # noqa: BLE001 - user hook
            state["failures"] += 1
            if state["failures"] == 1:
                print(f"snapshot hook failed: {err!r}", file=sys.stderr)
            _emit_worker_event(
                spec, "worker.checkpoint_error", lineage=lineage,
                trigger=trigger, error=repr(err),
            )
            return None
        if snap is None:
            return None
        tree, step = snap
        step = int(step)
        if step < 0:
            return None
        with lock:
            last = state["last_step"]
            if last is not None and step <= last:
                return None  # nothing new since the previous save
            path, digest, nbytes = _write_checkpoint_bundle(
                directory, lineage, step, tree, keep_n
            )
            state["last_step"] = step
        _emit_worker_event(
            spec, "worker.checkpoint_saved", lineage=lineage, step=step,
            digest=digest, path=path, bytes=nbytes, trigger=trigger,
        )
        return step

    stop = threading.Event()

    def loop() -> None:
        while not stop.wait(interval):
            try:
                save_now("interval")
            except Exception as err:  # noqa: BLE001 - never kill the task
                state["failures"] += 1
                if state["failures"] == 1:
                    print(f"checkpoint save failed: {err!r}", file=sys.stderr)

    threading.Thread(
        target=loop, name="covalent-tpu-checkpointer", daemon=True
    ).start()
    return stop, save_now


def _install_preempt_handler(spec: dict, save_now) -> None:
    """SIGTERM = the spot preemption notice: final snapshot, then die.

    The handler emits ``worker.preempt_notice`` (streamed up the telemetry
    side-band so the dispatcher can label the coming death), takes one
    last cooperative checkpoint inside the grace window, restores the
    default disposition and re-raises SIGTERM so the process exits with
    the true signal status the dispatcher's pollers expect.
    """
    import signal

    if threading.current_thread() is not threading.main_thread():
        return  # signal API is main-thread-only (RPC invocations skip)

    def _on_term(signum, frame):
        _emit_worker_event(spec, "worker.preempt_notice", signal="SIGTERM")
        try:
            if save_now is not None:
                save_now("preempt")
        except Exception:  # noqa: BLE001 - dying anyway; save is best-effort
            pass
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # pragma: no cover - exotic platforms
        pass


def install_pip_deps(pip_deps: list) -> None:
    """Install an electron's pip dependencies; raise RuntimeError on failure.

    Shared contract between this worker harness and the in-process
    LocalExecutor (reference ct.DepsPip, svm_workflow.py:6,19).  The
    command is overridable via ``COVALENT_TPU_PIP_CMD`` for sandboxed test
    environments.
    """
    import shlex
    import subprocess

    pip_cmd = shlex.split(
        os.environ.get("COVALENT_TPU_PIP_CMD", "")
    ) or [sys.executable, "-m", "pip", "install"]
    proc = subprocess.run(
        pip_cmd + list(pip_deps), capture_output=True, text=True
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"pip dependency install failed "
            f"({' '.join(pip_deps)}): {proc.stderr.strip()}"
        )


def _fallback_result(result_file: str, error: BaseException) -> None:
    """Best-effort ``(None, error)`` write with stdlib pickle, mirroring the
    reference's cloudpickle-ImportError path (``exec.py:16-24``)."""
    import pickle

    tmp = result_file + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump((None, error), f)
    os.replace(tmp, result_file)


def _to_host(tree):
    """Materialise jax arrays onto the host before pickling."""
    # If the task never imported jax there can be no device arrays in the
    # result — skip the (multi-second) jax import entirely.
    if "jax" not in sys.modules:
        return tree
    try:
        import jax
    except Exception:
        return tree
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return jax.tree_util.tree_map(
        lambda x: jax.device_get(x) if hasattr(x, "devices") else x, tree
    )


def _apply_spec_env(spec: dict) -> None:
    """Apply the task's env contract to THIS process.

    os.environ entries, a sys.path mirror for PYTHONPATH, and the jax
    platform pin.  Shared by the per-task harness (``run_task``) and RPC
    invocations executing inside the resident server — one server serves
    one executor, so ``task_env`` is constant across its invocations and
    the process-wide mutation is idempotent by construction.
    """
    env = spec.get("env") or {}
    for key, value in env.items():
        os.environ[key] = str(value)
    if "PYTHONPATH" in env:
        # The interpreter already started; os.environ alone no longer affects
        # import resolution.  Mirror the entries into sys.path so task_env
        # PYTHONPATH means what users expect.
        for entry in reversed(str(env["PYTHONPATH"]).split(os.pathsep)):
            if entry and entry not in sys.path:
                sys.path.insert(0, entry)
    # Env alone can lose to a site-level PJRT plugin registration that
    # re-pins the platform after interpreter start; jax.config wins if set
    # before first backend use.  Pin from the spec env always (explicit user
    # intent, worth the jax import), and from the inherited process env only
    # when a sitecustomize already imported jax — then the pin is free and
    # protects every subprocess on hosts whose site hook overrides the env.
    if "JAX_PLATFORMS" in env:
        platforms = env["JAX_PLATFORMS"]  # explicit, even "" = auto-select
    elif "jax" in sys.modules:
        platforms = os.environ.get("JAX_PLATFORMS")
    else:
        platforms = None
    if platforms is not None:
        try:
            import jax

            jax.config.update("jax_platforms", str(platforms))
        except Exception:
            pass


def run_task(spec: dict) -> int:
    """Execute one staged task described by ``spec``.  Returns the exit code."""
    result_file = spec["result_file"]

    pid_file = spec.get("pid_file")
    if pid_file:
        # First thing, before any failure mode: the dispatcher's orphan
        # cleanup kills by this pid when a launch channel dies mid-submit
        # (a pool fork keeps the server's cmdline, so pkill can't find it).
        # Atomic write: a reader must never observe an empty pid file.
        tmp_pid = f"{pid_file}.tmp.{os.getpid()}"
        with open(tmp_pid, "w") as f:
            f.write(str(os.getpid()))
        os.replace(tmp_pid, pid_file)

    _apply_spec_env(spec)

    # Event sinks resolve to absolute BEFORE the task chdirs into its
    # workdir: mid-task emissions (checkpoint saves, the SIGTERM
    # preemption notice) race the chdir'd function, and a relative
    # events path would scatter them across working directories.
    for sink_key in ("events_file", "telemetry_file"):
        if spec.get(sink_key):
            spec[sink_key] = os.path.abspath(spec[sink_key])

    distributed = spec.get("distributed")
    process_id = int(distributed["process_id"]) if distributed else 0
    _emit_worker_event(spec, "worker.task_started", process_id=process_id)
    # Liveness starts before any blocking stage (pip install, distributed
    # barrier, the task itself): a worker hung anywhere keeps beating —
    # and one that goes silent is genuinely wedged.
    heartbeat_stop = _start_heartbeat(spec)

    # Elastic gangs: interval checkpointer (the SIGTERM preemption handler
    # is installed LATER, after the distributed bootstrap — jax's
    # distributed runtime registers its own signal handlers during
    # initialize and would silently replace ours), and the resume
    # contract — a retry attempt shipping a verified checkpoint exposes
    # it to the electron via the COVALENT_TPU_RESUME_* env trio.
    checkpoint_stop, checkpoint_now = _start_checkpointer(spec)
    resume = spec.get("resume") or {}
    if resume.get("file"):
        # Absolute before the task chdirs into its workdir: the electron
        # reads this env var *after* the chdir.
        os.environ["COVALENT_TPU_RESUME_CHECKPOINT"] = os.path.abspath(
            str(resume["file"])
        )
        os.environ["COVALENT_TPU_RESUME_STEP"] = str(resume.get("step", ""))
        os.environ["COVALENT_TPU_RESUME_DIGEST"] = str(
            resume.get("digest", "")
        )
        _emit_worker_event(
            spec, "worker.resume_available", process_id=process_id,
            step=resume.get("step"), digest=resume.get("digest"),
        )
    else:
        for stale in (
            "COVALENT_TPU_RESUME_CHECKPOINT",
            "COVALENT_TPU_RESUME_STEP",
            "COVALENT_TPU_RESUME_DIGEST",
        ):
            os.environ.pop(stale, None)

    pip_deps = spec.get("pip_deps") or []
    if pip_deps:
        # Install BEFORE loading the function pickle — unpickling may import
        # the dependency (reference ct.DepsPip, svm_workflow.py:6,19).  A
        # non-zero process that fails here exits 1 *before* the distributed
        # barrier; the dispatcher's poller watches every worker's liveness
        # and fails the task fast instead of letting process 0 hang in
        # jax.distributed.initialize.
        try:
            install_pip_deps(pip_deps)
        except RuntimeError as pip_error:
            _emit_worker_event(
                spec, "worker.task_finished", process_id=process_id,
                ok=False, error=repr(pip_error),
            )
            if process_id == 0:
                _fallback_result(result_file, pip_error)
            return 1

    try:
        import cloudpickle as pickle
    except ImportError as import_error:
        if process_id == 0:
            _fallback_result(result_file, import_error)
        return 1

    expected_digest = spec.get("function_digest")
    if expected_digest:
        # The function file is a content-addressed (CAS) artifact: verify
        # its bytes against the digest the dispatcher staged before
        # unpickling, so a torn upload or stale cache entry fails loud
        # instead of executing the wrong payload.  Runs BEFORE the
        # distributed barrier so a bad artifact on any worker fails fast
        # with correct blame instead of hanging process 0 in initialize.
        import hashlib

        sha = hashlib.sha256()
        with open(spec["function_file"], "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                sha.update(chunk)
        if sha.hexdigest() != expected_digest:
            digest_error = RuntimeError(
                f"staged function {spec['function_file']} does not match "
                f"its content digest (torn or stale CAS artifact)"
            )
            _emit_worker_event(
                spec, "worker.task_finished", process_id=process_id,
                ok=False, error=repr(digest_error),
            )
            if process_id == 0:
                _fallback_result(result_file, digest_error)
            return 1

    if distributed:
        # Data-plane bootstrap: after this, in-electron jax code sees every
        # chip in the slice and XLA collectives ride ICI/DCN (SURVEY §2.4).
        import jax

        jax.distributed.initialize(
            coordinator_address=distributed["coordinator_address"],
            num_processes=int(distributed["num_processes"]),
            process_id=process_id,
        )

    with open(spec["function_file"], "rb") as f:
        fn, args, kwargs = pickle.load(f)

    # The SIGTERM preemption contract (notice event + final cooperative
    # snapshot + die with the signal) — installed after EVERY import that
    # can register its own signal handling (jax.distributed.initialize
    # above does), so the spot notice always reaches this handler.
    if spec.get("checkpoint"):
        _install_preempt_handler(spec, checkpoint_now)

    # Optional device-level tracing (SURVEY §5: the reference captures no
    # timings at all; this surfaces the XLA/TPU view of the electron).  The
    # trace lands in the task workdir/cache so the dispatcher can scp it.
    profile_dir = spec.get("profile_dir")
    profiling = False
    if profile_dir:
        try:
            import jax

            jax.profiler.start_trace(profile_dir)
            profiling = True
        except Exception as profile_error:  # pragma: no cover - best effort
            print(f"profiler unavailable: {profile_error}", file=sys.stderr)

    workdir = spec.get("workdir")
    current_dir = os.getcwd()
    result, exception = None, None
    try:
        if workdir:
            os.makedirs(workdir, exist_ok=True)
            os.chdir(workdir)
        result = fn(*args, **kwargs)
        result = _to_host(result)
    except Exception as task_error:  # noqa: BLE001 - transported to dispatcher
        exception = task_error
    finally:
        os.chdir(current_dir)
        if profiling:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # pragma: no cover
                pass

    # Replicated outputs: one writer suffices (process 0); the others emit a
    # done-marker the control plane can watch for all-workers-finished.
    if process_id == 0:
        tmp = result_file + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump((result, exception), f)
        os.replace(tmp, result_file)
    else:
        done = f"{result_file}.done.{process_id}"
        with open(done, "w") as f:
            f.write("done\n")

    if heartbeat_stop is not None:
        heartbeat_stop.set()
    if checkpoint_stop is not None:
        checkpoint_stop.set()
    _emit_worker_event(
        spec, "worker.task_finished", process_id=process_id,
        ok=exception is None,
        **({"error": repr(exception)} if exception is not None else {}),
    )
    return 0


# --------------------------------------------------------------------------
# Pool (forkserver) mode: `python harness.py --serve`
#
# One resident interpreter per worker, heavy imports preloaded ONCE, then a
# fork per task — the per-electron cost collapses from interpreter startup +
# imports (seconds) to a fork (milliseconds).  Speaks the same newline-JSON
# protocol as the native C++ agent (native/agent.cc) so the dispatcher
# drives both through one client, with `spec` instead of `argv`:
#
#   -> {"cmd":"run","id":"op","spec":"/path/spec.json","log":"/path/log"}
#   <- {"event":"started","id":"op","pid":123}
#   <- {"event":"exit","id":"op","code":0,"signal":0}
#
# Telemetry side-band: the dispatcher asks the server to tail a task's
# worker-local JSONL file (heartbeats + worker events) back over the same
# channel, turning post-mortem log files into a near-real-time stream:
#
#   -> {"cmd":"watch","id":"op","path":"/path/telemetry.jsonl"}
#   <- {"event":"watching","id":"op"}
#   <- {"event":"telemetry","id":"op","data":{...}}        (per line, pushed)
#   -> {"cmd":"unwatch","id":"op"}
#   <- {"event":"unwatched","id":"op"}
#
# A watch always starts from offset 0, so events buffered in the file while
# a channel was down are flushed on the reconnecting client's re-watch; the
# dispatcher dedups by each event's `seq`.
#
# Fork-safety: the parent preloads modules (cloudpickle, jax, ...) but never
# initializes an XLA backend or runs a computation — backend init happens in
# each child, which is the documented-safe pattern (import before fork, use
# after).  Children setsid into their own sessions, so they survive a pool/
# channel death exactly like the other launch paths, and the dispatcher can
# fall back to pid polling.
# --------------------------------------------------------------------------


#: Serializes protocol writes: the serve loop, RPC invocation threads, and
#: their heartbeat threads all share one stdout channel, and an interleaved
#: write would corrupt the line protocol.
_EMIT_LOCK = threading.Lock()


def _emit(obj: dict) -> None:
    with _EMIT_LOCK:
        try:
            sys.stdout.write(json.dumps(obj) + "\n")
            sys.stdout.flush()
        except OSError:
            # Dead channel (dispatcher gone, SIGPIPE ignored): swallowing
            # keeps session/RPC threads alive so the serve loop's orphan
            # path can hold their state for re-adoption instead of dying
            # on the first post-crash write.
            pass


# --------------------------------------------------------------------------
# Binary frame protocol (negotiated; JSONL stays the fallback).
#
# Hot-path payloads — RPC args/results, streamed serve tokens — used to pay
# pickle -> base64 -> JSON-line on every message (~33% inflation plus a
# JSON parse of the bulky string on both ends).  After negotiation the
# channel interleaves length-prefixed binary frames with JSON lines:
#
#   magic(2)=C5 F7  version(1)  verb(1)  flags(1)  hlen(4 BE)  blen(4 BE)
#   header: UTF-8 JSON object (the command/event, minus its bulky field)
#   body:   raw bytes, re-attached under the field named by header["_body"]
#
# Negotiation rides the ready banner (same one-round-trip shape as the
# COVALENT_TPU_CODECS= pre-flight probe): this server advertises
# `"frames": 1` in `ready`, the client answers `{"cmd":"frames",...}`, the
# ack flips both directions over.  No banner / no answer / the
# COVALENT_TPU_AGENT_FRAMES=0 kill switch all leave the channel on JSONL
# with byte-equal results.  This block mirrors transport/frames.py (and
# native/agent.cc) — it must stay stdlib-only because this file runs
# standalone on workers; the cross-implementation tests in
# tests/test_frames.py keep the three byte-compatible.
# --------------------------------------------------------------------------

_FRAME_MAGIC = b"\xc5\xf7"
_FRAME_VERSION = 1
_FRAME_HEADER = struct.Struct(">2sBBBII")
_FRAME_MAX_HEADER = 16 * 1024 * 1024
_FRAME_MAX_BODY = 512 * 1024 * 1024
_FRAME_MIN_COMPRESS = 512
_FRAME_FLAG_ZLIB = 0x01

_VERB_CMD = 0
_VERB_INVOKE = 1
_VERB_RESULT = 2
_VERB_TELEMETRY = 3
_VERB_MULTI_INVOKE = 4
_VERB_SERVE = 5

#: Outbound frame state, flipped by the negotiated `frames` command.
_FRAMES = {"out": False, "codec": ""}


def _frames_enabled() -> bool:
    """Kill switch: COVALENT_TPU_AGENT_FRAMES=0/off forces JSONL-only."""
    return os.environ.get(
        "COVALENT_TPU_AGENT_FRAMES", ""
    ).strip().lower() not in ("0", "off", "false", "no")


def _emit_frame(verb: int, header: dict, body: bytes = b"") -> None:
    """One binary frame on stdout (atomic under the emit lock).

    The body is zlib-compressed when the negotiated codec allows and the
    payload is big enough to win — same skip-if-incompressible heuristic
    the file-staging codec applies.
    """
    flags = 0
    if (
        body
        and _FRAMES["codec"] == "zlib"
        and len(body) >= _FRAME_MIN_COMPRESS
    ):
        packed = zlib.compress(body, 6)
        if len(packed) < len(body) * 0.9:
            body, flags = packed, _FRAME_FLAG_ZLIB
    head = json.dumps(header, separators=(",", ":")).encode()
    with _EMIT_LOCK:
        try:
            sys.stdout.flush()  # any pending text shares the one byte stream
            out = sys.stdout.buffer
            out.write(_FRAME_HEADER.pack(
                _FRAME_MAGIC, _FRAME_VERSION, verb, flags, len(head),
                len(body)
            ))
            out.write(head)
            if body:
                out.write(body)
            out.flush()
        except OSError:
            # Dead channel: same contract as _emit — stay alive for the
            # orphan/re-adoption path (a torn frame on a dead pipe is
            # unobservable; the adopted channel restarts on JSONL).
            pass


def _handle_frames_cmd(command: dict) -> None:
    """Negotiation verb: ack and flip the outbound side to frames.

    A disabled runtime (kill switch) answers ``version: 0`` so a capable
    client settles immediately on the JSONL fallback instead of waiting
    out a timeout.
    """
    if not _frames_enabled():
        _emit({"event": "frames", "version": 0})
        return
    codec = "zlib" if str(command.get("codec") or "") == "zlib" else ""
    _emit({"event": "frames", "version": _FRAME_VERSION, "codec": codec})
    _FRAMES["out"] = True
    _FRAMES["codec"] = codec


def _frame_resync(buffer: bytearray) -> None:
    """Drop garbage through the next newline (or all of it).

    After a bad magic/version/length the stream position is untrusted;
    valid traffic is self-delimiting frames or newline-terminated JSON,
    so the next newline is the only honest resync point.
    """
    nl = buffer.find(b"\n", 1)
    if nl < 0:
        buffer.clear()
    else:
        del buffer[:nl + 1]


def _extract_commands(buffer: bytearray) -> list:
    """Every complete inbound message in ``buffer`` (frames + JSON lines).

    Mutates the buffer in place; incomplete trailing frames/lines stay
    buffered for the next read.  Malformed input — bad magic or version,
    oversized lengths, non-JSON frame headers, torn compressed bodies —
    is answered with a clean ``error`` event and resynced past, NEVER
    allowed to hang the loop or kill the resident runtime (a channel
    death mid-frame simply leaves the partial frame buffered until the
    reader sees EOF).  Torn bodies carry ``permanent: true``: re-sending
    identical corrupt bytes can never succeed.
    """
    commands: list = []
    while buffer:
        if buffer[0] == _FRAME_MAGIC[0]:
            if len(buffer) < _FRAME_HEADER.size:
                break  # header still in flight
            magic, version, _verb, flags, hlen, blen = _FRAME_HEADER.unpack(
                bytes(buffer[:_FRAME_HEADER.size])
            )
            if magic != _FRAME_MAGIC or version != _FRAME_VERSION:
                _emit({
                    "event": "error", "code": "bad_frame",
                    "message": (
                        f"bad frame magic/version ({magic!r} v{version})"
                    ),
                })
                _frame_resync(buffer)
                continue
            if hlen > _FRAME_MAX_HEADER or blen > _FRAME_MAX_BODY:
                _emit({
                    "event": "error", "code": "bad_frame",
                    "message": (
                        f"oversized frame (header {hlen}B, body {blen}B)"
                    ),
                })
                _frame_resync(buffer)
                continue
            total = _FRAME_HEADER.size + hlen + blen
            if len(buffer) < total:
                break  # body still in flight
            header = bytes(buffer[_FRAME_HEADER.size:_FRAME_HEADER.size + hlen])
            body = bytes(buffer[_FRAME_HEADER.size + hlen:total])
            del buffer[:total]
            try:
                command = json.loads(header.decode("utf-8"))
                if not isinstance(command, dict):
                    raise ValueError("frame header is not an object")
            except (ValueError, UnicodeDecodeError) as err:
                # Frame consumed whole (lengths were valid): the stream
                # stays in sync, only this message is refused.
                _emit({"event": "error", "code": "bad_frame",
                       "message": f"frame header is not JSON: {err}"})
                continue
            if flags & _FRAME_FLAG_ZLIB:
                try:
                    body = zlib.decompress(body)
                except zlib.error as err:
                    ids = [str(command.get("id") or "")]
                    if command.get("cmd") == "multi_invoke":
                        # A batched frame's op ids live in ops: the
                        # permanent refusal must reach EVERY waiting op,
                        # not evaporate as one id-less log line.
                        ids = [
                            str(op.get("id") or "")
                            for op in (command.get("ops") or [])
                            if isinstance(op, dict)
                        ] or ids
                    for tid in ids:
                        _emit({
                            "event": "error", "id": tid,
                            "code": "bad_frame", "permanent": True,
                            "message": (
                                "frame body failed decompression "
                                f"(torn payload): {err}"
                            ),
                        })
                    continue
            key = command.pop("_body", None)
            if key:
                command[str(key)] = body
            commands.append(command)
        else:
            nl = buffer.find(b"\n")
            if nl < 0:
                break  # line still in flight
            raw = bytes(buffer[:nl])
            del buffer[:nl + 1]
            line = raw.decode(errors="replace").strip()
            if not line:
                continue
            try:
                command = json.loads(line)
            except ValueError:
                _emit({"event": "error", "message": "malformed command"})
                continue
            if isinstance(command, dict):
                commands.append(command)
            else:
                _emit({"event": "error", "message": "malformed command"})
    return commands


class _TelemetryBatcher:
    """Micro-batch coalescing for side-band telemetry frames.

    At 1000+ tokens/s per session the per-record line write + flush + JSON
    parse became its own hot path.  Intermediate ``serve.token`` chunks
    buffer up to a few ms (COVALENT_TPU_SERVE_COALESCE_MS, default 2) or N
    records (COVALENT_TPU_SERVE_COALESCE_MAX, default 32) and ship as ONE
    ``telemetry_batch`` frame whose body is the JSON array of records.
    Everything latency-sensitive — done markers, rejects, stats,
    heartbeats, lifecycle events — flushes the pending buffer and itself
    immediately, so per-id ordering is preserved and stream-final latency
    is untouched.  Each record keeps its own envelope (seq, cumulative
    ``idx``), so the dispatcher's dedup and the serving tier's
    exactly-once replay splice see exactly the records they always did.
    With frames off every record ships as its own JSON line — the
    pre-frame protocol, byte for byte.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pending: dict = {}  # id -> [records]
        self._oldest: dict = {}   # id -> monotonic stamp of first record
        try:
            self.window_s = max(0.0, float(os.environ.get(
                "COVALENT_TPU_SERVE_COALESCE_MS", "2"
            )) / 1000.0)
        except ValueError:
            self.window_s = 0.002
        try:
            self.max_records = max(1, int(os.environ.get(
                "COVALENT_TPU_SERVE_COALESCE_MAX", "32"
            )))
        except ValueError:
            self.max_records = 32

    def reset(self) -> None:
        """Forked children must not inherit buffers or a held lock."""
        self._lock = threading.Lock()
        self._pending = {}
        self._oldest = {}

    def emit(self, task_id: str, data: dict) -> None:
        if not _FRAMES["out"] or self.window_s <= 0:
            _emit({"event": "telemetry", "id": task_id, "data": data})
            return
        urgent = data.get("type") != "serve.token" or data.get("done")
        with self._lock:
            self._pending.setdefault(task_id, []).append(data)
            self._oldest.setdefault(task_id, time.monotonic())
            full = len(self._pending[task_id]) >= self.max_records
        if urgent or full:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, {}
            self._oldest = {}
        for task_id, records in pending.items():
            emit_telemetry_batch(task_id, records)

    def flush_aged(self) -> None:
        """Ship buffers older than the window (called by the owning loops)."""
        if not self._pending:
            return
        now = time.monotonic()
        groups = []
        with self._lock:
            for task_id, t0 in list(self._oldest.items()):
                if now - t0 >= self.window_s:
                    records = self._pending.pop(task_id, None)
                    self._oldest.pop(task_id, None)
                    if records:
                        groups.append((task_id, records))
        for task_id, records in groups:
            emit_telemetry_batch(task_id, records)


def emit_telemetry_batch(task_id: str, records: list) -> None:
    """One coalesced telemetry frame (or per-record lines when frames off)."""
    if not _FRAMES["out"]:
        for data in records:
            _emit({"event": "telemetry", "id": task_id, "data": data})
        return
    try:
        body = json.dumps(records, default=repr).encode()
    except (TypeError, ValueError):
        return
    _emit_frame(
        _VERB_TELEMETRY,
        {"event": "telemetry_batch", "id": task_id,
         "count": len(records), "_body": "records"},
        body,
    )


_BATCHER = _TelemetryBatcher()


def _spawn_task(command: dict, children: dict) -> None:
    task_id = command.get("id")
    spec_path = command.get("spec")
    if not task_id or not spec_path:
        _emit({"event": "error", "id": task_id or "",
               "message": "run requires id and spec"})
        return
    sys.stdout.flush()
    pid = os.fork()
    if pid == 0:
        rc = 1
        try:
            # Fork-safety: an RPC invocation/heartbeat thread may hold the
            # event or emit lock at fork time, and the child inherits the
            # locked state with no thread to ever release it — fresh locks
            # make the child's own event writes deadlock-free.
            global _worker_event_lock, _EMIT_LOCK
            _worker_event_lock = threading.Lock()
            _EMIT_LOCK = threading.Lock()
            # The child's stdout is about to become the task log, not the
            # protocol channel: frame mode and any half-filled telemetry
            # batch belong to the server process alone.
            _FRAMES["out"] = False
            _FRAMES["codec"] = ""
            _BATCHER.reset()
            # The child is a task runner, not a session host: an inherited
            # copy of the server's live sessions would make its heartbeats
            # report a frozen fork-time serve occupancy forever.
            _SERVE_SESSIONS.clear()
            # Same for an in-flight profiler capture: the trace belongs to
            # the server process; a child must neither think one is active
            # nor inherit a lock held at fork time.
            global _PROFILE_LOCK
            _PROFILE_LOCK = threading.Lock()
            _PROFILE_ACTIVE.clear()
            import signal as _signal

            _signal.set_wakeup_fd(-1)
            _signal.signal(_signal.SIGCHLD, _signal.SIG_DFL)
            # The serve-preempt notice handler belongs to the server; a
            # task child's own preemption contract (checkpoint + die) is
            # installed by run_task when the spec configures it.
            _signal.signal(_signal.SIGTERM, _signal.SIG_DFL)
            os.setsid()
            log_fd = os.open(
                command.get("log") or os.devnull,
                os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                0o644,
            )
            devnull = os.open(os.devnull, os.O_RDONLY)
            os.dup2(devnull, 0)
            os.dup2(log_fd, 1)
            os.dup2(log_fd, 2)
            with open(spec_path) as f:
                spec = json.load(f)
            rc = run_task(spec)
        except BaseException:  # noqa: BLE001 - child must never return
            import traceback

            traceback.print_exc()
        finally:
            os._exit(rc)
    children[pid] = task_id
    _emit({"event": "started", "id": task_id, "pid": pid})


# --------------------------------------------------------------------------
# RPC execute-by-digest: the resident executor loop.
#
# Launch mode (above) pays a fork + interpreter state per electron and
# stages args/results through remote disk.  RPC mode keeps the *work* in
# the resident interpreter too: the dispatcher ships the cloudpickled
# function ONCE per connection into the CAS, registers it by digest, and
# thereafter invokes by digest with args inline on the channel — results
# stream back base64-pickled over the same channel.  No per-electron
# process, no pid file, no poll loop, no result file:
#
#   -> {"cmd":"register_fn","digest":"<sha256>","path":"/cas/<sha256>.pkl"}
#   <- {"event":"registered","digest":"<sha256>"}
#   <- {"event":"register_error","digest":"...","code":"digest_mismatch"|
#       "missing"|"load_failed","message":"..."}           (on failure)
#   -> {"cmd":"invoke","id":"<op>","digest":"<sha256>","spec":{...},
#       "args":"<b64 cloudpickle (args, kwargs)>"}            (inline)
#       ... or "args_path"/"args_digest" for oversized args staged in the
#       CAS (digest verified before unpickling, like the function itself)
#   <- {"event":"started","id":"<op>","pid":<server pid>,"rpc":true}
#   <- {"event":"telemetry","id":"<op>","data":{...}}   (task events +
#       heartbeats, same schema/trace contract as launch-mode workers)
#   <- {"event":"result","id":"<op>","ok":true,"data":"<b64 pickle of
#       (result, exception)>"}
#
# Registration digest-verifies the CAS artifact BEFORE unpickling (the
# same torn-payload guard run_task applies) and unpickles once; each
# invocation runs on a daemon thread so the command loop stays live and
# concurrent invocations share the warm imports.  A crash that takes the
# resident process down surfaces to the dispatcher as a channel death —
# classified transient, gang retried, function re-registered.
# --------------------------------------------------------------------------


def _load_fn_payload(path: str, digest: str):
    """``(code, fn_or_error)``: digest-verified CAS bytes -> callable."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as err:
        return "missing", err
    import hashlib

    if hashlib.sha256(data).hexdigest() != digest:
        return "digest_mismatch", RuntimeError(
            f"registered function {path} does not match its content digest "
            "(torn or stale CAS artifact)"
        )
    try:
        import cloudpickle

        return "", cloudpickle.loads(data)
    except BaseException as err:  # noqa: BLE001 - arbitrary user payloads
        return "load_failed", err


def _rpc_register(command: dict, registry: dict) -> None:
    digest = command.get("digest")
    path = command.get("path")
    if not digest or not path:
        _emit({"event": "error", "message": "register_fn requires digest and path"})
        return
    if digest in registry:  # idempotent: re-register is a no-op ack
        _emit({"event": "registered", "digest": digest})
        return
    code, loaded = _load_fn_payload(path, digest)
    if code:
        _emit({
            "event": "register_error", "digest": digest,
            "code": code, "message": repr(loaded),
        })
        return
    registry[digest] = loaded
    _emit({"event": "registered", "digest": digest})


def _decode_rpc_args(command: dict) -> tuple:
    """``(args, kwargs)`` from the invoke command (inline b64 or CAS path).

    CAS-staged args are digest-verified before unpickling — oversized
    payloads keep the same torn-artifact guard inline ones get for free
    (the channel delivered the exact bytes the dispatcher encoded).
    """
    import base64

    import cloudpickle

    raw = command.get("args_bytes")
    b64 = command.get("args")
    if raw is not None:
        # Binary-frame road: the channel delivered the exact pickle bytes,
        # no base64 leg to pay or verify.
        data = raw
    elif b64 is not None:
        data = base64.b64decode(b64)
    else:
        path = command.get("args_path")
        if not path:
            return (), {}
        with open(path, "rb") as f:
            data = f.read()
        expected = command.get("args_digest")
        if expected:
            import hashlib

            if hashlib.sha256(data).hexdigest() != expected:
                raise RuntimeError(
                    f"staged RPC args {path} do not match their content "
                    "digest (torn or stale CAS artifact)"
                )
    args, kwargs = cloudpickle.loads(data)
    return tuple(args), dict(kwargs)


def _pickle_rpc_result(result, exception) -> bytes:
    """The ``(result, exception)`` pickle — byte-identical layout to the
    result file launch mode writes."""
    try:
        import cloudpickle as pick
    except ImportError:
        import pickle as pick
    try:
        return pick.dumps((result, exception))
    except BaseException as err:  # noqa: BLE001 - unpicklable user results
        import pickle

        return pickle.dumps(
            (None, RuntimeError(f"RPC result not picklable: {err!r}"))
        )


def _emit_rpc_result(task_id: str, result, exception, command: dict) -> None:
    """Stream one invocation's result, inline or staged by size.

    The dispatcher's ``rpc_inline_args_max`` policy applies symmetrically:
    a result pickle at or below ``result_max_inline`` rides the channel
    base64-inline; a larger one is written (atomically) to the
    command-provided ``result_path`` and announced by path + sha256 digest
    — a multi-MB pickle must not be base64-inlined onto the channel in
    one write, for the same reason oversized args take the CAS road in.
    No ``result_path`` (or no threshold) preserves the inline-always
    contract.  A staging failure degrades to inline rather than losing
    the result.
    """
    import base64

    data = _pickle_rpc_result(result, exception)
    result_path = command.get("result_path")
    try:
        max_inline = int(command.get("result_max_inline"))
    except (TypeError, ValueError):
        max_inline = -1
    if result_path and 0 <= max_inline < len(data):
        import hashlib

        try:
            tmp = f"{result_path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, result_path)
        except OSError:
            pass  # fall through to the inline road below
        else:
            _emit({
                "event": "result", "id": task_id,
                "ok": exception is None,
                "data_path": result_path,
                "data_digest": hashlib.sha256(data).hexdigest(),
                "bytes": len(data),
            })
            return
    if _FRAMES["out"]:
        # Negotiated binary road: the raw pickle rides the frame body —
        # no base64 inflation, no giant JSON string to escape and parse.
        _emit_frame(
            _VERB_RESULT,
            {"event": "result", "id": task_id,
             "ok": exception is None, "_body": "data_bytes"},
            data,
        )
        return
    _emit({
        "event": "result", "id": task_id,
        "ok": exception is None,
        "data": base64.b64encode(data).decode("ascii"),
    })


def _emit_rpc_event(spec: dict, task_id: str, type: str, **fields) -> None:
    """One worker-side record pushed straight over the channel.

    Same envelope (`_build_worker_event`: ts/pid/seq/trace) as launch-mode
    workers write to their telemetry files — the dispatcher's backhaul
    handler can't tell the transports apart, which is the point.  The
    ``rpc`` marker tells the dispatcher these events did NOT also land in
    a shared-filesystem sink, so they re-emit even on the local transport.
    """
    _BATCHER.emit(task_id, _build_worker_event(spec, type, rpc=True, **fields))


def _start_rpc_heartbeat(spec: dict, task_id: str):
    """Channel-streamed heartbeats for one invocation (no snapshot files)."""
    try:
        interval = float(spec.get("heartbeat_s") or 0)
    except (TypeError, ValueError):
        interval = 0.0
    if interval <= 0:
        return None
    stop = threading.Event()

    def beat_loop() -> None:
        hb_seq = 0
        while True:
            hb_seq += 1
            _emit_rpc_event(
                spec, task_id, "worker.heartbeat",
                hb_seq=hb_seq, interval_s=interval,
                **_heartbeat_payload(""),
            )
            if stop.wait(interval):
                return

    threading.Thread(
        target=beat_loop, name="covalent-tpu-rpc-heartbeat", daemon=True
    ).start()
    return stop


def _run_rpc_task(command: dict, fn) -> None:
    """Execute one registered function in-process and stream the result.

    The launch-mode contract, minus the process: task_started /
    heartbeats / task_finished events (trace-stamped from the spec), user
    exceptions transported — never raised — and device arrays materialised
    to host before pickling.
    """
    task_id = command.get("id") or ""
    spec = dict(command.get("spec") or {})
    spec.setdefault("operation_id", task_id)
    # Same env contract as a launch-mode harness child (os.environ +
    # PYTHONPATH sys.path mirror + jax platform pin): task_env must mean
    # the same thing whichever runtime executes the function.
    _apply_spec_env(spec)
    result, exception = None, None
    try:
        args, kwargs = _decode_rpc_args(command)
    except BaseException as err:  # noqa: BLE001 - torn args fail the task
        args, kwargs, exception = (), {}, err
    _emit_rpc_event(spec, task_id, "worker.task_started", process_id=0)
    heartbeat_stop = _start_rpc_heartbeat(spec, task_id)
    try:
        if exception is None:
            try:
                result = fn(*args, **kwargs)
                result = _to_host(result)
            except Exception as task_error:  # noqa: BLE001 - transported
                exception = task_error
    finally:
        if heartbeat_stop is not None:
            heartbeat_stop.set()
    _emit_rpc_result(task_id, result, exception, command)
    _emit_rpc_event(
        spec, task_id, "worker.task_finished", process_id=0,
        ok=exception is None,
        **({"error": repr(exception)} if exception is not None else {}),
    )


def _rpc_invoke(command: dict, registry: dict, sync: bool = False) -> None:
    task_id = command.get("id")
    digest = command.get("digest")
    if not task_id or not digest:
        _emit({"event": "error", "id": task_id or "",
               "message": "invoke requires id and digest"})
        return
    fn = registry.get(digest)
    if fn is None and command.get("path"):
        # Self-heal a lost registration (agent restarted between the
        # dispatcher's register and invoke) and serve the --rpc-child
        # one-shot mode: load from the CAS path, digest verified.
        code, loaded = _load_fn_payload(command["path"], digest)
        if not code:
            registry[digest] = fn = loaded
    if fn is None:
        _emit({"event": "error", "id": task_id, "code": "unregistered",
               "message": f"no registered function for digest {digest[:12]}"})
        return
    _emit({"event": "started", "id": task_id, "pid": os.getpid(),
           "rpc": True})
    if sync:
        _run_rpc_task(command, fn)
        return
    threading.Thread(
        target=_run_rpc_task, args=(command, fn),
        name=f"covalent-tpu-rpc-{task_id}", daemon=True,
    ).start()


def _rpc_multi_invoke(command: dict, registry: dict) -> None:
    """Batched invoke: N queued electrons for one digest in ONE frame.

    The frame header carries the per-op command dicts (id, spec,
    result_path, ...) plus ``args_lens``; the body is the concatenation of
    each op's args pickle, split back out here by length.  One
    ``multi_started`` acks every op at once; results fan back out by op id
    through the exact same per-invocation path a lone ``invoke`` takes —
    each op gets its own thread, heartbeats, and result event.  A body
    whose lengths don't reconcile is torn content (``permanent``): the
    dispatcher must not burn retries re-sending identical corrupt bytes.
    """
    digest = command.get("digest")
    ops = [op for op in (command.get("ops") or []) if isinstance(op, dict)]
    lens = command.get("args_lens") or []
    body = command.get("args_bytes") or b""
    ids = [str(op.get("id") or "") for op in ops]
    if not digest or not ops or len(lens) != len(ops):
        for tid in ids or [""]:
            _emit({"event": "error", "id": tid, "code": "bad_request",
                   "message": "multi_invoke requires digest, ops and "
                              "args_lens"})
        return
    try:
        lens = [int(n) for n in lens]
        lens_ok = all(n >= 0 for n in lens) and sum(lens) == len(body)
    except (TypeError, ValueError):
        lens_ok = False
    if not lens_ok:
        for tid in ids:
            _emit({"event": "error", "id": tid, "code": "bad_frame",
                   "permanent": True,
                   "message": "multi_invoke args_lens do not match the "
                              "frame body (torn payload)"})
        return
    fn = registry.get(digest)
    if fn is None and command.get("path"):
        code, loaded = _load_fn_payload(command["path"], digest)
        if not code:
            registry[digest] = fn = loaded
    if fn is None:
        for tid in ids:
            _emit({"event": "error", "id": tid, "code": "unregistered",
                   "message": f"no registered function for digest "
                              f"{str(digest)[:12]}"})
        return
    _emit({"event": "multi_started", "ids": ids, "pid": os.getpid(),
           "rpc": True})
    offset = 0
    for op, n in zip(ops, lens):
        op = dict(op)
        op["args_bytes"] = body[offset:offset + n]
        offset += n
        threading.Thread(
            target=_run_rpc_task, args=(op, fn),
            name=f"covalent-tpu-rpc-{op.get('id')}", daemon=True,
        ).start()


def rpc_child() -> int:
    """``harness.py --rpc-child``: one invocation, command on stdin.

    The native C++ agent's invoke support: it forks this runner per
    invocation, pipes the invoke command (which carries the CAS ``path``)
    to stdin, and streams the started/telemetry/result events from stdout
    back over its channel.  Slower than the resident pool loop (one
    interpreter start per call) but keeps the protocol — and the
    no-disk-for-args/results property — uniform across both runtimes.
    """
    buffer = bytearray()
    saw_bytes = False
    while True:
        for command in _extract_commands(buffer):
            if command.get("cmd") == "frames":
                # The native agent pre-announces the client's negotiated
                # frame mode so this runner's result events ride frames.
                _handle_frames_cmd(command)
                continue
            _rpc_invoke(command, {}, sync=True)
            return 0
        data = sys.stdin.buffer.read1(65536)
        if not data:
            break
        saw_bytes = True
        buffer.extend(data)
    if saw_bytes:
        _emit({"event": "error", "message": "malformed invoke command"})
        return 1
    print("usage: harness.py --rpc-child  (invoke command on stdin)",
          file=sys.stderr)
    return 2


# --------------------------------------------------------------------------
# Resident-mode profiling: drive jax.profiler inside the resident runtime.
#
# Launch-mode profiling wraps one harness process per task; the warm
# resident runtimes (RPC invocations, serving sessions) used to be
# unprofilable — setting profile_dir forced launch mode.  These verbs
# capture the resident process itself:
#
#   -> {"cmd":"profile_start","id":"<pid>","dir":"/path/trace_dir"}
#   <- {"event":"profile_started","id":"<pid>","pid":123}
#   <- {"event":"profile_error","id":"<pid>","code":"busy"|"unavailable"|
#       "bad_request"|"not_running"|"stop_failed"|"package_failed",
#       "message":"..."}                                     (on failure)
#   -> {"cmd":"profile_stop","id":"<pid>","artifact_dir":"/cache/cas"}
#   <- {"event":"profile_stopped","id":"<pid>",
#       "path":"/cache/cas/<sha256>.profile.tgz",
#       "digest":"<sha256>","bytes":N}
#
# `profile_stop` packages the trace directory into ONE tar.gz artifact
# named by its own sha256 under `artifact_dir` (the dispatcher points this
# at the CAS dir, so the artifact is content-addressed like every other
# staged payload) and announces path + digest; the dispatcher fetches and
# digest-verifies before trusting the bytes.  jax.profiler is
# process-wide, so exactly one trace runs at a time — a second start is
# refused `busy` rather than corrupting the active capture.  The pool
# server handles the verbs directly (RPC invocations and pool-mode
# serving sessions execute in its process); `--serve-child` handles them
# too so the native agent can forward a capture into the session child
# that actually holds the model.
# --------------------------------------------------------------------------


_PROFILE_LOCK = threading.Lock()
#: {"id", "dir"} while a trace is active (jax.profiler is process-wide).
_PROFILE_ACTIVE: dict = {}


def _profile_start(command: dict) -> None:
    profile_id = str(command.get("id") or "")
    trace_dir = command.get("dir")
    if not profile_id or not trace_dir:
        _emit({"event": "profile_error", "id": profile_id,
               "code": "bad_request",
               "message": "profile_start requires id and dir"})
        return
    sid = str(command.get("sid") or "")
    if sid and sid not in _SERVE_SESSIONS:
        # A sid-pinned capture must land on the runtime hosting that
        # session; tracing whichever process got the command first would
        # return a digest-valid artifact of the WRONG runtime.  Refuse so
        # the dispatcher's target loop moves on to the right worker.
        _emit({"event": "profile_error", "id": profile_id,
               "code": "unknown_session",
               "message": f"no live serving session {sid!r} here"})
        return
    with _PROFILE_LOCK:
        if _PROFILE_ACTIVE:
            _emit({"event": "profile_error", "id": profile_id,
                   "code": "busy",
                   "message": (
                       f"trace {_PROFILE_ACTIVE.get('id')!r} already "
                       "active (the profiler is process-wide)"
                   )})
            return
        try:
            import jax

            os.makedirs(trace_dir, exist_ok=True)
            jax.profiler.start_trace(trace_dir)
        except Exception as err:  # noqa: BLE001 - any profiler failure
            _emit({"event": "profile_error", "id": profile_id,
                   "code": "unavailable", "message": repr(err)})
            return
        _PROFILE_ACTIVE.update({"id": profile_id, "dir": trace_dir})
    _emit({"event": "profile_started", "id": profile_id, "pid": os.getpid()})


def _package_trace(trace_dir: str, artifact_dir: str) -> tuple:
    """``(path, digest, bytes)``: one content-addressed trace artifact.

    Digest is computed over the exact tar bytes shipped, then the file is
    renamed to ``<digest>.profile.tgz`` — the same publish-by-content
    contract as every CAS artifact, so the dispatcher's fetch can verify
    end to end.  The raw trace directory is consumed (removed) so repeat
    captures never accrete worker disk.
    """
    import hashlib
    import shutil
    import tarfile

    os.makedirs(artifact_dir, exist_ok=True)
    tmp = os.path.join(
        artifact_dir, f".profile.tmp.{os.getpid()}.{time.time_ns()}.tgz"
    )
    with tarfile.open(tmp, "w:gz") as tar:
        tar.add(trace_dir, arcname=".")
    sha = hashlib.sha256()
    with open(tmp, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            sha.update(chunk)
    digest = sha.hexdigest()
    final = os.path.join(artifact_dir, f"{digest}.profile.tgz")
    os.replace(tmp, final)
    shutil.rmtree(trace_dir, ignore_errors=True)
    return final, digest, os.path.getsize(final)


def _profile_stop(command: dict) -> None:
    """Validate + hand off; the heavy work runs on a daemon thread.

    Stopping the profiler and tarring/hashing a trace (routinely tens to
    hundreds of MB) must not run inline in the pool server's command
    loop: a capture on a busy server would otherwise freeze ping /
    serve_request / invoke admission for the whole packaging time — long
    enough for the dispatcher's stall detector to tear down the very
    runtime being profiled.
    """
    profile_id = str(command.get("id") or "")
    with _PROFILE_LOCK:
        active = dict(_PROFILE_ACTIVE)
        if not active or (profile_id and active.get("id") != profile_id):
            _emit({"event": "profile_error", "id": profile_id,
                   "code": "not_running",
                   "message": f"no active trace for {profile_id!r}"})
            return
        if _PROFILE_ACTIVE.get("stopping"):
            _emit({"event": "profile_error", "id": profile_id,
                   "code": "not_running",
                   "message": f"trace {profile_id!r} is already stopping"})
            return
        _PROFILE_ACTIVE["stopping"] = True
    artifact_dir = command.get("artifact_dir") or os.path.dirname(
        str(active["dir"]).rstrip("/")
    )
    threading.Thread(
        target=_profile_finish,
        args=(profile_id, str(active["dir"]), artifact_dir,
              bool(command.get("discard"))),
        daemon=True,
        name="profile-stop",
    ).start()


def _profile_finish(
    profile_id: str, trace_dir: str, artifact_dir: str, discard: bool = False
) -> None:
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as err:  # noqa: BLE001
        # The trace may still be running: KEEP the active record (minus
        # the stopping mark) so the caller can retry the stop — clearing
        # it here would wedge profiling on this runtime forever (every
        # later start would hit jax's own trace-in-progress error).
        with _PROFILE_LOCK:
            _PROFILE_ACTIVE.pop("stopping", None)
        _emit({"event": "profile_error", "id": profile_id,
               "code": "stop_failed", "message": repr(err)})
        return
    with _PROFILE_LOCK:
        _PROFILE_ACTIVE.clear()
    if discard:
        # A compensating stop for an abandoned capture (cancelled
        # mid-sleep, lost start ack): no caller will ever fetch the
        # artifact, so skip the tar+hash entirely and reclaim the disk.
        import shutil

        shutil.rmtree(trace_dir, ignore_errors=True)
        _emit({"event": "profile_stopped", "id": profile_id,
               "discarded": True})
        return
    try:
        path, digest, size = _package_trace(trace_dir, artifact_dir)
    except Exception as err:  # noqa: BLE001 - tar/disk failures
        _emit({"event": "profile_error", "id": profile_id,
               "code": "package_failed", "message": repr(err)})
        return
    _emit({"event": "profile_stopped", "id": profile_id,
           "path": path, "digest": digest, "bytes": size})


# --------------------------------------------------------------------------
# Serving sessions: resident model server with in-worker continuous batching.
#
# RPC mode (above) made single *calls* cheap; a serving session makes whole
# REQUEST STREAMS cheap: `serve_open` loads a cloudpickled model-factory
# from the CAS ONCE (digest verified, like register_fn), builds its engine
# — params loaded, decode/prefill programs compiled — and then serves
# request-level commands for the session's whole lifetime.  Tokens stream
# back incrementally over the EXISTING telemetry side-band (same envelope,
# seq counter, and dedup contract as heartbeats), so time-to-first-token is
# real, not end-of-batch:
#
#   -> {"cmd":"serve_open","id":"<sid>","digest":"<sha256>",
#       "path":"/cas/<sha256>.pkl","options":{"queue_max":64,
#       "default_deadline_s":0,"stats_interval_s":1.0},"spec":{...}}
#   <- {"event":"serve_opened","id":"<sid>","slots":4,"pid":123}
#   <- {"event":"serve_error","id":"<sid>","code":"digest_mismatch"|
#       "missing"|"load_failed"|"factory_failed","message":"...",
#       "permanent":true|false,"label":"..."}              (on failure)
#   -> {"cmd":"serve_request","id":"<sid>","rid":"<rid>","prompt":[...],
#       "params":{...},"deadline_s":5.0,"tenant":"a"}
#   <- {"event":"telemetry","id":"<sid>","data":{"type":"serve.token",
#       "rid":"<rid>","idx":N,"tokens":[...],"done":false,...}}  (pushed)
#   <- {"event":"telemetry","id":"<sid>","data":{"type":"serve.reject",
#       "rid":"<rid>","code":"serve_admission_shed"|"unknown_session"|
#       "deadline"|"engine_error","message":"..."}}       (backpressure)
#   <- {"event":"telemetry","id":"<sid>","data":{"type":"serve.stats",
#       "slots":4,"busy":2,"queued":7,"served":123,"tokens_per_s":...}}
#   -> {"cmd":"serve_close","id":"<sid>"}
#   <- {"event":"serve_closed","id":"<sid>","served":123}
#
# The factory returns an ENGINE the session thread drives through a small
# duck-typed surface (no imports required on this side):
#
#   engine.slots          int, concurrent request lanes (default 1)
#   engine.admit(rid, prompt, params)   occupy a free lane (host-side)
#   engine.step() -> [{"rid", "tokens": [...], "done": bool, ...}, ...]
#                         advance every busy lane one chunk
#   engine.cancel(rid)    optional: free a lane early (deadline)
#   engine.close()        optional: teardown at serve_close
#
# Inside the worker an admission queue feeds the engine's slot loop, so
# concurrent requests share one static-shape batch (continuous batching —
# models/serve.py's ContinuousEngine implements this surface for LMs).
# Backpressure is a bounded queue: a request arriving on a full queue is
# rejected immediately with code `serve_admission_shed`, which the
# dispatcher classifies PERMANENT via the duck-typed fault-label hook
# (retrying amplifies the very overload that shed the work).  Per-request
# deadlines are enforced both in the queue and mid-generation.
#
# `serve.token` events carry `idx` — the request's cumulative token count
# BEFORE the chunk — so a dispatcher replaying a deterministic request on a
# fresh session after a mid-stream death can splice the streams with no
# duplicate or lost tokens.
# --------------------------------------------------------------------------


#: Dispatcher epoch fence (split-brain guard).  ``value`` is the highest
#: epoch this worker has EVER seen (epoch command, or the adopt handshake);
#: ``channel`` is the epoch the CURRENT channel declared.  A channel whose
#: declared epoch is below the high-water mark belongs to a dispatcher that
#: crashed and was succeeded — its mutating commands are refused with
#: ``stale_epoch`` so a zombie controller can never double-dispatch work a
#: newer incarnation already owns.  Both start at 0: a dispatcher that
#: never declares an epoch (journaling off, old client) is unfenced.
_EPOCH = {"value": 0, "channel": 0}

#: Commands that mutate worker state and must be epoch-fenced.  Reads
#: (ping, inventories, watch) stay open to any dispatcher — a stale one
#: can look, not touch.
_FENCED_CMDS = frozenset((
    "run", "register_fn", "invoke", "multi_invoke", "serve_open",
    "serve_request", "serve_prefill", "serve_close", "serve_resume",
    "serve_cancel", "serve_attach", "serve_detach", "kill",
))


def _epoch_ok() -> bool:
    return _EPOCH["channel"] >= _EPOCH["value"]


def _handle_epoch_cmd(command: dict) -> None:
    try:
        declared = int(command.get("epoch") or 0)
    except (TypeError, ValueError):
        declared = 0
    _EPOCH["channel"] = declared
    if declared >= _EPOCH["value"]:
        _EPOCH["value"] = declared
        _emit({"event": "epoch_ok", "epoch": declared})
    else:
        _emit({
            "event": "error", "id": "", "code": "stale_epoch",
            "message": (
                f"dispatcher epoch {declared} is stale "
                f"(worker has seen {_EPOCH['value']})"
            ),
        })


def _refuse_stale(name: str, command: dict) -> None:
    """Answer one fenced command from a stale dispatcher.

    The refusal rides whatever event shape that command's waiter settles
    on, so the stale dispatcher fails fast instead of timing out."""
    message = (
        f"stale dispatcher epoch {_EPOCH['channel']} "
        f"(worker fenced at {_EPOCH['value']})"
    )
    sid = str(command.get("id") or "")
    if name == "serve_request":
        _emit({
            "event": "telemetry", "id": sid,
            "data": _build_worker_event(
                {}, "serve.reject", rpc=True,
                rid=str(command.get("rid") or ""),
                code="stale_epoch", message=message,
            ),
        })
    elif name == "serve_prefill":
        _emit({"event": "serve_kv", "id": sid,
               "rid": str(command.get("rid") or ""),
               "code": "stale_epoch", "message": message})
    elif name in ("serve_open", "serve_close"):
        _emit({"event": "serve_error", "id": sid, "code": "stale_epoch",
               "message": message, "permanent": True})
    elif name == "serve_resume":
        _emit({"event": "serve_resumed", "id": sid,
               "rid": str(command.get("rid") or ""),
               "state": "refused", "code": "stale_epoch"})
    elif name in ("serve_attach", "serve_detach"):
        _emit({"event": name + "ed", "id": sid,
               "adapter": str(command.get("adapter") or ""),
               "code": "stale_epoch", "message": message,
               "permanent": True})
    else:
        _emit({"event": "error", "id": sid, "code": "stale_epoch",
               "message": message})


#: sid -> live _ServeSession; read by the heartbeat payload so a serving
#: worker's beats carry slot occupancy.
_SERVE_SESSIONS: dict = {}


def _gray_chaos_from_env() -> dict | None:
    """Worker-side gray-fault injection spec from ``COVALENT_TPU_CHAOS``.

    The transport-level ``ChaosTransport`` gates dispatcher-side ops, but
    a serving brownout has to live where the latency lives: in the decode
    loop.  This parses only the gray keys (``seed``, ``jitter``,
    ``p_slow``, ``slow_factor``) from the same spec — unknown keys are
    *ignored* here (they are the transport's business, validated there) —
    and returns a seeded plan dict, or None when no gray mode is set.
    """
    import random as random_mod

    spec = os.environ.get("COVALENT_TPU_CHAOS", "").strip()
    if not spec:
        return None
    vals = {"seed": 0.0, "jitter": 0.0, "p_slow": 0.0, "slow_factor": 10.0}
    for token in spec.split(","):
        key, sep, value = token.strip().partition("=")
        if sep and key.strip() in vals:
            try:
                vals[key.strip()] = float(value)
            except ValueError:
                pass
    if vals["jitter"] <= 0 and vals["p_slow"] <= 0:
        return None
    return {
        "rng": random_mod.Random(int(vals["seed"])),
        "jitter": vals["jitter"],
        "p_slow": vals["p_slow"],
        "slow_s": vals["slow_factor"] * max(vals["jitter"], 0.01),
    }


def _serve_occupancy() -> dict:
    """Aggregate slot occupancy across this process's live sessions."""
    sessions = list(_SERVE_SESSIONS.values())
    if not sessions:
        return {}
    return {
        "sessions": len(sessions),
        "slots": sum(s.slots for s in sessions),
        "busy": sum(len(s.running) for s in sessions),
        "queued": sum(s.queue.qsize() for s in sessions),
    }


class _ServeSession:
    """One resident serving session: engine + admission queue + loop thread.

    The command loop calls :meth:`submit` / :meth:`close` (cheap, non-
    blocking); everything slow — the factory call (model load + compile),
    admission, decode chunks — runs on the session's own daemon thread so
    the protocol stays live while the engine works.
    """

    def __init__(self, sid: str, command: dict) -> None:
        import queue as queue_mod

        self.sid = sid
        self.spec = dict(command.get("spec") or {})
        self.spec.setdefault("operation_id", sid)
        options = dict(command.get("options") or {})
        try:
            self.queue_max = max(1, int(options.get("queue_max", 64)))
        except (TypeError, ValueError):
            self.queue_max = 64
        try:
            self.default_deadline_s = float(
                options.get("default_deadline_s") or 0.0
            )
        except (TypeError, ValueError):
            self.default_deadline_s = 0.0
        try:
            self.stats_interval_s = float(
                options.get("stats_interval_s") or 1.0
            )
        except (TypeError, ValueError):
            self.stats_interval_s = 1.0
        self.digest = str(command.get("digest") or "")
        self.path = str(command.get("path") or "")
        self.queue: "queue_mod.Queue" = queue_mod.Queue()
        #: serve_prefill commands awaiting the session thread (the
        #: disaggregated tier's prefill-only work: no decode lane taken).
        self.prefill_queue: "queue_mod.Queue" = queue_mod.Queue()
        #: serve_attach/serve_detach commands awaiting the session thread
        #: (adapter splices mutate engine state, so they serialize with
        #: admission and decode on the one thread that owns the engine).
        self.attach_queue: "queue_mod.Queue" = queue_mod.Queue()
        self.attaches = 0
        #: rid -> {"deadline": abs_ts|None, "emitted": n, "t_admit": ts}
        self.running: dict = {}
        #: rid -> full emitted-token list for RUNNING lanes; the recovery
        #: path's `serve_resume` re-emits `history[from:]` so a restarted
        #: dispatcher can splice a surviving stream exactly-once from the
        #: client-held high-water mark.  Guarded by ``_history_lock``
        #: together with the emit, so a resume re-emission and a live
        #: chunk can never interleave with a gap between them.
        self.history: dict = {}
        #: rid -> {"tokens": [...], "error": str} for FINISHED requests
        #: (bounded FIFO): a stream that completed while the dispatcher
        #: was dead resumes to its full final answer instead of "unknown".
        self.finished: dict = {}
        self.finished_max = 256
        #: every rid ever accepted into the queue — distinguishes a
        #: queued-but-unadmitted request ("pending") from one this worker
        #: never saw ("unknown") at resume time.
        self.submitted: set = set()
        #: rids a ``serve_cancel`` asked to kill, drained on the session
        #: thread (running lane -> engine cancel + terminal record;
        #: queued-only -> skipped at admission).  The hedging loser-
        #: cancel path frees decode lanes through here.
        self.cancels: set = set()
        self._cancel_lock = threading.Lock()
        self._cancelled_pending: set = set()
        #: Worker-side gray chaos (seeded slow tail / jitter on decode
        #: steps), parsed from COVALENT_TPU_CHAOS after the task env is
        #: applied — how a bench brownouts ONE replica of a set.
        self._gray = None
        self._history_lock = threading.Lock()
        self.slots = 1
        self.served = 0
        self.tokens_total = 0
        #: KV data plane accounting (disaggregated prefill/decode).
        self.kv_admits = 0
        self.kv_fallbacks = 0
        self.prefills = 0
        self._t_open = time.time()
        self._closed = threading.Event()
        self._engine = None
        self._thread = threading.Thread(
            target=self._loop, name=f"covalent-tpu-serve-{sid}", daemon=True
        )

    # -- command-loop surface (must never block) ---------------------------

    def start(self) -> None:
        self._thread.start()

    def submit(self, command: dict) -> None:
        """Admission control: bounded queue, immediate shed on overflow."""
        rid = str(command.get("rid") or "")
        if not rid:
            self._emit_reject("", "bad_request", "serve_request requires rid")
            return
        if self._closed.is_set():
            self._emit_reject(rid, "unknown_session", "session closed")
            return
        if self.queue.qsize() >= self.queue_max:
            self._emit_reject(
                rid, "serve_admission_shed",
                f"admission queue full ({self.queue_max})",
            )
            return
        command = dict(command)
        command["_enqueued"] = time.monotonic()
        self.submitted.add(rid)
        self.queue.put(command)

    def submit_prefill(self, command: dict) -> None:
        """Queue one prefill-only command (disaggregated tier).

        Same bounded-admission verdict as :meth:`submit`; refusals
        answer with a ``serve_kv`` error event so the dispatcher's
        prefill waiter fails fast (and degrades to a full prefill on the
        decode replica) instead of sitting out its timeout.
        """
        rid = str(command.get("rid") or "")
        if not rid:
            self._emit_kv("", code="bad_request",
                          message="serve_prefill requires rid")
            return
        if self._closed.is_set():
            self._emit_kv(rid, code="unknown_session",
                          message="session closed")
            return
        if self.prefill_queue.qsize() >= self.queue_max:
            self._emit_kv(
                rid, code="serve_admission_shed",
                message=f"prefill queue full ({self.queue_max})",
            )
            return
        self.prefill_queue.put(dict(command))
        # Wake an idle session loop NOW instead of on its 100ms tick: a
        # prefill replica is usually idle exactly when a prefill lands,
        # and the tick would tax every disaggregated request's TTFT.
        self.queue.put(None)

    def submit_attach(self, command: dict) -> None:
        """Queue one serve_attach/serve_detach for the session thread.

        Splices happen BETWEEN decode chunks on the engine's own thread
        — live lanes never observe a half-written bank — and the answer
        (``serve_attached``/``serve_detached``) is emitted from there so
        it cannot reorder against the splice itself.
        """
        name = str(command.get("adapter") or "")
        event = str(command.get("cmd") or "serve_attach") + "ed"
        if self._closed.is_set():
            _emit({"event": event, "id": self.sid, "adapter": name,
                   "code": "unknown_session", "message": "session closed",
                   "permanent": True})
            return
        self.attach_queue.put(dict(command))
        self.queue.put(None)  # wake an idle loop promptly

    def cancel_request(self, rid: str) -> None:
        """Ask the session thread to cancel one request (running or
        queued).  Cheap and non-blocking: the terminal ``serve.token``
        record (``error="cancelled"``) is emitted from the session
        thread so it serializes with live chunks under the history
        lock."""
        if not rid:
            return
        with self._cancel_lock:
            self.cancels.add(rid)
        self.queue.put(None)  # wake an idle loop promptly

    def close(self) -> None:
        self._closed.set()
        self.queue.put(None)  # wake the loop

    def join(self, timeout: float = 10.0) -> None:
        self._thread.join(timeout)

    # -- emission ----------------------------------------------------------

    def _emit_serve(self, type: str, **fields) -> None:
        """One session record over the telemetry side-band (seq-stamped).

        Routed through the coalescer: intermediate token chunks micro-
        batch into one frame per window, everything else flushes through
        immediately (and in order).
        """
        _BATCHER.emit(
            self.sid,
            _build_worker_event(self.spec, type, rpc=True, **fields),
        )

    def _emit_reject(self, rid: str, code: str, message: str) -> None:
        self._emit_serve(
            "serve.reject", rid=rid, code=code, message=message
        )

    def _emit_span(
        self, name: str, trace, t0: float, t1: float | None = None,
        **attrs,
    ) -> None:
        """One worker-side span record over the telemetry side-band.

        ``trace`` is the per-request ``context_of`` carrier off the
        ``serve_request``/``serve_prefill`` command header; without one
        (an old dispatcher, a malformed carrier) the span is dropped —
        a worker must never mint orphan traces the store can't finalize.
        The dispatcher re-emits the record with these ids preserved
        (``SessionSupervisor._on_remote_span``), which is what puts the
        worker's queue/admission/decode time inside the request's own
        waterfall.  ``t0``/``t1`` are monotonic stamps; the wall-clock
        ``start_ts`` is reconstructed here so the two clock domains
        never mix on the wire.
        """
        if not isinstance(trace, dict) or not trace.get("trace_id"):
            return
        t1 = time.monotonic() if t1 is None else t1
        parent = trace.get("span_id")
        fields = {
            "name": name,
            "trace_id": str(trace["trace_id"]),
            "parent_id": str(parent) if parent else None,
            "span_id": os.urandom(8).hex(),
            "start_ts": round(time.time() - (time.monotonic() - t0), 6),
            "duration_s": round(max(0.0, t1 - t0), 6),
            "status": "OK",
        }
        if attrs:
            fields["attributes"] = attrs
        self._emit_serve("span", **fields)

    def _emit_kv(
        self, rid: str, data: bytes | None = None,
        code: str = "", message: str = "",
    ) -> None:
        """One ``serve_kv`` answer to a prefill command: the bundle bytes
        ride a raw binary frame body on a negotiated channel (the same
        road RPC result pickles take), base64-in-JSON otherwise; a
        failure ships the ``code``/``message`` pair with no body."""
        event = {"event": "serve_kv", "id": self.sid, "rid": rid}
        if code:
            event["code"] = code
            event["message"] = message
            _emit(event)
            return
        data = data or b""
        import hashlib as hashlib_mod

        event["digest"] = hashlib_mod.sha256(data).hexdigest()
        event["bytes"] = len(data)
        if _FRAMES["out"]:
            event["_body"] = "data_bytes"
            _emit_frame(_VERB_SERVE, event, data)
        else:
            import base64

            event["data"] = base64.b64encode(data).decode("ascii")
            _emit(event)

    def _pump_prefill(self) -> None:
        """Run queued prefill-only commands on the session thread (the
        engine is single-threaded state) and stream each KV bundle back."""
        import queue as queue_mod

        while True:
            try:
                command = self.prefill_queue.get_nowait()
            except queue_mod.Empty:
                return
            rid = str(command.get("rid") or "")
            prefill = getattr(self._engine, "prefill_only", None)
            if prefill is None:
                self._emit_kv(
                    rid, code="unsupported",
                    message="engine has no prefill_only surface",
                )
                continue
            trace = command.get("trace")
            t_prefill = time.monotonic()
            try:
                data = prefill(
                    command.get("prompt"),
                    dict(command.get("params") or {}),
                )
                if not isinstance(data, (bytes, bytearray)):
                    raise TypeError(
                        f"prefill_only returned {type(data).__name__}, "
                        "want bytes"
                    )
            except BaseException as err:  # noqa: BLE001 - engine refusals
                self._emit_kv(rid, code="prefill_failed", message=repr(err))
                continue
            self.prefills += 1
            self._emit_span(
                "serve.worker.prefill", trace, t_prefill,
                rid=rid, kv_bytes=len(data),
            )
            self._emit_kv(rid, bytes(data))

    def _pump_attach(self) -> None:
        """Apply queued adapter splices on the session thread.

        ``serve_attach`` loads a sha256-verified CAS bundle (the model
        registry's wire form) and calls the engine's duck-typed
        ``attach_adapter(name, payload)``; ``serve_detach`` retires a
        name.  Failures answer with the same event carrying ``code`` /
        ``message`` and a duck-typed ``permanent`` flag (an
        ``AdapterUnsupported`` — bad geometry, full bank, reserved name
        — must refuse ONCE, not burn retries), mirroring the open path's
        fault classification.
        """
        import queue as queue_mod

        while True:
            try:
                command = self.attach_queue.get_nowait()
            except queue_mod.Empty:
                return
            verb = str(command.get("cmd") or "serve_attach")
            event = verb + "ed"
            name = str(command.get("adapter") or "")
            t_attach = time.monotonic()

            def _fail(code: str, err, permanent: bool = True,
                      label: str = "") -> None:
                _emit({"event": event, "id": self.sid, "adapter": name,
                       "code": code, "message": repr(err),
                       "permanent": bool(permanent),
                       **({"label": label} if label else {})})

            if verb == "serve_detach":
                detach = getattr(self._engine, "detach_adapter", None)
                if detach is None:
                    _fail("unsupported",
                          "engine has no detach_adapter surface")
                    continue
                try:
                    detach(name)
                except BaseException as err:  # noqa: BLE001 - refusals
                    _fail("unknown_adapter", err)
                    continue
                _emit({"event": event, "id": self.sid, "adapter": name})
                continue
            attach = getattr(self._engine, "attach_adapter", None)
            if attach is None:
                _fail("unsupported", "engine has no attach_adapter surface")
                continue
            code, payload = _load_fn_payload(
                str(command.get("path") or ""),
                str(command.get("digest") or ""),
            )
            if code:
                _fail(code, payload, permanent=(code == "digest_mismatch"))
                continue
            try:
                digest = attach(name, payload)
            except BaseException as err:  # noqa: BLE001 - engine refusals
                label = getattr(err, "fault_label", "") or ""
                permanent = bool(label) and not bool(
                    getattr(err, "fault_transient", False)
                )
                _fail("attach_failed", err, permanent=permanent,
                      label=label)
                continue
            self.attaches += 1
            _emit({
                "event": event, "id": self.sid, "adapter": name,
                "digest": str(digest or ""),
                "attach_s": round(time.monotonic() - t_attach, 6),
            })

    def _resolve_kv(self, command: dict):
        """``(kv_bytes | None, verified)`` for a KV-attached request.

        The bundle arrives as a raw frame body (``kv_bytes``), base64
        JSON (``kv``), or a CAS path staged by the dispatcher
        (``kv_path``); whichever road, its sha256 must match the
        announced ``kv_digest`` BEFORE the engine may unpickle it —
        exactly the register_fn contract.  Any resolution or digest
        failure returns ``(None, False)``: the caller degrades to a full
        prefill, never a user-visible error.
        """
        data = command.get("kv_bytes")
        if data is None and command.get("kv"):
            import base64

            try:
                data = base64.b64decode(command["kv"])
            except (TypeError, ValueError):
                return None, False
        if data is None and command.get("kv_path"):
            try:
                with open(command["kv_path"], "rb") as f:
                    data = f.read()
            except OSError:
                return None, False
        if data is None:
            return None, False
        import hashlib as hashlib_mod

        digest = str(command.get("kv_digest") or "")
        if not digest or hashlib_mod.sha256(
            data
        ).hexdigest() != digest:
            return None, False
        return bytes(data), True

    def _emit_stats(self) -> None:
        age = max(time.time() - self._t_open, 1e-9)
        extra: dict = {}
        # Engine-local counters (ContinuousEngine.stats: prefix-tree
        # hits/misses, prefill positions, KV traffic) become serving
        # metrics — without this they are invisible to /metrics,
        # /history, and the SLO plane.
        engine_stats = getattr(self._engine, "stats", None)
        if isinstance(engine_stats, dict):
            for key in (
                "prefix_hits", "prefix_misses", "prefill_positions",
                "prefix_evictions", "kv_exports",
                "spec_rounds", "spec_proposed", "spec_accepted",
                "spec_refusals", "mode_refusals",
            ):
                value = engine_stats.get(key)
                if isinstance(value, (int, float)):
                    extra[key] = value
            # Per-decode-mode token counters ride through verbatim (the
            # mode set is closed, so the key space is bounded), as do the
            # adapter bank's lifecycle + per-adapter counters (bounded by
            # COVALENT_TPU_SERVE_ADAPTERS_MAX; the dispatcher reaps the
            # per-name series when the session closes).
            for key, value in engine_stats.items():
                if (
                    key.startswith("mode_tokens_")
                    or key.startswith("adapter_")
                ) and isinstance(value, (int, float)):
                    extra[key] = value
            # The accept rate is computed HERE (not on the dispatcher)
            # so any engine exposing the two counters — the real one or
            # a CI stub — feeds the gauge the same way.
            proposed = engine_stats.get("spec_proposed")
            if isinstance(proposed, (int, float)) and proposed > 0:
                accepted = engine_stats.get("spec_accepted") or 0
                extra["spec_accept_rate"] = round(
                    float(accepted) / float(proposed), 4
                )
        if self.kv_admits or self.kv_fallbacks:
            extra["kv_admits"] = self.kv_admits
            extra["kv_fallbacks"] = self.kv_fallbacks
        if self.prefills:
            extra["prefills"] = self.prefills
        self._emit_serve(
            "serve.stats",
            slots=self.slots,
            busy=len(self.running),
            queued=self.queue.qsize(),
            served=self.served,
            tokens_total=self.tokens_total,
            tokens_per_s=round(self.tokens_total / age, 3),
            **extra,
        )

    # -- session thread ----------------------------------------------------

    def _open_engine(self) -> bool:
        """Load + verify the factory payload, build the engine, ack open."""
        code, loaded = _load_fn_payload(self.path, self.digest)
        if code:
            self._emit_open_error(code, loaded, permanent=(
                code == "digest_mismatch"
            ))
            return False
        try:
            self._engine = loaded()
        except BaseException as err:  # noqa: BLE001 - arbitrary factories
            # Duck-typed permanence: a factory refusing its model shape
            # (e.g. rolling_cache) tags fault_label/fault_transient; the
            # dispatcher must NOT burn gang retries re-opening it.
            label = getattr(err, "fault_label", "") or ""
            permanent = bool(label) and not bool(
                getattr(err, "fault_transient", False)
            )
            self._emit_open_error(
                "factory_failed", err, permanent=permanent, label=label
            )
            return False
        try:
            self.slots = max(1, int(getattr(self._engine, "slots", 1)))
        except (TypeError, ValueError):
            self.slots = 1
        _emit({
            "event": "serve_opened", "id": self.sid,
            "slots": self.slots, "pid": os.getpid(),
        })
        return True

    def _emit_open_error(
        self, code: str, err, permanent: bool = False, label: str = ""
    ) -> None:
        # Mark terminal BEFORE the error leaves the process: the client
        # reopens the sid the moment this event lands, and _serve_open
        # must find a closed session it can wait out — not a live-looking
        # one it refuses as a duplicate.
        self._closed.set()
        _emit({
            "event": "serve_error", "id": self.sid, "code": code,
            "message": repr(err), "permanent": bool(permanent),
            **({"label": label} if label else {}),
        })

    def _admit_waiting(self) -> None:
        """Move queued requests onto free engine lanes (deadline-checked)."""
        import queue as queue_mod

        while len(self.running) < self.slots:
            try:
                command = self.queue.get_nowait()
            except queue_mod.Empty:
                return
            if command is None:
                continue
            rid = str(command.get("rid") or "")
            if rid in self._cancelled_pending:
                # Cancelled while queued: never admit; terminal record so
                # a resume finds "done" with the cancellation marker.
                self._cancelled_pending.discard(rid)
                with self._history_lock:
                    self._emit_serve(
                        "serve.token", rid=rid, idx=0, tokens=[],
                        done=True, error="cancelled",
                    )
                    self._finish_history(rid, "cancelled")
                continue
            deadline_s = command.get("deadline_s", self.default_deadline_s)
            try:
                deadline_s = float(deadline_s or 0.0)
            except (TypeError, ValueError):
                deadline_s = 0.0
            if deadline_s > 0 and (
                time.monotonic() - command["_enqueued"] >= deadline_s
            ):
                self._emit_reject(
                    rid, "deadline",
                    f"request spent its {deadline_s:.1f}s deadline queued",
                )
                continue
            prompt = command.get("prompt")
            params = dict(command.get("params") or {})
            trace = command.get("trace")
            t_admit_start = time.monotonic()
            self._emit_span(
                "serve.worker.queue_wait", trace,
                command["_enqueued"], t_admit_start, rid=rid,
            )
            admitted = False
            if (
                command.get("kv_bytes") is not None
                or command.get("kv")
                or command.get("kv_path")
            ):
                # Disaggregated fast path: scatter the shipped KV bundle
                # straight into a lane (digest-verified first).  ANY
                # failure — torn transfer, mismatched digest, a bundle
                # from a different engine shape, an engine without the
                # surface — degrades to the full prefill below; the
                # caller's stream must never see the difference.
                kv_data, verified = self._resolve_kv(command)
                admit_kv = getattr(self._engine, "admit_from_kv", None)
                if verified and admit_kv is not None:
                    try:
                        admit_kv(rid, kv_data, params)
                        admitted = True
                        self.kv_admits += 1
                    except BaseException:  # noqa: BLE001 - fall back
                        admitted = False
                if not admitted:
                    self.kv_fallbacks += 1
            if not admitted:
                try:
                    self._engine.admit(rid, prompt, params)
                except BaseException as err:  # noqa: BLE001 - rejections
                    self._emit_reject(rid, "engine_error", repr(err))
                    continue
            t_admitted = time.monotonic()
            self._emit_span(
                "serve.worker.admission", trace, t_admit_start, t_admitted,
                rid=rid, kv=admitted,
            )
            self.running[rid] = {
                "deadline": (
                    command["_enqueued"] + deadline_s
                    if deadline_s > 0 else None
                ),
                "emitted": 0,
                "t_admit": t_admitted,
                "trace": trace,
            }

    def _cancel_lane(self, rid: str) -> None:
        cancel = getattr(self._engine, "cancel", None)
        if cancel is not None:
            try:
                cancel(rid)
            except BaseException:  # noqa: BLE001 - best-effort free
                pass

    def _drain_cancels(self) -> None:
        """Apply queued ``serve_cancel`` requests on the session thread.

        A running lane is cancelled mid-stream: engine lane freed, one
        terminal ``serve.token`` (``done=True, error="cancelled"``)
        emitted under the history lock, history moved to the finished
        ring — exactly the deadline-reclaim shape, so a later resume
        answers ``done`` with the cancellation marker.  A rid still
        queued is remembered and skipped at admission.  An unknown rid
        is a no-op (cancels are fire-and-forget and race completion).
        """
        with self._cancel_lock:
            if not self.cancels:
                return
            rids = list(self.cancels)
            self.cancels.clear()
        for rid in rids:
            state = self.running.get(rid)
            if state is None:
                if rid in self.submitted and rid not in self.finished:
                    self._cancelled_pending.add(rid)
                continue
            self._cancel_lane(rid)
            self._emit_span(
                "serve.worker.decode", state.get("trace"),
                state["t_admit"], rid=rid,
                tokens=state["emitted"], error="cancelled",
            )
            with self._history_lock:
                self._emit_serve(
                    "serve.token", rid=rid, idx=state["emitted"],
                    tokens=[], done=True, error="cancelled",
                )
                self.served += 1
                self.running.pop(rid, None)
                self._finish_history(rid, "cancelled")

    def _finish_history(self, rid: str, error: str = "") -> None:
        """Move one rid's history into the bounded finished ring.

        Caller holds ``_history_lock``.  The ring exists for the crash
        window: a stream that completes while no dispatcher is listening
        must still resume to its full final answer, but memory for dead
        streams cannot grow forever."""
        tokens = self.history.pop(rid, [])
        self.finished[rid] = {"tokens": tokens, "error": error}
        while len(self.finished) > self.finished_max:
            self.finished.pop(next(iter(self.finished)))

    def resume(self, rid: str, start: int) -> None:
        """Re-emit one stream's tokens from ``start`` (recovery path).

        Called on the command-loop thread by ``serve_resume`` after a
        dispatcher restart re-adopts this session.  The re-emission and
        any concurrent live chunk serialize on ``_history_lock``, so the
        wire sees ``history[start:]`` at some idx==start followed by
        chunks whose idx continues from the re-emitted end — the
        dispatcher's existing splice dedups any overlap and a gap is
        impossible.  The ``serve_resumed`` ack tells the dispatcher what
        this worker knows: ``streaming`` (live lane, tokens re-emitted),
        ``done`` (finished ring hit, full tail + done re-emitted),
        ``pending`` (queued, nothing emitted yet), ``unknown`` (never
        seen — the dispatcher re-sends the full request).
        """
        start = max(0, int(start or 0))
        with self._history_lock:
            if rid in self.running:
                tokens = list(self.history.get(rid, ())[start:])
                self._emit_serve(
                    "serve.token", rid=rid, idx=start, tokens=tokens,
                    done=False, resumed=True,
                )
                state, sent = "streaming", len(tokens)
            elif rid in self.finished:
                entry = self.finished[rid]
                tokens = list(entry["tokens"][start:])
                extra = (
                    {"error": entry["error"]} if entry.get("error") else {}
                )
                self._emit_serve(
                    "serve.token", rid=rid, idx=start, tokens=tokens,
                    done=True, resumed=True, **extra,
                )
                state, sent = "done", len(tokens)
            elif rid in self.submitted:
                state, sent = "pending", 0
            else:
                state, sent = "unknown", 0
        _emit({
            "event": "serve_resumed", "id": self.sid, "rid": rid,
            "state": state, "from": start, "sent": sent,
        })

    def inventory(self) -> dict:
        """This session's entry in the ``serve_inventory`` answer."""
        with self._history_lock:
            running = {
                rid: int(state.get("emitted") or 0)
                for rid, state in self.running.items()
            }
            finished = {
                rid: {"tokens": len(entry["tokens"]),
                      "error": entry.get("error") or ""}
                for rid, entry in self.finished.items()
            }
        entry = {
            "sid": self.sid,
            "digest": self.digest,
            "slots": self.slots,
            "served": self.served,
            "queued": self.queue.qsize(),
            "running": running,
            "finished": finished,
        }
        # Attached adapters (name -> content digest): the recovery path
        # compares this against the journaled registry records to decide
        # which re-attaches a re-adopted session still needs.
        digests = getattr(self._engine, "adapter_digests", None)
        if isinstance(digests, dict) and digests:
            entry["adapters"] = {
                str(k): str(v) for k, v in digests.items()
            }
        return entry

    def _pump_engine(self) -> None:
        """One decode chunk for every busy lane; stream fresh tokens.

        On speculative engines (``engine.spec_active``) the chunk's wall
        time is attributed per-request PROPORTIONALLY to each request's
        share of the chunk's fresh tokens, accumulated per lane and
        attached to the final token record as ``spec_verify_s`` — the
        dispatcher tiles it into the request's latency waterfall.  An
        attribution, not a measurement: lanes decode fused, so a
        per-request split of one wave is proportional by construction.
        """
        gray = self._gray
        if gray is not None:
            # Seeded gray latency: the engine still answers — just late.
            if gray["jitter"] > 0:
                time.sleep(gray["rng"].random() * gray["jitter"])
            if gray["p_slow"] > 0 and gray["rng"].random() < gray["p_slow"]:
                time.sleep(gray["slow_s"])
        spec = bool(getattr(self._engine, "spec_active", False))
        t_step = time.monotonic()
        try:
            events = self._engine.step() or []
        except BaseException as err:  # noqa: BLE001 - engine crash fails all
            for rid in list(self.running):
                self._emit_reject(rid, "engine_error", repr(err))
                self._cancel_lane(rid)
                self.running.pop(rid, None)
            return
        step_s = time.monotonic() - t_step
        chunk_tokens = sum(
            len(e.get("tokens") or ()) for e in events
        ) if spec else 0
        for event in events:
            rid = str(event.get("rid") or "")
            state = self.running.get(rid)
            if state is None:
                continue
            tokens = list(event.get("tokens") or ())
            done = bool(event.get("done"))
            extra = {
                k: v for k, v in event.items()
                if k not in ("rid", "tokens", "done")
            }
            if spec and chunk_tokens and tokens:
                state["spec_s"] = (
                    state.get("spec_s", 0.0)
                    + step_s * len(tokens) / chunk_tokens
                )
            if done:
                extra.setdefault(
                    "gen_s", round(time.monotonic() - state["t_admit"], 6)
                )
                if state.get("spec_s"):
                    extra.setdefault(
                        "spec_verify_s", round(state["spec_s"], 6)
                    )
                # Span BEFORE the final token record: the dispatcher
                # finalizes the trace on ``done``, and the side-band is
                # ordered — emitting after would strand the decode span
                # as a straggler.
                self._emit_span(
                    "serve.worker.decode", state.get("trace"),
                    state["t_admit"], rid=rid,
                    tokens=state["emitted"] + len(tokens),
                )
            # History extend + emit are one atomic unit under the lock a
            # serve_resume re-emission also takes: either the resume
            # snapshot includes this chunk, or this chunk's idx lands at
            # (or past) the resume's end — never a gap between them.
            with self._history_lock:
                idx = state["emitted"]
                state["emitted"] += len(tokens)
                self.tokens_total += len(tokens)
                if tokens:
                    self.history.setdefault(rid, []).extend(tokens)
                self._emit_serve(
                    "serve.token", rid=rid, idx=idx, tokens=tokens,
                    done=done, **extra,
                )
                if done:
                    self.served += 1
                    self.running.pop(rid, None)
                    self._finish_history(rid, str(extra.get("error") or ""))
        # Mid-generation deadline enforcement: a lane past its budget is
        # cancelled and finalized with an error marker, freeing the slot.
        now = time.monotonic()
        for rid, state in list(self.running.items()):
            if state["deadline"] is not None and now >= state["deadline"]:
                self._cancel_lane(rid)
                self._emit_span(
                    "serve.worker.decode", state.get("trace"),
                    state["t_admit"], rid=rid,
                    tokens=state["emitted"], error="deadline_exceeded",
                )
                with self._history_lock:
                    self._emit_serve(
                        "serve.token", rid=rid, idx=state["emitted"],
                        tokens=[], done=True, error="deadline_exceeded",
                    )
                    self.served += 1
                    self.running.pop(rid, None)
                    self._finish_history(rid, "deadline_exceeded")

    def _loop(self) -> None:
        _apply_spec_env(self.spec)
        self._gray = _gray_chaos_from_env()
        if not self._open_engine():
            # Failed open: mark closed so late requests reject cleanly
            # instead of queueing into a thread that already exited.
            self._closed.set()
            _SERVE_SESSIONS.pop(self.sid, None)
            return
        last_stats = time.monotonic()
        try:
            while not (self._closed.is_set()
                       and not self.running
                       and self.queue.empty()):
                self._drain_cancels()
                self._pump_attach()
                self._pump_prefill()
                self._admit_waiting()
                if self.running:
                    self._pump_engine()
                else:
                    # Idle: block on the queue with a short tick so stats
                    # keep flowing and close() wakes promptly.
                    import queue as queue_mod

                    try:
                        command = self.queue.get(timeout=0.1)
                    except queue_mod.Empty:
                        command = None
                    if command is not None:
                        self.queue.put(command)
                # Age-out the coalescing buffer: a token batch must never
                # wait on MORE tokens to ship once its window expires.
                _BATCHER.flush_aged()
                if (
                    self.stats_interval_s > 0
                    and time.monotonic() - last_stats >= self.stats_interval_s
                ):
                    last_stats = time.monotonic()
                    self._emit_stats()
        finally:
            closer = getattr(self._engine, "close", None)
            if closer is not None:
                try:
                    closer()
                except BaseException:  # noqa: BLE001 - teardown best-effort
                    pass
            self._emit_stats()
            # The stats record is urgent (non-token) so the coalescer has
            # flushed every buffered token ahead of it; serve_closed must
            # still never overtake a straggler batch.
            _BATCHER.flush()
            _SERVE_SESSIONS.pop(self.sid, None)
            _emit({
                "event": "serve_closed", "id": self.sid,
                "served": self.served,
            })


def _serve_open(command: dict, sessions: dict) -> None:
    sid = str(command.get("id") or "")
    if not sid or not command.get("digest") or not command.get("path"):
        _emit({"event": "serve_error", "id": sid, "code": "bad_request",
               "message": "serve_open requires id, digest and path",
               "permanent": True})
        return
    existing = sessions.get(sid)
    if existing is not None:
        if existing._closed.is_set() and existing._thread.is_alive():
            # Terminating but not yet dead: a failed factory open (or a
            # drained close) emits its error BEFORE the thread's last
            # instructions run, and the client legitimately reopens the
            # moment that event lands — wait out the teardown rather
            # than racing it into a spurious permanent "duplicate".
            existing._thread.join(timeout=2.0)
        if existing._closed.is_set() and not existing._thread.is_alive():
            # A dead entry (failed factory open, or a drained close whose
            # serve_close never arrived): evict so the sid is re-openable
            # — the reconnect path retries the SAME sid on a live agent,
            # and a stale tombstone must not refuse it as a duplicate.
            sessions.pop(sid, None)
        else:
            _emit({"event": "serve_error", "id": sid, "code": "duplicate",
                   "message": f"session {sid} already open",
                   "permanent": True})
            return
    session = _ServeSession(sid, command)
    sessions[sid] = session
    _SERVE_SESSIONS[sid] = session
    session.start()


def _serve_request(command: dict, sessions: dict) -> None:
    sid = str(command.get("id") or "")
    session = sessions.get(sid)
    if session is None:
        # Streamed as a per-request reject so the caller's stream fails
        # fast; the envelope needs no session spec (there is none).
        _emit({
            "event": "telemetry", "id": sid,
            "data": _build_worker_event(
                {}, "serve.reject", rpc=True,
                rid=str(command.get("rid") or ""),
                code="unknown_session",
                message=f"no open session {sid!r}",
            ),
        })
        return
    session.submit(command)


def _serve_prefill(command: dict, sessions: dict) -> None:
    sid = str(command.get("id") or "")
    session = sessions.get(sid)
    if session is None:
        # A direct serve_kv error (not a streamed reject): the prefill
        # waiter settles on serve_kv events only.
        _emit({
            "event": "serve_kv", "id": sid,
            "rid": str(command.get("rid") or ""),
            "code": "unknown_session",
            "message": f"no open session {sid!r}",
        })
        return
    session.submit_prefill(command)


def _serve_attach(command: dict, sessions: dict) -> None:
    """Route one adapter splice (attach or detach) to its session."""
    sid = str(command.get("id") or "")
    event = str(command.get("cmd") or "serve_attach") + "ed"
    session = sessions.get(sid)
    if session is None:
        _emit({"event": event, "id": sid,
               "adapter": str(command.get("adapter") or ""),
               "code": "unknown_session",
               "message": f"no open session {sid!r}", "permanent": True})
        return
    session.submit_attach(command)


def _serve_close(command: dict, sessions: dict) -> None:
    sid = str(command.get("id") or "")
    session = sessions.pop(sid, None)
    if session is None:
        _emit({"event": "serve_error", "id": sid, "code": "unknown_session",
               "message": f"no open session {sid!r}", "permanent": True})
        return
    session.close()
    # The session thread emits serve_closed after its drain; nothing to
    # block on here — the command loop must stay live.


def _serve_cancel(command: dict, sessions: dict) -> None:
    """Fire-and-forget cancellation of one in-flight request.

    The hedging path uses this to free the losing replica's decode lane
    the moment the winner's first token lands.  No waiter: an unknown
    session or rid is a silent no-op (the cancel races completion by
    design), so the only answer is the stream's own terminal record.
    """
    sid = str(command.get("id") or "")
    session = sessions.get(sid)
    if session is not None:
        session.cancel_request(str(command.get("rid") or ""))


def _serve_resume(command: dict, sessions: dict) -> None:
    sid = str(command.get("id") or "")
    rid = str(command.get("rid") or "")
    session = sessions.get(sid)
    if session is None:
        _emit({"event": "serve_resumed", "id": sid, "rid": rid,
               "state": "unknown", "from": 0, "sent": 0})
        return
    try:
        start = int(command.get("from") or 0)
    except (TypeError, ValueError):
        start = 0
    session.resume(rid, start)


def _serve_inventory(sessions: dict) -> None:
    entries = []
    for session in list(sessions.values()):
        if session._closed.is_set():
            continue
        try:
            entries.append(session.inventory())
        except Exception:  # noqa: BLE001 - one bad session must not hide rest
            pass
    _emit({
        "event": "serve_inventory", "pid": os.getpid(),
        "epoch": _EPOCH["value"], "sessions": entries,
    })


def _task_inventory(children: dict) -> None:
    _emit({
        "event": "task_inventory", "pid": os.getpid(),
        "epoch": _EPOCH["value"],
        "tasks": [
            {"id": task_id, "pid": pid}
            for pid, task_id in children.items()
        ],
    })


# --------------------------------------------------------------------------
# Orphan self-defense + live re-adoption.
#
# A pool server's only channel is the stdin/stdout pipe of the process the
# dispatcher spawned — when the dispatcher dies, so does the channel, while
# the resident sessions (model weights, running decodes) live on.  Instead
# of tearing them down, a server with live sessions and a configured grace
# TTL (COVALENT_TPU_ORPHAN_TTL_S) goes into *orphan mode*: it silences its
# dead stdout, opens a unix rendezvous socket next to this file (the remote
# cache directory the dispatcher stages into), publishes its coordinates in
# `pool_orphan.json`, and keeps decoding — growing each stream's token
# history — until either a successor dispatcher adopts it (one `adopt`
# line, epoch-fenced, then the socket BECOMES fds 0/1 and a fresh ready
# banner starts the protocol over) or the TTL expires and it drains and
# exits rather than leaking model memory forever.
# --------------------------------------------------------------------------

ORPHAN_RENDEZVOUS = "pool_orphan.json"


def _orphan_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def _orphan_ttl_s() -> float:
    try:
        return float(os.environ.get("COVALENT_TPU_ORPHAN_TTL_S", "0") or 0)
    except (TypeError, ValueError):
        return 0.0


def _enter_orphan_mode(sel, serve_sessions: dict):
    """Switch a channel-dead pool server into adoption-wait; returns the
    orphan state dict, or None when orphan mode does not apply (no live
    sessions, no TTL, or the socket cannot be created)."""
    import selectors
    import socket

    ttl = _orphan_ttl_s()
    live = {
        sid: s for sid, s in serve_sessions.items()
        if not s._closed.is_set()
    }
    if ttl <= 0 or not live:
        return None
    base = _orphan_dir()
    sock_path = os.path.join(base, f"pool_orphan.{os.getpid()}.sock")
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    try:
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(sock_path)
        listener.listen(2)
        listener.setblocking(False)
    except OSError as err:
        print(f"orphan socket failed: {err}", file=sys.stderr)
        return None
    meta = {
        "pid": os.getpid(), "sock": sock_path, "epoch": _EPOCH["value"],
        "sessions": sorted(live), "ttl_s": ttl, "t_orphaned": time.time(),
    }
    rendezvous = os.path.join(base, ORPHAN_RENDEZVOUS)
    tmp = f"{rendezvous}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(meta, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, rendezvous)
    except OSError as err:
        print(f"orphan rendezvous failed: {err}", file=sys.stderr)
        listener.close()
        return None
    # Silence the dead pipe: every emitter (session threads included)
    # keeps running, but writes land in /dev/null instead of raising.
    devnull = os.open(os.devnull, os.O_WRONLY)
    with _EMIT_LOCK:
        try:
            sys.stdout.flush()
        except OSError:
            pass
        os.dup2(devnull, 1)
    os.close(devnull)
    try:
        _BATCHER.flush()
    except Exception:  # noqa: BLE001 - buffers now drain to /dev/null
        pass
    sel.register(listener, selectors.EVENT_READ, "orphan")
    return {
        "listener": listener, "sock_path": sock_path,
        "rendezvous": rendezvous, "deadline": time.monotonic() + ttl,
    }


def _orphan_cleanup(sel, orphan: dict) -> None:
    try:
        sel.unregister(orphan["listener"])
    except (KeyError, ValueError):
        pass
    try:
        orphan["listener"].close()
    except OSError:
        pass
    for path in (orphan["sock_path"], orphan["rendezvous"]):
        try:
            os.unlink(path)
        except OSError:
            pass


def _orphan_try_adopt(sel, orphan: dict, serve_sessions: dict) -> bool:
    """Accept one adoption attempt; True when the socket became the new
    channel (caller restarts the protocol), False to keep waiting."""
    try:
        conn, _ = orphan["listener"].accept()
    except OSError:
        return False
    try:
        conn.setblocking(True)
        conn.settimeout(10.0)
        data = b""
        while not data.endswith(b"\n") and len(data) < 65536:
            chunk = conn.recv(4096)
            if not chunk:
                break
            data += chunk
        try:
            adopt = json.loads(data.decode("utf-8", "replace"))
        except ValueError:
            adopt = {}
        epoch = 0
        try:
            epoch = int(adopt.get("epoch") or 0)
        except (TypeError, ValueError):
            pass
        if adopt.get("cmd") != "adopt" or epoch < _EPOCH["value"]:
            # Fence: a stale dispatcher (or garbage) does not get the
            # sessions — answer and keep waiting for the real successor.
            try:
                conn.sendall((json.dumps({
                    "event": "error", "code": "stale_epoch",
                    "message": (
                        f"adopt epoch {epoch} < fence {_EPOCH['value']}"
                    ),
                }) + "\n").encode())
            except OSError:
                pass
            conn.close()
            return False
        _EPOCH["value"] = epoch
        _EPOCH["channel"] = epoch
        conn.settimeout(None)
        fd = conn.fileno()
        with _EMIT_LOCK:
            try:
                sys.stdout.flush()
            except OSError:
                pass
            os.dup2(fd, 0)
            os.dup2(fd, 1)
            # The adopted channel starts over on JSONL; the successor
            # re-negotiates frames off the fresh banner like any client.
            _FRAMES["out"] = False
            _FRAMES["codec"] = ""
        conn.close()  # fds 0/1 hold the socket now
    except OSError:
        try:
            conn.close()
        except OSError:
            pass
        return False
    _orphan_cleanup(sel, orphan)
    banner = {
        "event": "ready", "pid": os.getpid(), "mode": "pool",
        "reattach": True, "epoch": epoch,
        "sessions": sorted(
            sid for sid, s in serve_sessions.items()
            if not s._closed.is_set()
        ),
    }
    if _frames_enabled():
        banner["frames"] = _FRAME_VERSION
        banner["codecs"] = ["zlib"]
    _emit(banner)
    return True


def attach_relay(sock_path: str) -> int:
    """``harness.py --attach <sock>``: bridge stdio onto an orphan socket.

    The successor dispatcher cannot dial a unix socket on a remote worker
    directly, but it CAN spawn processes there — so re-adoption rides the
    same road as a fresh pool server: spawn this relay via the transport,
    and the relay splices its stdin/stdout onto the orphan's socket.  The
    relay is a dumb pump — the adopt handshake, epoch fence, and banner
    all flow through it verbatim, keeping protocol logic in one place.
    """
    import select as select_mod
    import socket

    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(sock_path)
    except OSError as err:
        sys.stdout.write(json.dumps({
            "event": "error", "code": "attach_failed",
            "message": f"connect {sock_path}: {err}",
        }) + "\n")
        sys.stdout.flush()
        return 3
    sock.setblocking(True)
    sfd = sock.fileno()

    def _write_all(fd: int, data: bytes) -> bool:
        while data:
            try:
                n = os.write(fd, data)
            except OSError:
                return False
            data = data[n:]
        return True

    try:
        while True:
            ready, _, _ = select_mod.select([0, sfd], [], [])
            if 0 in ready:
                data = os.read(0, 65536)
                if not data:
                    break  # dispatcher hung up: orphan re-enters wait
                try:
                    sock.sendall(data)
                except OSError:
                    break
            if sfd in ready:
                data = sock.recv(65536)
                if not data:
                    break  # worker side closed (refused or exited)
                if not _write_all(1, data):
                    break
    finally:
        try:
            sock.close()
        except OSError:
            pass
    return 0


def _announce_preemption(reason: str = "sigterm") -> None:
    """Emit ``serve.preempt`` on every live session's side-band."""
    for session in list(_SERVE_SESSIONS.values()):
        try:
            session._emit_serve("serve.preempt", reason=reason)
        except Exception:  # noqa: BLE001 - notice is best-effort
            pass
    try:
        _BATCHER.flush()
    except Exception:  # noqa: BLE001
        pass


def _install_serve_preempt_notice() -> None:
    """SIGTERM on a serving runtime = the spot preemption notice.

    Announce ``serve.preempt`` on every live session's side-band and KEEP
    SERVING: the dispatcher-side supervisor warm-hands the sessions off to
    a fresh gang during the grace window (draining in-flight streams via
    the exactly-once idx splice), and the preempter's hard kill — or the
    channel death — is what actually ends this process, not the notice.
    """
    import signal

    if threading.current_thread() is not threading.main_thread():
        return  # pragma: no cover - signal API is main-thread-only

    def _on_term(signum, frame):
        if not _SERVE_SESSIONS:
            # Nothing to hand off: keep the pre-notice contract and die
            # with the signal, so plain TERM-driven teardown of idle
            # runtimes is unchanged.
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
            return
        # Never write the channel from the handler itself: the main thread
        # may hold _EMIT_LOCK at delivery time and the handler runs ON the
        # main thread (same-thread deadlock).  A helper thread serializes
        # through the lock normally.
        threading.Thread(
            target=_announce_preemption,
            name="covalent-tpu-preempt-notice", daemon=True,
        ).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # pragma: no cover - exotic platforms
        pass


def serve_child() -> int:
    """``harness.py --serve-child``: one serving session over stdin.

    The native C++ agent's session support: it forks this runner at
    ``serve_open`` with the pipe held open, forwards ``serve_request`` /
    ``serve_close`` lines to stdin, and streams every stdout event back
    over its channel verbatim — the protocol (and the engine contract)
    stays uniform across both runtimes.  EOF closes the session.
    """
    _install_serve_preempt_notice()
    sessions: dict = {}
    opened: list = []  # every session ever opened, for the final drain
    buffer = bytearray()
    closing = False
    while not closing:
        for command in _extract_commands(buffer):
            name = command.get("cmd")
            if name == "frames":
                _handle_frames_cmd(command)
            elif name == "serve_open":
                _serve_open(command, sessions)
                session = sessions.get(str(command.get("id") or ""))
                if session is not None and session not in opened:
                    opened.append(session)
            elif name == "serve_request":
                _serve_request(command, sessions)
            elif name == "serve_cancel":
                _serve_cancel(command, sessions)
            elif name == "serve_resume":
                _serve_resume(command, sessions)
            elif name == "serve_inventory":
                _serve_inventory(sessions)
            elif name == "serve_prefill":
                _serve_prefill(command, sessions)
            elif name in ("serve_attach", "serve_detach"):
                _serve_attach(command, sessions)
            elif name == "profile_start":
                _profile_start(command)
            elif name == "profile_stop":
                _profile_stop(command)
            elif name == "serve_close":
                _serve_close(command, sessions)
                closing = True
                break
            else:
                _emit({"event": "error", "message": f"unknown cmd: {name}"})
        if closing:
            break
        data = sys.stdin.buffer.read1(65536)
        if not data:
            break  # EOF closes the session, as before
        buffer.extend(data)
    for session in sessions.values():
        session.close()
    for session in opened:
        session.join()
    return 0


#: Per-pump read ceiling: one oversized telemetry burst must not wedge the
#: command loop behind a single giant read.
_WATCH_READ_LIMIT = 256 * 1024


def _pump_watchers(watchers: dict) -> None:
    """Forward new complete JSONL lines from every watched file.

    Each watcher tracks a byte offset; partial trailing lines wait in a
    buffer for the next pump.  Unparsable lines are dropped (the side-band
    forwards structured events only), and a missing file just means the
    task hasn't emitted yet.
    """
    for task_id, w in list(watchers.items()):
        try:
            size = os.path.getsize(w["path"])
        except OSError:
            continue
        if size < w["pos"]:
            w["pos"], w["buf"] = 0, ""  # truncated/rotated: start over
        if size == w["pos"]:
            continue
        try:
            with open(w["path"], "r", encoding="utf-8", errors="replace") as f:
                f.seek(w["pos"])
                chunk = f.read(_WATCH_READ_LIMIT)
                w["pos"] = f.tell()
        except OSError:
            continue
        w["buf"] += chunk
        records = []
        while "\n" in w["buf"]:
            line, w["buf"] = w["buf"].split("\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                data = json.loads(line)
            except ValueError:
                continue
            if isinstance(data, dict):
                records.append(data)
        if records:
            # One frame per pump per task (or per-record lines when frames
            # are off): a telemetry burst costs one write, not one per line.
            emit_telemetry_batch(task_id, records)


def _reap(children: dict, watchers: dict | None = None) -> None:
    while True:
        try:
            pid, status = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid <= 0:
            return
        task_id = children.pop(pid, None)
        if task_id is None:
            continue
        code = os.waitstatus_to_exitcode(status)
        if watchers is not None and task_id in watchers:
            # Auto-unwatch on exit (after one final pump so the tail of
            # the telemetry file is flushed): a long-lived server must not
            # keep stat()ing files of finished tasks forever.
            _pump_watchers({task_id: watchers[task_id]})
            del watchers[task_id]
        _emit({
            "event": "exit",
            "id": task_id,
            "code": code if code >= 0 else -1,
            "signal": -code if code < 0 else 0,
        })


def serve() -> int:
    """Forkserver main loop: poll stdin commands + a SIGCHLD wakeup pipe."""
    import selectors
    import signal

    for mod in filter(None, os.environ.get(
        "COVALENT_TPU_POOL_PRELOAD", "cloudpickle"
    ).split(",")):
        try:
            __import__(mod.strip())
        except Exception as preload_error:  # noqa: BLE001 - children retry
            print(f"preload {mod} failed: {preload_error}", file=sys.stderr)

    rpipe, wpipe = os.pipe()
    os.set_blocking(rpipe, False)
    os.set_blocking(wpipe, False)
    signal.set_wakeup_fd(wpipe)
    signal.signal(signal.SIGCHLD, lambda *_: None)
    signal.signal(signal.SIGPIPE, signal.SIG_IGN)
    # Preemption notice for resident serving sessions hosted in THIS
    # process (pool mode): announce, keep serving through the grace window.
    _install_serve_preempt_notice()

    sel = selectors.DefaultSelector()
    sel.register(0, selectors.EVENT_READ, "stdin")
    sel.register(rpipe, selectors.EVENT_READ, "sigchld")

    children: dict = {}
    #: task id -> {"path", "pos", "buf"} telemetry tails (watch cmd).
    watchers: dict = {}
    #: digest -> unpickled callable (register_fn cmd); dies with the
    #: process, which is exactly the lifetime the dispatcher's
    #: per-connection registered-set mirrors.
    rpc_registry: dict = {}
    #: sid -> _ServeSession (serve_open cmd); sessions die with the
    #: channel — a reconnecting dispatcher re-opens on a fresh server.
    serve_sessions: dict = {}
    buffer = bytearray()
    running = True
    stdin_open = True
    #: Non-None while waiting out the orphan grace TTL for re-adoption.
    orphan: dict | None = None
    banner: dict = {"event": "ready", "pid": os.getpid(), "mode": "pool"}
    if _frames_enabled():
        # Capability advertisement: the client answers with a `frames`
        # command (or stays silently on JSONL — old clients, kill switch).
        banner["frames"] = _FRAME_VERSION
        banner["codecs"] = ["zlib"]
    _emit(banner)

    while running and (stdin_open or children or orphan is not None):
        # With live watchers (or an orphan TTL ticking down) the select
        # wakes on a short tick; otherwise block freely.
        tick = 0.25 if (watchers or orphan is not None) else None
        for key, _ in sel.select(timeout=tick):
            if key.data == "sigchld":
                try:
                    while os.read(rpipe, 512):
                        pass
                except BlockingIOError:
                    pass
                _reap(children, watchers)
                continue
            if key.data == "orphan":
                if orphan is not None and _orphan_try_adopt(
                    sel, orphan, serve_sessions
                ):
                    # The orphan socket IS fds 0/1 now: restart the
                    # protocol on it (stale inbound bytes discarded).
                    orphan = None
                    stdin_open = True
                    buffer.clear()
                    sel.register(0, selectors.EVENT_READ, "stdin")
                continue
            data = os.read(0, 65536)
            if not data:
                # Channel dropped: children keep running in their own
                # sessions; serve until they are all reaped, then exit.
                # Serving sessions historically died with the channel —
                # but with an orphan grace TTL configured they are held
                # (still decoding, token history growing) for a successor
                # dispatcher to re-adopt; only when no TTL/no sessions
                # do they drain immediately as before.
                stdin_open = False
                sel.unregister(0)
                orphan = _enter_orphan_mode(sel, serve_sessions)
                if orphan is None:
                    for session in list(serve_sessions.values()):
                        session.close()
                    serve_sessions.clear()
                continue
            buffer.extend(data)
            for command in _extract_commands(buffer):
                name = command.get("cmd")
                if name == "ping":
                    _emit({"event": "pong"})
                elif name == "frames":
                    _handle_frames_cmd(command)
                elif name == "epoch":
                    _handle_epoch_cmd(command)
                elif name == "serve_inventory":
                    _serve_inventory(serve_sessions)
                elif name == "task_inventory":
                    _task_inventory(children)
                elif name in _FENCED_CMDS and not _epoch_ok():
                    _refuse_stale(name, command)
                elif name == "serve_resume":
                    _serve_resume(command, serve_sessions)
                elif name == "run":
                    _spawn_task(command, children)
                elif name == "register_fn":
                    _rpc_register(command, rpc_registry)
                elif name == "invoke":
                    _rpc_invoke(command, rpc_registry)
                elif name == "multi_invoke":
                    _rpc_multi_invoke(command, rpc_registry)
                elif name == "serve_open":
                    _serve_open(command, serve_sessions)
                elif name == "serve_request":
                    _serve_request(command, serve_sessions)
                elif name == "serve_cancel":
                    _serve_cancel(command, serve_sessions)
                elif name == "serve_prefill":
                    _serve_prefill(command, serve_sessions)
                elif name in ("serve_attach", "serve_detach"):
                    _serve_attach(command, serve_sessions)
                elif name == "serve_close":
                    _serve_close(command, serve_sessions)
                elif name == "profile_start":
                    _profile_start(command)
                elif name == "profile_stop":
                    _profile_stop(command)
                elif name == "kill":
                    target = command.get("id")
                    sig = int(command.get("sig", 15))
                    for pid, task_id in list(children.items()):
                        if task_id == target:
                            # Group AND direct pid: a kill racing the child's
                            # setsid() would otherwise no-op (same guard as
                            # native/agent.cc kill_task).
                            try:
                                os.killpg(pid, sig)
                            except ProcessLookupError:
                                pass
                            try:
                                os.kill(pid, sig)
                            except ProcessLookupError:
                                pass
                            _emit({"event": "killed", "id": target})
                            break
                    else:
                        _emit({"event": "error", "id": target or "",
                               "message": "unknown task id"})
                elif name == "watch":
                    task_id = command.get("id")
                    path = command.get("path")
                    if not task_id or not path:
                        _emit({"event": "error", "id": task_id or "",
                               "message": "watch requires id and path"})
                    else:
                        # Offset 0 on every (re-)watch: a reconnecting
                        # dispatcher gets the buffered backlog flushed.
                        watchers[task_id] = {"path": path, "pos": 0,
                                             "buf": ""}
                        _emit({"event": "watching", "id": task_id})
                elif name == "unwatch":
                    task_id = command.get("id")
                    watchers.pop(task_id, None)
                    _emit({"event": "unwatched", "id": task_id or ""})
                elif name == "shutdown":
                    _emit({"event": "bye"})
                    running = False
                else:
                    _emit({"event": "error",
                           "message": f"unknown cmd: {name}"})
        if orphan is not None and time.monotonic() >= orphan["deadline"]:
            # Grace TTL spent with no successor: drain and exit instead of
            # leaking model memory (and a TPU reservation) forever.
            _orphan_cleanup(sel, orphan)
            orphan = None
            for session in list(serve_sessions.values()):
                session.close()
            serve_sessions.clear()
        _pump_watchers(watchers)
        _reap(children, watchers)  # belt-and-braces against missed wakeups
    return 0


def main(argv: list[str]) -> int:
    if len(argv) == 2 and argv[1] == "--serve":
        return serve()
    if len(argv) >= 2 and argv[1] == "--rpc-child":
        return rpc_child()
    if len(argv) >= 2 and argv[1] == "--serve-child":
        return serve_child()
    if len(argv) >= 3 and argv[1] == "--attach":
        return attach_relay(argv[2])
    if len(argv) != 2:
        print(
            "usage: harness.py <task_spec.json> | --serve | --rpc-child"
            " | --serve-child | --attach <socket>",
            file=sys.stderr,
        )
        return 2
    # Become a session/process-group leader (pool-mode children already do
    # this in _spawn_task): the dispatcher's cancel and timeout-escalation
    # paths kill `-- -pid`, and only a group leader pid makes that reach
    # the user function's own subprocesses — no orphans on billed TPU time.
    try:
        os.setsid()
    except OSError:
        pass  # already a leader (or platform without sessions)
    with open(argv[1]) as f:
        spec = json.load(f)
    return run_task(spec)


if __name__ == "__main__":
    sys.exit(main(sys.argv))
