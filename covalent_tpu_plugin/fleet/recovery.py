"""Dispatcher crash recovery: replayed journal state → re-adopted fleet.

The journal (:mod:`.journal`) records what the dead dispatcher *meant*
to be true; the workers themselves know what *survived* (orphan-mode
pool servers hold their sessions through the dispatcher's death).  This
module reconciles the two on restart:

1. ``lease_gang()`` re-dials every worker.  The agent warm-up path
   tries orphan adoption first (``_try_adopt_orphan`` reads the
   rendezvous file, fence-checks the epoch, and splices the successor's
   channel onto the surviving process) and declares the new epoch on
   every channel — so by the time the lease returns, stale-dispatcher
   fencing is up and surviving pool servers are back on live pipes.
2. ``serve_inventory`` / ``task_inventory`` ask each worker what it
   still holds: sessions by generation sid, running rids with
   emitted-token counts, forked task children.
3. Each journaled session found in an inventory is re-adopted into a
   fresh :class:`~..serving.supervisor.SessionSupervisor`
   (:meth:`~..serving.supervisor.SessionSupervisor.adopt`), and each
   journaled in-flight stream is re-attached with
   :meth:`~..serving.supervisor.SessionSupervisor.resume_stream` from
   its journaled token high-water mark — the worker re-emits history
   from that offset and the supervisor's idx-splice keeps delivery
   exactly-once.  Journaled sessions NO worker still holds are reaped:
   counted, journaled closed, reported.
4. Journaled in-flight electrons are *reported*, not re-run: Covalent's
   own retry re-dispatches them, and the checkpoint-resume discovery
   path (``_discover_resume``) picks up whatever step the orphaned run
   reached.

The whole pass is fenced by the epoch bump :meth:`Journal.open` already
performed — a zombie predecessor that wakes up mid-recovery finds every
worker refusing its commands.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

from . import journal as journal_mod
from ..obs import events as obs_events
from ..obs.metrics import REGISTRY
from ..utils.log import app_log

__all__ = ["recover", "RecoveryReport", "last_report"]

RECOVERY_DURATION = REGISTRY.histogram(
    "covalent_tpu_recovery_duration_seconds",
    "Wall time of one dispatcher crash-recovery pass",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
RECOVERY_ADOPTED = REGISTRY.counter(
    "covalent_tpu_recovery_adopted_total",
    "Surviving sessions re-adopted from orphaned workers after a "
    "dispatcher restart",
)
RECOVERY_ORPHANED = REGISTRY.counter(
    "covalent_tpu_recovery_orphaned_total",
    "Journaled sessions no surviving worker still held (reaped)",
)
RECOVERY_STREAMS = REGISTRY.counter(
    "covalent_tpu_recovery_streams_total",
    "In-flight streams re-attached from journaled high-water marks",
    ("state",),
)
RECOVERY_ADAPTERS = REGISTRY.counter(
    "covalent_tpu_recovery_adapters_total",
    "Journaled LoRA adapters restored to re-adopted sessions "
    "(resident: the worker still held it; attached: re-shipped from "
    "the local CAS; error: restore failed)",
    ("state",),
)

#: The last completed recovery pass, for the ``/status`` recovery
#: section and the bench drill's assertions.  One dispatcher process
#: recovers at most once per incarnation, so a module global is enough.
_LAST_REPORT: dict | None = None


def last_report() -> dict | None:
    """The most recent recovery report (``None`` before any recovery)."""
    return _LAST_REPORT


class RecoveryReport(dict):
    """The recovery pass's outcome — a dict, plus the live handles.

    The dict half is JSON-safe (it feeds ``/status`` and the bench
    drill's artifact); ``supervisors`` and ``requests`` carry the
    re-adopted runtime objects so the caller can await the resumed
    streams' results directly.
    """

    def __init__(self) -> None:
        super().__init__()
        #: sid -> the re-adopted SessionSupervisor
        self.supervisors: dict[str, Any] = {}
        #: (sid, rid) -> the resumed ServeRequest
        self.requests: dict[tuple[str, str], Any] = {}


def _status_section() -> dict:
    report = _LAST_REPORT
    if report is None:
        return {"recovered": False}
    return dict(report)


async def recover(executor: Any, timeout_s: float = 120.0) -> RecoveryReport:
    """Run one crash-recovery pass for ``executor``.

    Reads the journal's *replayed* state (``journal.recovered`` — the
    dead incarnation's world, captured before the epoch bump), re-dials
    the fleet, and re-adopts everything that survived.  Safe to call
    when journaling is off or the journal was empty: returns a report
    with ``recovered=False`` and touches nothing.
    """
    global _LAST_REPORT
    report = RecoveryReport()
    journal = journal_mod.get_journal()
    prior = dict(journal.recovered) if journal is not None else {}
    sessions: dict[str, dict] = dict(prior.get("sessions") or {})
    streams: dict[str, dict] = dict(prior.get("streams") or {})
    tasks: dict[str, dict] = dict(prior.get("tasks") or {})
    report.update({
        "recovered": False,
        "epoch": journal.epoch if journal is not None else 0,
        "journaled_sessions": len(sessions),
        "journaled_streams": len(streams),
        "journaled_tasks": len(tasks),
        "adopted_sessions": [],
        "orphaned_sessions": [],
        "resumed_streams": [],
        "reattached_adapters": [],
        "pending_tasks": sorted(tasks),
        "pools": dict(prior.get("pools") or {}),
        "pool_targets": dict(prior.get("pool_targets") or {}),
        "replica_sets": dict(prior.get("replica_sets") or {}),
        "workers": [],
        "duration_s": 0.0,
    })
    if journal is None or not (sessions or streams or tasks):
        _LAST_REPORT = dict(report)
        return report

    t0 = time.monotonic()
    app_log.info(
        "recovery: epoch %d, replayed %d session(s) / %d stream(s) / "
        "%d task(s) from journal",
        journal.epoch, len(sessions), len(streams), len(tasks),
    )

    # -- 1. re-dial.  lease_gang's warm-up adopts orphaned pool servers
    # (rendezvous + fence-checked attach) and declares the new epoch on
    # every channel before this returns.
    lease = await asyncio.wait_for(executor.lease_gang(), timeout_s)
    # Re-dialed workers start with NEUTRAL health: pre-crash scores and
    # quarantines describe the dead incarnation's observations, and a
    # stale quarantine would drain a worker that just proved itself by
    # answering the re-dial.  Real traffic re-earns the grade.
    from .health import HEALTH

    for address in lease.addresses:
        HEALTH.neutral(str(address))

    # -- 2. inventory every live channel.
    by_sidg: dict[str, tuple[Any, Any, str, dict]] = {}
    running_tasks: list[dict] = []
    for conn, address in zip(lease.conns, lease.addresses):
        client = executor._agents.get(conn.address)
        if client is None:
            continue
        worker: dict = {"address": address, "sessions": [], "tasks": 0}
        try:
            inv = await client.serve_inventory()
            tinv = await client.task_inventory()
        except Exception as err:  # noqa: BLE001 - a dead worker is data
            worker["error"] = repr(err)
            report["workers"].append(worker)
            continue
        for entry in inv.get("sessions") or []:
            sid_g = str(entry.get("sid") or "")
            if sid_g:
                by_sidg[sid_g] = (client, conn, address, dict(entry))
                worker["sessions"].append(sid_g)
        children = list(tinv.get("tasks") or [])
        worker["tasks"] = len(children)
        running_tasks.extend(children)
        report["workers"].append(worker)

    # -- 3. re-adopt each journaled session a worker still holds; resume
    # its journaled streams from their high-water marks.
    from ..serving.supervisor import ServeRequest, SessionSupervisor

    for sid, meta in sessions.items():
        sid_g = str(meta.get("sid_g") or "")
        found = by_sidg.pop(sid_g, None)
        if found is None:
            report["orphaned_sessions"].append(sid)
            RECOVERY_ORPHANED.inc()
            # Journal the reap so the NEXT replay doesn't resurrect it.
            journal_mod.record("session_closed", sid=sid, sync=True)
            continue
        client, conn, address, entry = found
        supervisor = SessionSupervisor(
            executor,
            sid=sid,
            queue_max=meta.get("queue_max"),
            default_deadline_s=meta.get("default_deadline_s"),
            stats_interval_s=meta.get("stats_interval_s"),
        )
        try:
            await supervisor.adopt(
                client=client,
                conns=[conn],
                address=address,
                sid_g=sid_g,
                slots=int(entry.get("slots") or meta.get("slots") or 1),
                digest=str(meta.get("digest") or entry.get("digest") or ""),
                payload_path=str(meta.get("payload") or ""),
            )
        except Exception as err:  # noqa: BLE001 - keep recovering others
            app_log.warning("recovery: adopt of %s failed: %r", sid, err)
            report["orphaned_sessions"].append(sid)
            RECOVERY_ORPHANED.inc()
            continue
        report["adopted_sessions"].append(sid)
        report.supervisors[sid] = supervisor
        RECOVERY_ADOPTED.inc()
        # Restore journaled adapters BEFORE resuming streams: a resumed
        # request naming an adapter the fresh engine view lacks would
        # refuse.  The worker's inventory says which adapters survived
        # in-engine (by content digest) — those are book-kept without
        # re-shipping a byte; anything else re-attaches from the
        # dispatcher-local CAS bundle the journal points at.
        resident = (
            entry.get("adapters")
            if isinstance(entry.get("adapters"), dict) else {}
        ) or {}
        for aname, arec in dict(meta.get("adapters") or {}).items():
            arec = arec if isinstance(arec, dict) else {}
            content = str(arec.get("content") or "")
            try:
                if content and str(resident.get(aname) or "") == content:
                    supervisor.note_adapter(
                        aname,
                        digest=str(arec.get("digest") or ""),
                        path=str(arec.get("path") or ""),
                        content=content,
                    )
                    state = "resident"
                else:
                    await supervisor.attach_adapter(
                        aname,
                        path=str(arec.get("path") or ""),
                        digest=str(arec.get("digest") or ""),
                    )
                    state = "attached"
            except Exception as err:  # noqa: BLE001 - keep recovering
                app_log.warning(
                    "recovery: adapter %r re-attach on %s failed: %r",
                    aname, sid, err,
                )
                state = "error"
            RECOVERY_ADAPTERS.labels(state=state).inc()
            report["reattached_adapters"].append({
                "sid": sid, "adapter": aname, "state": state,
            })
        for key, srec in streams.items():
            ssid, _, rid = key.partition("\x00")
            if ssid != sid or not rid:
                continue
            request = ServeRequest(
                rid,
                list(srec.get("prompt") or []),
                dict(srec.get("params") or {}),
                float(srec.get("deadline_s") or 0.0),
                str(srec.get("tenant") or ""),
            )
            request.resumed_from = int(srec.get("hwm") or 0)
            try:
                state = await supervisor.resume_stream(request)
            except Exception as err:  # noqa: BLE001
                app_log.warning(
                    "recovery: resume of %s/%s failed: %r", sid, rid, err
                )
                RECOVERY_STREAMS.labels(state="error").inc()
                report["resumed_streams"].append({
                    "sid": sid, "rid": rid, "state": "error",
                    "from": request.resumed_from,
                })
                continue
            RECOVERY_STREAMS.labels(state=state or "unknown").inc()
            report.requests[(sid, rid)] = request
            report["resumed_streams"].append({
                "sid": sid, "rid": rid, "state": state,
                "from": request.resumed_from,
            })

    # Surviving sessions the journal never heard of (journaling enabled
    # mid-flight, or a torn tail ate the open record): count them so the
    # operator sees the mismatch, but leave them alone — their worker
    # keeps serving whoever still holds the other end.
    report["unjournaled_sessions"] = sorted(by_sidg)
    report["running_task_children"] = len(running_tasks)
    report["recovered"] = True
    report["duration_s"] = round(time.monotonic() - t0, 3)
    RECOVERY_DURATION.observe(report["duration_s"])
    _LAST_REPORT = dict(report)
    try:
        from ..obs.opsserver import register_status_provider

        register_status_provider("recovery", _status_section)
    except Exception:  # noqa: BLE001 - ops server is optional
        pass
    obs_events.emit(
        "recovery.complete",
        epoch=report["epoch"],
        adopted=len(report["adopted_sessions"]),
        orphaned=len(report["orphaned_sessions"]),
        streams=len(report["resumed_streams"]),
        adapters=len(report["reattached_adapters"]),
        duration_s=report["duration_s"],
    )
    app_log.info(
        "recovery: adopted %d session(s), reaped %d, resumed %d "
        "stream(s) in %.3fs",
        len(report["adopted_sessions"]), len(report["orphaned_sessions"]),
        len(report["resumed_streams"]), report["duration_s"],
    )
    return report
