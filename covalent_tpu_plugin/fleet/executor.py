"""FleetExecutor: the ``@ct.electron(executor=...)`` facade over the queue.

Electrons keep the executor surface they already have — the workflow
runner calls ``run(fn, args, kwargs, task_metadata)`` and awaits a result
— but a ``FleetExecutor`` routes that call through the fleet work queue
instead of mapping it 1:1 onto a gang: admission control applies, tenants
share under deficit round-robin, and the placement engine bin-packs the
electron onto whichever pool's warm gang fits best.

Three spellings::

    # 1. The process-wide default fleet (pools from COVALENT_TPU_POOLS /
    #    the fleet.pools config key, CPU fallback auto-registered):
    @ct.electron(executor="fleet")
    def task(...): ...

    # 2. Tenant/pool-tagged facades over the same shared scheduler:
    heavy = FleetExecutor(tenant="batch")
    @ct.electron(executor=heavy, metadata={"tenant": "batch"})

    # 3. A private fleet (owns its scheduler; closed with the facade):
    fleet = FleetExecutor(pools=[
        {"name": "v5e", "workers": ["w1", "w2"], "capacity": 4},
        {"name": "cpu", "fallback": True, "capacity": 2},
    ])

Electron metadata wins over the facade's defaults: the runner threads
``metadata={"tenant": ..., "pool": ...}`` into ``task_metadata``, so one
facade instance can serve many tenants.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

from ..utils.config import get_config
from ..utils.log import app_log
from .pools import PoolRegistry, PoolSpec
from .queue import DEFAULT_TENANT, FairWorkQueue
from .scheduler import AutoscaleHook, FleetScheduler

_default_lock = threading.Lock()
_default: FleetScheduler | None = None


def default_scheduler() -> FleetScheduler:
    """The process-wide fleet scheduler, built lazily on first use.

    Pools come from ``COVALENT_TPU_POOLS`` (or the ``fleet.pools`` config
    key); a CPU/local fallback pool is always ensured so ``executor=
    "fleet"`` works out of the box.  Queue knobs read the ``fleet.*``
    config keys (``queue_depth``, ``admission``, ``tenant_weights``).
    """
    global _default
    with _default_lock:
        if _default is None:
            registry = PoolRegistry.from_environment()
            registry.ensure_fallback()
            _default = FleetScheduler(registry, queue=_queue_from_config())
        return _default


def reset_default_scheduler() -> None:
    """Forget the process default (tests; the old one is NOT closed —
    callers holding electrons on it drain first)."""
    global _default
    with _default_lock:
        _default = None


def _queue_from_config() -> FairWorkQueue:
    weights_raw = get_config("fleet.tenant_weights", {}) or {}
    weights = {}
    if isinstance(weights_raw, dict):
        for tenant, weight in weights_raw.items():
            try:
                weights[str(tenant)] = float(weight)
            except (TypeError, ValueError):
                continue
    return FairWorkQueue(
        max_depth=int(get_config("fleet.queue_depth", 1024) or 0),
        policy=str(get_config("fleet.admission", "reject") or "reject"),
        weights=weights,
    )


class FleetExecutor:
    """Queue-routed executor facade (``executor="fleet"`` registers one).

    ``scheduler`` binds an explicit scheduler; ``pools`` builds a private
    one from specs (owned: ``close()`` tears it down); with neither, the
    facade rides the shared process default.  ``tenant``/``pool`` are
    defaults for electrons that carry no metadata of their own.
    """

    SHORT_NAME = "fleet"

    def __init__(
        self,
        scheduler: FleetScheduler | None = None,
        tenant: str = DEFAULT_TENANT,
        pool: str | None = None,
        pools: "Sequence[PoolSpec | dict] | None" = None,
        queue: FairWorkQueue | None = None,
        autoscale: AutoscaleHook | None = None,
        ensure_fallback: bool = True,
    ) -> None:
        if scheduler is not None and pools is not None:
            raise ValueError("pass either `scheduler` or `pools`, not both")
        if pools is None and (queue is not None or autoscale is not None):
            # Silently dropping a caller's bounded queue would disable
            # the admission control they configured.
            raise ValueError(
                "queue=/autoscale= configure a PRIVATE scheduler and "
                "require pools=; tune the shared fleet via the fleet.* "
                "config keys (queue_depth, admission, tenant_weights) or "
                "pass an explicit scheduler"
            )
        self.tenant = str(tenant)
        self.pool = pool
        self._owns_scheduler = False
        if pools is not None:
            registry = PoolRegistry()
            for spec in pools:
                registry.register(spec)
            if ensure_fallback:
                registry.ensure_fallback()
            # Private fleets honor the same fleet.* config knobs as the
            # shared default (an explicit queue always wins): queue_depth/
            # admission/tenant_weights apply to the README's pools= shape.
            scheduler = FleetScheduler(
                registry,
                queue=queue if queue is not None else _queue_from_config(),
                autoscale=autoscale,
            )
            self._owns_scheduler = True
        self._scheduler = scheduler

    @property
    def scheduler(self) -> FleetScheduler:
        if self._scheduler is None:
            self._scheduler = default_scheduler()
        return self._scheduler

    async def run(
        self,
        function: Callable,
        args: list | tuple,
        kwargs: dict,
        task_metadata: dict,
    ) -> Any:
        metadata = dict(task_metadata or {})
        metadata.setdefault("tenant", self.tenant)
        if self.pool is not None:
            metadata.setdefault("pool", self.pool)
        return await self.scheduler.run(function, args, kwargs, metadata)

    async def prewarm(self) -> bool:
        """DAG-driven warm-up hook (the runner calls this on dep-blocked
        nodes): warms the fleet's accelerator pools."""
        return await self.scheduler.prewarm()

    async def cancel(self, operation_id: str | None = None) -> None:
        """Cancel one electron by operation id — or, on a PRIVATELY owned
        fleet, everything.  A facade riding the shared scheduler refuses
        the cancel-all spelling: other dispatches and facades share that
        queue, and one caller's teardown must not fail their electrons."""
        if operation_id is None and not self._owns_scheduler:
            app_log.warning(
                "FleetExecutor.cancel() without an operation id ignored: "
                "this facade rides the shared fleet scheduler, and a "
                "blanket cancel would kill other dispatches' electrons"
            )
            return
        await self.scheduler.cancel(operation_id)

    def attempts_of(self, operation_id: str) -> int:
        return self.scheduler.attempts_of(operation_id)

    async def close(self) -> None:
        """Close a privately owned scheduler; shared ones stay up (other
        facades and future dispatches ride them)."""
        if self._owns_scheduler and self._scheduler is not None:
            await self._scheduler.close()
