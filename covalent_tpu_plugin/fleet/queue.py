"""Admission-controlled work queue with per-tenant weighted fairness.

The dispatch queue is the fleet's front door: every electron submitted
through the :class:`~covalent_tpu_plugin.fleet.executor.FleetExecutor`
facade becomes a :class:`WorkItem` here and waits for the placement engine
to bin-pack it onto a warm gang.  Two properties make the queue safe to
put in front of sustained multi-tenant traffic:

* **Admission control.**  Depth is bounded (``max_depth``); past the
  bound, the ``reject`` policy refuses new work and the ``shed_oldest``
  policy fails the oldest queued item instead — either way the refused
  electron sees :class:`QueueFullError`, which ``resilience.classify_error``
  reads as PERMANENT (label ``admission_shed``): a full queue is a
  capacity decision, and burning gang retries on it would amplify the
  overload that caused it.
* **Weighted fairness.**  Dequeue order is deficit round-robin keyed on
  the electron's tenant tag (``task_metadata["tenant"]``, threaded from
  electron metadata by the workflow runner): each tenant earns
  ``quantum × weight`` service credit per round, so a tenant flooding the
  queue gets proportionally more throughput, never the light tenant's
  starvation (DRR's O(1) fairness — Shreedhar & Varghese, SIGCOMM '95).

The queue is event-loop-agnostic and synchronous (the scheduler's pump
drives it from the dispatcher loop); ``clock`` is injectable so fairness
and aging are testable on a fake clock.
"""

from __future__ import annotations

import collections
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..obs.metrics import REGISTRY

QUEUE_DEPTH = REGISTRY.gauge(
    "covalent_tpu_queue_depth",
    "Electrons waiting in the fleet work queue",
    ("tenant",),
)

#: Tenant applied when neither the electron metadata nor the facade set one.
DEFAULT_TENANT = "default"


class QueueFullError(RuntimeError):
    """Admission refused: the fleet queue is at its depth bound.

    Deliberately NOT a ``TransportError``: shedding is a *capacity*
    verdict, and the resilience layer must classify it permanent (no gang
    retries, no local fallback re-run loops).  The ``fault_label`` /
    ``fault_transient`` attributes are the duck-typed classification hook
    ``resilience.classify_error`` honors without importing this module.
    """

    fault_label = "admission_shed"
    fault_transient = False


@dataclass
class WorkItem:
    """One queued electron: payload + tenant + the future its caller awaits."""

    fn: Callable
    args: tuple
    kwargs: dict
    task_metadata: dict
    tenant: str = DEFAULT_TENANT
    future: Any = None  # asyncio.Future set by the scheduler
    enqueued_at: float = 0.0
    seq: int = field(default_factory=itertools.count().__next__)

    @property
    def operation_id(self) -> str:
        dispatch_id = self.task_metadata.get("dispatch_id", "dispatch")
        node_id = self.task_metadata.get("node_id", 0)
        return f"{dispatch_id}_{node_id}"


class _TenantLane:
    __slots__ = ("items", "deficit")

    def __init__(self) -> None:
        self.items: collections.deque[WorkItem] = collections.deque()
        self.deficit = 0.0


class FairWorkQueue:
    """Bounded multi-tenant queue with deficit-round-robin dequeue.

    ``weights`` maps tenant -> relative service share (default 1.0; must
    be > 0).  ``max_depth`` bounds TOTAL queued items across tenants
    (0 = unbounded); ``policy`` decides what happens at the bound:
    ``"reject"`` raises :class:`QueueFullError` at :meth:`put`,
    ``"shed_oldest"`` fails the oldest queued item's future with one and
    admits the newcomer (freshness wins under overload).
    """

    def __init__(
        self,
        max_depth: int = 0,
        policy: str = "reject",
        weights: dict[str, float] | None = None,
        quantum: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        depth_gauge: Any = None,
    ) -> None:
        if policy not in ("reject", "shed_oldest"):
            raise ValueError(
                f'policy must be "reject" or "shed_oldest", got {policy!r}'
            )
        #: which gauge this queue's per-tenant depth moves.  The fleet
        #: scheduler queue (the default) owns covalent_tpu_queue_depth;
        #: other DRR reusers (the serving replica router) MUST pass their
        #: own series — two queues writing one gauge would overwrite and
        #: even delete each other's tenant depths.
        self._depth_gauge = depth_gauge if depth_gauge is not None else (
            QUEUE_DEPTH
        )
        self.max_depth = max(0, int(max_depth))
        self.policy = policy
        if quantum <= 0:
            # A non-positive quantum earns no lane any credit: pop() would
            # rotate the active ring forever and hang the scheduler pump.
            raise ValueError(f"quantum must be > 0, got {quantum}")
        self.quantum = float(quantum)
        self._clock = clock
        self._weights: dict[str, float] = {}
        for tenant, weight in (weights or {}).items():
            self.set_weight(tenant, weight)
        self._lanes: dict[str, _TenantLane] = {}
        #: round-robin order over tenants with backlog (rotated by pop).
        self._active: collections.deque[str] = collections.deque()
        self._depth = 0

    # -- configuration ------------------------------------------------------

    def set_weight(self, tenant: str, weight: float) -> None:
        weight = float(weight)
        if weight <= 0:
            raise ValueError(f"tenant {tenant!r} weight must be > 0, got {weight}")
        self._weights[tenant] = weight

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    # -- introspection ------------------------------------------------------

    def __len__(self) -> int:
        return self._depth

    @property
    def depth(self) -> int:
        return self._depth

    def backlog(self) -> dict[str, int]:
        """tenant -> queued item count (non-empty lanes only).

        Read from the ops HTTP thread while the pump mutates: ``list()``
        snapshots the dict in one C-level step (atomic under the GIL), so
        a concurrent insert can never raise mid-iteration here.
        """
        return {
            tenant: len(lane.items)
            for tenant, lane in list(self._lanes.items())
            if lane.items
        }

    def oldest_age(self) -> float:
        """Seconds the oldest queued item has waited (0 when empty).

        Same cross-thread read contract as :meth:`backlog`; a lane
        drained between the snapshot and the head read just skips.
        """
        oldest = None
        for lane in list(self._lanes.values()):
            try:
                head = lane.items[0].enqueued_at
            except IndexError:
                continue
            oldest = head if oldest is None else min(oldest, head)
        return 0.0 if oldest is None else max(0.0, self._clock() - oldest)

    def _drop_lane(self, tenant: str) -> None:
        """Retire a drained tenant lane AND its gauge series: tenant
        strings are user-derived and unbounded, so empty lanes must not
        accumulate for the process lifetime."""
        self._lanes.pop(tenant, None)
        self._depth_gauge.remove(tenant=tenant)
        try:
            self._active.remove(tenant)
        except ValueError:
            pass

    # -- admission ----------------------------------------------------------

    def put(self, item: WorkItem) -> list[WorkItem]:
        """Admit one item; returns the items shed to make room (if any).

        Under the ``reject`` policy a full queue raises
        :class:`QueueFullError` instead; the shed list lets the caller
        fail the victims' futures and count the decisions.
        """
        shed: list[WorkItem] = []
        if self.max_depth and self._depth >= self.max_depth:
            if self.policy == "reject":
                raise QueueFullError(
                    f"fleet queue at depth bound ({self._depth}/"
                    f"{self.max_depth}); electron {item.operation_id} "
                    f"(tenant {item.tenant!r}) rejected"
                )
            victim = self._shed_oldest()
            if victim is None:
                raise QueueFullError(
                    f"fleet queue at depth bound ({self._depth}/"
                    f"{self.max_depth}) with nothing sheddable"
                )
            shed.append(victim)
        if not item.enqueued_at:
            # First admission stamps the wait clock; a defensive requeue
            # (scheduler pop that could not place) keeps the original
            # stamp so queue_wait_s / oldest_age never under-report.
            item.enqueued_at = self._clock()
        lane = self._lanes.get(item.tenant)
        if lane is None:
            lane = self._lanes[item.tenant] = _TenantLane()
        if not lane.items:
            self._active.append(item.tenant)
        lane.items.append(item)
        self._depth += 1
        self._depth_gauge.labels(tenant=item.tenant).set(len(lane.items))
        return shed

    def _shed_oldest(self) -> WorkItem | None:
        """Remove and return the globally oldest queued item."""
        oldest_tenant: str | None = None
        oldest_seq = None
        for tenant, lane in self._lanes.items():
            if not lane.items:
                continue
            head = lane.items[0].seq
            if oldest_seq is None or head < oldest_seq:
                oldest_seq = head
                oldest_tenant = tenant
        if oldest_tenant is None:
            return None
        lane = self._lanes[oldest_tenant]
        victim = lane.items.popleft()
        self._depth -= 1
        self._depth_gauge.labels(tenant=oldest_tenant).set(len(lane.items))
        if not lane.items:
            self._drop_lane(oldest_tenant)
        return victim

    # -- dequeue (deficit round-robin) --------------------------------------

    def pop(self) -> WorkItem | None:
        """The next item under weighted fairness, or None when empty.

        Classic unit-cost DRR: the tenant at the head of the active ring
        spends a credit if it has one, otherwise earns
        ``quantum × weight`` and yields the head to the next tenant.  A
        heavy tenant therefore drains at most ``weight``-proportional
        rate — it cannot starve a light one, whose lane is visited every
        round regardless of the heavy lane's depth.
        """
        while self._active:
            tenant = self._active[0]
            lane = self._lanes.get(tenant)
            if lane is None or not lane.items:
                # Lane drained by a shed: drop it from the ring.
                self._active.popleft()
                continue
            if lane.deficit < 1.0:
                lane.deficit += self.quantum * self.weight(tenant)
                self._active.rotate(-1)
                continue
            lane.deficit -= 1.0
            item = lane.items.popleft()
            self._depth -= 1
            self._depth_gauge.labels(tenant=tenant).set(len(lane.items))
            if not lane.items:
                # An emptied lane retires whole (deficit included — DRR
                # never banks credit across idle periods) so tenant churn
                # cannot grow the lane map or the gauge without bound.
                self._active.popleft()
                self._drop_lane(tenant)
            return item
        return None

    def remove(self, predicate: Callable[[WorkItem], bool]) -> list[WorkItem]:
        """Remove (and return) every queued item matching ``predicate`` —
        the cancellation path for electrons that never got placed."""
        removed: list[WorkItem] = []
        for tenant, lane in list(self._lanes.items()):
            kept = collections.deque()
            for item in lane.items:
                if predicate(item):
                    removed.append(item)
                else:
                    kept.append(item)
            if len(kept) != len(lane.items):
                lane.items = kept
                self._depth_gauge.labels(tenant=tenant).set(len(kept))
                if not kept:
                    self._drop_lane(tenant)
        self._depth -= len(removed)
        return removed

    def drain(self) -> list[WorkItem]:
        """Remove and return everything queued (scheduler shutdown)."""
        return self.remove(lambda _item: True)
