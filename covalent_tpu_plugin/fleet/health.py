"""Continuous health scoring: the fleet's gray-failure sense organ.

Crash-stop failures are easy — a dead channel raises, the breaker opens,
the scheduler routes around it.  Production TPU fleets fail *gray*: a
degraded chip, a lossy NIC, a throttled disk.  The worker still answers,
still heartbeats, still completes ops — just 10x slower — and a binary
breaker never fires while one browned-out replica drags the whole set's
p99.  This module gives every worker/replica a *continuous* health score
in ``[0, 1]`` fed passively from signals the repo already emits:

* **differential latency** — EWMA op latency vs the peer-group median
  (a straggler is slow *relative to its gang*, not in absolute terms);
* **heartbeat jitter** — inter-arrival coefficient of variation (a
  wedging worker beats erratically before it stops beating);
* **fault attribution** — transient faults from
  ``resilience.classify_error`` decay the score, successes heal it;
* **queue drain** — serving queue depth that grows while peers drain.

Scores drive a four-state machine generalizing the binary breaker
(which stays as the crash-stop fast path)::

    HEALTHY ──score<degraded──▶ PROBATION ──sustained──▶ DEGRADED
       ▲                            │                        │
       │ score recovers             │ score<quarantine       │ score<quarantine
       │                            ▼                        ▼
    PROBATION ◀──canary ok── PROBING ◀──cooldown──── QUARANTINED
                                  │
                                  └──canary fail──▶ QUARANTINED (longer)

``DEGRADED`` targets are deprioritized (placed/routed last);
``QUARANTINED`` ones receive no traffic at all and are readmitted only
through a single-flight cheap canary probe (:meth:`HealthMonitor.allow_probe`
/ :meth:`HealthMonitor.record_probe`).  Crash recovery deliberately does
NOT persist scores: re-adopted sessions and re-dialed workers restart
:meth:`neutral` so a rebooted fleet never inherits a stale quarantine.

Knobs (env, all optional)::

    COVALENT_TPU_HEALTH=off            disable scoring entirely
    COVALENT_TPU_HEALTH_DEGRADED=0.6   score below which -> degraded
    COVALENT_TPU_HEALTH_QUARANTINE=0.3 score below which -> quarantined
    COVALENT_TPU_HEALTH_RECOVER=0.75   score above which -> healthy
    COVALENT_TPU_HEALTH_MIN_SAMPLES=5  latency samples before judging
    COVALENT_TPU_HEALTH_COOLDOWN_S=5   quarantine dwell before probing
    COVALENT_TPU_HEALTH_ALPHA=0.3      EWMA smoothing factor
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

from ..obs import events as obs_events
from ..obs.metrics import REGISTRY
from ..utils.log import app_log

__all__ = [
    "HEALTH",
    "HealthMonitor",
    "HEALTHY",
    "PROBATION",
    "DEGRADED",
    "QUARANTINED",
    "PROBING",
]

# -- states (ordered by severity; the gauge encodes the index) --------------

HEALTHY = "healthy"
PROBATION = "probation"
DEGRADED = "degraded"
QUARANTINED = "quarantined"
PROBING = "probing"

_STATES = (HEALTHY, PROBATION, DEGRADED, QUARANTINED, PROBING)

HEALTH_SCORE = REGISTRY.gauge(
    "covalent_tpu_health_score",
    "Continuous health score per fleet target (1.0 = perfectly healthy)",
    ("target",),
)
HEALTH_STATE = REGISTRY.gauge(
    "covalent_tpu_health_state",
    "Health state per target (0=healthy 1=probation 2=degraded "
    "3=quarantined 4=probing)",
    ("target",),
)
HEALTH_TRANSITIONS_TOTAL = REGISTRY.counter(
    "covalent_tpu_health_transitions_total",
    "Health state-machine transitions, by destination state",
    ("to",),
)
STRAGGLERS_TOTAL = REGISTRY.counter(
    "covalent_tpu_stragglers_total",
    "Gang members flagged as differential stragglers",
    ("worker",),
)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class _Record:
    """Mutable per-target signal accumulators (guarded by monitor lock)."""

    __slots__ = (
        "group", "lat_ewma", "lat_samples", "hb_last", "hb_mean", "hb_var",
        "hb_samples", "fault_score", "queue_ewma", "queue_trend", "state",
        "state_since", "quarantined_at", "quarantine_round", "probe_open",
        "last_transition_reason",
    )

    def __init__(self, group: str = "") -> None:
        self.group = group
        self.lat_ewma = 0.0
        self.lat_samples = 0
        self.hb_last = 0.0
        self.hb_mean = 0.0       # EWMA of inter-arrival gaps
        self.hb_var = 0.0        # EWMA of squared deviation
        self.hb_samples = 0
        self.fault_score = 1.0   # 1.0 = no recent faults, decays toward 0
        self.queue_ewma = 0.0
        self.queue_trend = 0.0   # positive = depth growing
        self.state = HEALTHY
        self.state_since = 0.0
        self.quarantined_at = 0.0
        self.quarantine_round = 0
        self.probe_open = False
        self.last_transition_reason = ""


class HealthMonitor:
    """Process-wide continuous health scoring over opaque target keys.

    Targets are strings — a replica session id, a worker address, a pool
    name — the monitor does not care.  ``group`` ties peers together for
    differential (vs-median) scoring; targets without a group are scored
    on absolute signals only.  Thread-safe; ``clock`` is injectable for
    deterministic unit tests.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._records: dict[str, _Record] = {}
        self.alpha = _env_float("COVALENT_TPU_HEALTH_ALPHA", 0.3)
        self.degraded_below = _env_float("COVALENT_TPU_HEALTH_DEGRADED", 0.6)
        self.quarantine_below = _env_float(
            "COVALENT_TPU_HEALTH_QUARANTINE", 0.3
        )
        self.recover_above = _env_float("COVALENT_TPU_HEALTH_RECOVER", 0.75)
        self.min_samples = int(
            _env_float("COVALENT_TPU_HEALTH_MIN_SAMPLES", 5)
        )
        self.cooldown_s = _env_float("COVALENT_TPU_HEALTH_COOLDOWN_S", 5.0)

    @property
    def enabled(self) -> bool:
        return os.environ.get("COVALENT_TPU_HEALTH", "").lower() not in (
            "off", "0", "false", "disabled",
        )

    # -- signal feeds ------------------------------------------------------

    def _rec(self, key: str, group: str = "") -> _Record:
        rec = self._records.get(key)
        if rec is None:
            rec = _Record(group)
            rec.state_since = self._clock()
            self._records[key] = rec
        if group and not rec.group:
            rec.group = group
        return rec

    def record_latency(self, key: str, seconds: float, group: str = "") -> None:
        """One completed-op latency sample (TTFT, rpc round trip, ...)."""
        if seconds < 0:
            return
        with self._lock:
            rec = self._rec(key, group)
            if rec.lat_samples == 0:
                rec.lat_ewma = seconds
            else:
                rec.lat_ewma += self.alpha * (seconds - rec.lat_ewma)
            rec.lat_samples += 1
        self._judge(key)

    def record_heartbeat(self, key: str, group: str = "") -> None:
        """A fresh heartbeat arrived; tracks inter-arrival jitter."""
        now = self._clock()
        with self._lock:
            rec = self._rec(key, group)
            if rec.hb_last > 0:
                gap = now - rec.hb_last
                if rec.hb_samples == 0:
                    rec.hb_mean = gap
                else:
                    dev = gap - rec.hb_mean
                    rec.hb_mean += self.alpha * dev
                    rec.hb_var += self.alpha * (dev * dev - rec.hb_var)
                rec.hb_samples += 1
            rec.hb_last = now

    def record_fault(self, key: str, label: str = "", group: str = "") -> None:
        """A fault attributed to this target (classify_error transients)."""
        with self._lock:
            rec = self._rec(key, group)
            rec.fault_score = max(0.0, rec.fault_score - 0.34)
        self._judge(key, reason=f"fault:{label}" if label else "fault")

    def record_success(self, key: str, group: str = "") -> None:
        """A clean completion; heals fault decay."""
        with self._lock:
            rec = self._rec(key, group)
            rec.fault_score = min(1.0, rec.fault_score + 0.1)
        self._judge(key)

    def record_queue_depth(self, key: str, depth: float, group: str = "") -> None:
        """Serving queue depth sample; a growing queue while peers drain
        is the drain-rate brownout signal."""
        with self._lock:
            rec = self._rec(key, group)
            prev = rec.queue_ewma
            rec.queue_ewma += self.alpha * (depth - rec.queue_ewma)
            rec.queue_trend += self.alpha * (
                (rec.queue_ewma - prev) - rec.queue_trend
            )
        self._judge(key)

    # -- scoring -----------------------------------------------------------

    def _group_median_latency(self, group: str, exclude: str) -> float:
        """Median peer EWMA latency (lock held by caller)."""
        peers = sorted(
            rec.lat_ewma
            for key, rec in self._records.items()
            if rec.group == group and key != exclude and rec.lat_samples > 0
        )
        if not peers:
            return 0.0
        mid = len(peers) // 2
        if len(peers) % 2:
            return peers[mid]
        return 0.5 * (peers[mid - 1] + peers[mid])

    def _score_locked(self, key: str) -> float:
        rec = self._records.get(key)
        if rec is None:
            return 1.0
        # Differential latency: ratio of this target's EWMA to its peer
        # median.  1x -> 1.0, 2x -> ~0.5, 4x -> ~0.25.  Absolute latency
        # is meaningless across heterogeneous pools; *relative* is the
        # straggler signal.
        lat_score = 1.0
        if rec.lat_samples >= self.min_samples:
            median = (
                self._group_median_latency(rec.group, key)
                if rec.group else 0.0
            )
            if median > 0 and rec.lat_ewma > median:
                lat_score = min(1.0, median / rec.lat_ewma)
        # Heartbeat jitter: coefficient of variation of inter-arrival
        # gaps.  A steady beat (cv ~ 0) scores 1.0; cv >= 1 (gaps as
        # erratic as their mean) scores 0.
        jitter_score = 1.0
        if rec.hb_samples >= self.min_samples and rec.hb_mean > 0:
            cv = (max(0.0, rec.hb_var) ** 0.5) / rec.hb_mean
            jitter_score = max(0.0, 1.0 - min(1.0, cv))
        # Queue drain: depth growing against the trend line reads as a
        # brownout even before latency moves.
        queue_score = 1.0
        if rec.queue_trend > 0.5:
            queue_score = max(0.0, 1.0 - min(1.0, rec.queue_trend / 4.0))
        return (
            0.45 * lat_score
            + 0.15 * jitter_score
            + 0.30 * rec.fault_score
            + 0.10 * queue_score
        )

    def score(self, key: str) -> float:
        with self._lock:
            return round(self._score_locked(key), 4)

    def state(self, key: str) -> str:
        with self._lock:
            rec = self._records.get(key)
            return rec.state if rec is not None else HEALTHY

    def rank(self, key: str) -> int:
        """Placement rank term: 0 healthy, 1 probation, 2 degraded/probing,
        3 quarantined — lower sorts earlier.  PROBING stays down at the
        degraded tier: a canary in flight is not a verdict, and full
        traffic must not land on a still-suspect target during the probe
        window (readmission to PROBATION is what restores priority)."""
        st = self.state(key)
        if st == HEALTHY:
            return 0
        if st == PROBATION:
            return 1
        if st in (DEGRADED, PROBING):
            return 2
        return 3

    def quarantined(self, key: str) -> bool:
        return self.state(key) == QUARANTINED

    def degraded(self, key: str) -> bool:
        return self.state(key) in (DEGRADED, PROBING, QUARANTINED)

    # -- state machine -----------------------------------------------------

    def _transition(self, key: str, rec: _Record, to: str, reason: str) -> None:
        """Lock held by caller; publishes outside is fine (metrics are
        themselves locked)."""
        if rec.state == to:
            return
        frm = rec.state
        rec.state = to
        rec.state_since = self._clock()
        rec.last_transition_reason = reason
        if to == QUARANTINED:
            rec.quarantined_at = self._clock()
            rec.quarantine_round += 1
            rec.probe_open = False
        HEALTH_TRANSITIONS_TOTAL.labels(to=to).inc()
        HEALTH_STATE.labels(target=key).set(_STATES.index(to))
        obs_events.emit(
            "health.transition", target=key, to=to,
            frm=frm, reason=reason, score=round(self._score_locked(key), 4),
        )
        app_log.info(
            "health: %s %s -> %s (%s)", key, frm, to, reason
        )

    def _judge(self, key: str, reason: str = "") -> None:
        """Re-evaluate the state machine after a signal lands."""
        if not self.enabled:
            return
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            score = self._score_locked(key)
            HEALTH_SCORE.labels(target=key).set(round(score, 4))
            st = rec.state
            if st in (QUARANTINED, PROBING):
                # Readmission only through the canary probe path.
                return
            why = reason or f"score={score:.3f}"
            if score < self.quarantine_below:
                self._transition(key, rec, QUARANTINED, why)
            elif score < self.degraded_below:
                if st == HEALTHY:
                    self._transition(key, rec, PROBATION, why)
                elif st == PROBATION:
                    # Sustained low score graduates probation to degraded.
                    if self._clock() - rec.state_since >= self.cooldown_s / 2:
                        self._transition(key, rec, DEGRADED, why)
            elif score >= self.recover_above and st in (PROBATION, DEGRADED):
                self._transition(key, rec, HEALTHY, why)

    # -- canary readmission ------------------------------------------------

    def allow_probe(self, key: str) -> bool:
        """True exactly once per cooldown window for a quarantined target:
        the caller should run a cheap canary op and report via
        :meth:`record_probe`.  Single-flight: a second caller in the same
        window gets False."""
        with self._lock:
            rec = self._records.get(key)
            if rec is None or rec.state != QUARANTINED or rec.probe_open:
                return False
            # Exponential back-off on repeated quarantine rounds.
            dwell = self.cooldown_s * min(8, 2 ** max(0, rec.quarantine_round - 1))
            if self._clock() - rec.quarantined_at < dwell:
                return False
            rec.probe_open = True
            self._transition(key, rec, PROBING, "cooldown elapsed")
            return True

    def record_probe(self, key: str, ok: bool) -> None:
        """Canary verdict: ok readmits to probation (NOT straight to
        healthy — it must re-earn its score), failure re-quarantines with
        a longer cooldown."""
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            rec.probe_open = False
            if ok:
                # Reset the signals that put it there; it starts clean but
                # watched.
                rec.fault_score = 1.0
                rec.lat_ewma = 0.0
                rec.lat_samples = 0
                rec.queue_ewma = 0.0
                rec.queue_trend = 0.0
                self._transition(key, rec, PROBATION, "canary ok")
            else:
                self._transition(key, rec, QUARANTINED, "canary failed")

    def release_probe(self, key: str) -> None:
        """Release a probe slot WITHOUT a verdict — the canary never ran
        (e.g. no event loop on a sync status path).  The target returns
        to QUARANTINED with its prior dwell clock and quarantine round
        intact: an un-run probe must neither readmit the target nor
        lengthen its back-off the way a genuinely failed canary would."""
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                return
            rec.probe_open = False
            if rec.state != PROBING:
                return
            # _transition to QUARANTINED stamps a fresh quarantined_at and
            # bumps the round; restore both — no probe ran, nothing was
            # learned.
            at, rnd = rec.quarantined_at, rec.quarantine_round
            self._transition(key, rec, QUARANTINED, "probe released unrun")
            rec.quarantined_at = at
            rec.quarantine_round = rnd

    # -- lifecycle ---------------------------------------------------------

    def neutral(self, key: str, group: str = "") -> None:
        """Reset a target to a neutral (healthy, zero-signal) record —
        crash recovery calls this for re-adopted sessions and re-dialed
        workers so a restarted control plane never inherits a stale
        quarantine (the journal deliberately does not persist scores)."""
        with self._lock:
            old = self._records.get(key)
            rec = _Record(group or (old.group if old else ""))
            rec.state_since = self._clock()
            self._records[key] = rec
        HEALTH_SCORE.labels(target=key).set(1.0)
        HEALTH_STATE.labels(target=key).set(0)

    def drop(self, key: str) -> None:
        """Forget a target and reap its metric series (replica closed,
        worker released) — stale series must not haunt /metrics."""
        with self._lock:
            self._records.pop(key, None)
        try:
            HEALTH_SCORE.remove(target=key)
            HEALTH_STATE.remove(target=key)
        except Exception:  # noqa: BLE001 - series may never have published
            pass

    def flag_straggler(self, worker: str, differential: float, **detail: Any) -> None:
        """A gang member ran slower than its peers by more than the
        budget: event + metric + a fault mark on its health record."""
        STRAGGLERS_TOTAL.labels(worker=worker).inc()
        obs_events.emit(
            "fleet.straggler", worker=worker,
            differential=round(differential, 3), **detail,
        )
        self.record_fault(worker, label="straggler")

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """target -> {score, state, ...} for /status and tests."""
        with self._lock:
            return {
                key: {
                    "score": round(self._score_locked(key), 4),
                    "state": rec.state,
                    "group": rec.group,
                    "lat_ewma_s": round(rec.lat_ewma, 6),
                    "lat_samples": rec.lat_samples,
                    "hb_jitter_cv": round(
                        (max(0.0, rec.hb_var) ** 0.5) / rec.hb_mean, 4
                    ) if rec.hb_mean > 0 else 0.0,
                    "fault_score": round(rec.fault_score, 4),
                    "queue_ewma": round(rec.queue_ewma, 3),
                    "reason": rec.last_transition_reason,
                }
                for key, rec in self._records.items()
            }

    def reset(self) -> None:
        """Drop every record (tests)."""
        with self._lock:
            keys = list(self._records)
            self._records.clear()
        for key in keys:
            try:
                HEALTH_SCORE.remove(target=key)
                HEALTH_STATE.remove(target=key)
            except Exception:  # noqa: BLE001
                pass


#: Process-wide monitor every fleet/serving signal feeds.
HEALTH = HealthMonitor()
