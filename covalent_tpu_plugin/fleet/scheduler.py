"""The fleet placement engine: queue pump, bin-packing, breaker-aware routing.

One :class:`FleetScheduler` owns a :class:`~covalent_tpu_plugin.fleet.pools.
PoolRegistry` and a :class:`~covalent_tpu_plugin.fleet.queue.FairWorkQueue`
and runs a single pump task on the dispatcher event loop.  Each cycle it
pops the fairest queued electron (deficit round-robin over tenants) and
**bin-packs** it onto a pool: up to ``capacity`` electrons share one warm
gang, so the gang's dial + pre-flight cost amortises across the whole
backlog instead of being paid 1:1 per electron.

Placement preference, in order: the electron's pinned pool (metadata
``pool`` — a preference, not a constraint), accelerator pools over the
CPU fallback, **warm** gangs over cold, then most free slots.  Pools with
an OPEN circuit breaker on any worker are routed around entirely — the
decision is counted ``rerouted`` — rather than burning the dial + retry
envelope against a quarantined host; once every placeable pool is open,
the pump idles on a short tick so cooldown-driven HALF_OPEN promotion
re-admits pools without new traffic.

Autoscale rides the queue depth: crossing the high watermark fires
``on_high`` (edge-triggered), draining back to the low watermark fires
``on_low``.  The default hook is a no-op; :class:`LocalPoolAutoscaler`
resizes a named pool's capacity — the shape a cloud implementation
(spin up a TPU slice, register the pool) plugs into.
"""

from __future__ import annotations

import asyncio
import itertools
import time
import weakref
from typing import Any, Callable

from ..obs import events as obs_events
from ..obs.metrics import REGISTRY
from ..obs.trace import context_of, current_span, record_span
from ..obs.opsserver import (
    ensure_ops_server,
    register_status_provider,
    unregister_status_provider,
)
from ..utils.log import app_log
from . import journal
from .pools import Pool, PoolRegistry
from .queue import DEFAULT_TENANT, FairWorkQueue, QueueFullError, WorkItem

SCHED_DECISIONS_TOTAL = REGISTRY.counter(
    "covalent_tpu_sched_decisions_total",
    "Fleet scheduler decisions by outcome",
    ("outcome",),
)

#: pump idle tick while backlog exists but no pool is placeable (waits out
#: breaker cooldowns without new traffic); releases wake it sooner.
_BLOCKED_TICK_S = 0.25


class AutoscaleHook:
    """Watermark callbacks; the default implementation is a no-op.

    ``on_high(depth, registry)`` fires once when the queue depth crosses
    the high watermark (edge-triggered; re-arms after draining below the
    low watermark); ``on_low(depth, registry)`` fires once on the way
    back down.  Implementations spin pool capacity up/down — resize a
    local pool, provision a TPU slice and ``registry.register`` it,
    whatever the deployment can do.
    """

    def on_high(self, depth: int, registry: PoolRegistry) -> None:
        """Queue pressure: add capacity if you can."""

    def on_low(self, depth: int, registry: PoolRegistry) -> None:
        """Pressure released: shed surplus capacity."""


class LocalPoolAutoscaler(AutoscaleHook):
    """Resize one named pool's slot count between min/max capacity.

    The test/local implementation of the autoscale contract: scale-up
    adds ``step`` slots (bounded by ``max_capacity``), scale-down removes
    them (never below ``min_capacity``).  In-flight electrons are never
    interrupted — capacity only bounds NEW placements.

    ``cooldown_s`` is the anti-thrash dwell: after any resize, further
    resizes are suppressed (counted in ``suppressed``) until the dwell
    elapses.  Without it, a queue oscillating around the watermarks can
    resize capacity back and forth on consecutive pump ticks — each
    flap re-publishing slot gauges and (for a cloud implementation)
    churning real capacity.  ``clock`` is injectable for tests.
    """

    def __init__(
        self,
        pool_name: str,
        step: int = 1,
        max_capacity: int = 8,
        min_capacity: int = 1,
        cooldown_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.pool_name = pool_name
        self.step = max(1, int(step))
        self.max_capacity = int(max_capacity)
        self.min_capacity = max(1, int(min_capacity))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._clock = clock
        self._last_resize: float | None = None
        self.scale_ups = 0
        self.scale_downs = 0
        #: watermark firings ignored because the dwell had not elapsed.
        self.suppressed = 0

    def _in_cooldown(self) -> bool:
        if self._last_resize is None or self.cooldown_s <= 0:
            return False
        if self._clock() - self._last_resize < self.cooldown_s:
            self.suppressed += 1
            return True
        return False

    def on_high(self, depth: int, registry: PoolRegistry) -> None:
        pool = registry.get(self.pool_name)
        if pool is None or pool.capacity >= self.max_capacity:
            return
        if self._in_cooldown():
            return
        pool.capacity = min(self.max_capacity, pool.capacity + self.step)
        self.scale_ups += 1
        self._last_resize = self._clock()
        obs_events.emit(
            "fleet.scale_up",
            pool=self.pool_name,
            capacity=pool.capacity,
            queue_depth=depth,
        )

    def on_low(self, depth: int, registry: PoolRegistry) -> None:
        pool = registry.get(self.pool_name)
        if pool is None or pool.capacity <= self.min_capacity:
            return
        if self._in_cooldown():
            return
        pool.capacity = max(self.min_capacity, pool.capacity - self.step)
        self.scale_downs += 1
        self._last_resize = self._clock()
        obs_events.emit(
            "fleet.scale_down",
            pool=self.pool_name,
            capacity=pool.capacity,
            queue_depth=depth,
        )


class FleetScheduler:
    """Fair queue + bin-packed, breaker-aware placement over a pool registry.

    ``high_watermark``/``low_watermark`` of 0 pick defaults at check time
    (high = 2× total pool capacity, min 4; low = 0 — "drained").
    ``clock`` is injectable for deterministic tests.
    """

    _ids = itertools.count()

    def __init__(
        self,
        registry: PoolRegistry,
        queue: FairWorkQueue | None = None,
        autoscale: AutoscaleHook | None = None,
        high_watermark: int = 0,
        low_watermark: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.registry = registry
        # NOT `queue or ...`: an empty FairWorkQueue is falsy (__len__).
        # The default queue shares this scheduler's clock so queue_wait_s
        # and oldest_age never mix two time sources under a fake clock.
        self.queue = (
            queue if queue is not None else FairWorkQueue(clock=clock)
        )
        self.autoscale = autoscale or AutoscaleHook()
        self.high_watermark = max(0, int(high_watermark))
        self.low_watermark = max(0, int(low_watermark))
        self._clock = clock
        self._above_high = False
        self._closing = False
        self._loop: asyncio.AbstractEventLoop | None = None
        self._wake: asyncio.Event | None = None
        self._pump_task: asyncio.Task | None = None
        #: operation_id -> (pool, item, runner task) for in-flight electrons.
        self._running: dict[str, tuple[Pool, WorkItem, asyncio.Task]] = {}
        #: operation_id -> pool that ran it (attempts_of delegation);
        #: bounded FIFO so direct-API users can't grow it unread.
        self._ran: dict[str, Pool] = {}
        self.decisions: dict[str, int] = {
            "queued": 0, "placed": 0, "shed": 0, "rerouted": 0,
        }

        # Ops plane: the scheduler's live view under /status "fleet"
        # (weakref provider, same pruning contract as the executor's).
        ensure_ops_server()
        self._ops_name = f"fleet:{next(self._ids)}"
        ops_name = self._ops_name
        self_ref = weakref.ref(
            self, lambda _ref: unregister_status_provider(ops_name)
        )

        def _ops_provider():
            scheduler = self_ref()
            return scheduler.status() if scheduler is not None else None

        register_status_provider(ops_name, _ops_provider)

    # -- submission ---------------------------------------------------------

    async def run(
        self,
        function: Callable,
        args: tuple,
        kwargs: dict,
        task_metadata: dict,
    ) -> Any:
        """Queue one electron and await its result.

        The executor-compatible entry point: admission control may raise
        :class:`QueueFullError` immediately (classified permanent); an
        admitted electron resolves with whatever the placed pool's
        executor ``run`` returns or raises.
        """
        if self._closing:
            raise RuntimeError("fleet scheduler is closed")
        loop = asyncio.get_running_loop()
        self._ensure_pump(loop)
        item = WorkItem(
            fn=function,
            args=tuple(args or ()),
            kwargs=dict(kwargs or {}),
            task_metadata=dict(task_metadata or {}),
            tenant=str(
                (task_metadata or {}).get("tenant") or DEFAULT_TENANT
            ),
            future=loop.create_future(),
        )
        # Capture the submitter's trace context at enqueue: placement
        # happens later on the pump task, where the ambient contextvar
        # is the pump's, not the caller's — without the carrier the
        # queue-wait span would land in the wrong trace.
        ambient = current_span()
        if ambient is not None:
            item.task_metadata.setdefault("trace", context_of(ambient))
        try:
            shed = self.queue.put(item)
        except QueueFullError:
            self._count("shed")
            obs_events.emit(
                "fleet.shed",
                operation_id=item.operation_id,
                tenant=item.tenant,
                depth=self.queue.depth,
                policy=self.queue.policy,
            )
            raise
        for victim in shed:
            self._count("shed")
            obs_events.emit(
                "fleet.shed",
                operation_id=victim.operation_id,
                tenant=victim.tenant,
                depth=self.queue.depth,
                policy=self.queue.policy,
            )
            if victim.future is not None and not victim.future.done():
                victim.future.set_exception(
                    QueueFullError(
                        f"electron {victim.operation_id} (tenant "
                        f"{victim.tenant!r}) shed: queue at depth bound "
                        f"({self.queue.max_depth})"
                    )
                )
        self._count("queued")
        obs_events.emit(
            "fleet.queued",
            operation_id=item.operation_id,
            tenant=item.tenant,
            depth=self.queue.depth,
        )
        self._check_high_watermark()
        self._wake.set()
        try:
            return await item.future
        except asyncio.CancelledError:
            # The caller gave up (wait_for timeout, task cancel): don't
            # leave the electron running detached on a capacity slot —
            # unqueue it, or tear the placed attempt down through the
            # owning executor's cancel (remote process groups included).
            # Detached task: the caller's cancellation must not be
            # blocked on the remote kill round trips.
            cleanup = loop.create_task(self.cancel(item.operation_id))
            cleanup.add_done_callback(
                lambda t: None if t.cancelled() else t.exception()
            )
            raise

    # -- pump ---------------------------------------------------------------

    def _ensure_pump(self, loop: asyncio.AbstractEventLoop) -> None:
        if (
            self._pump_task is not None
            and not self._pump_task.done()
            and self._loop is loop
        ):
            return
        if self._loop is not None and self._loop is not loop:
            if not self._loop.is_closed() and self._loop.is_running():
                raise RuntimeError(
                    "FleetScheduler is bound to a different running event "
                    "loop; one scheduler serves one dispatcher loop"
                )
            dropped = self.queue.drain()
            if dropped:
                # Their futures belong to the dead loop — unresolvable.
                app_log.warning(
                    "fleet scheduler moved event loops; dropping %d queued "
                    "electron(s) from the previous loop", len(dropped),
                )
            # In-flight entries died with the old loop without running
            # _run_item's finally: give their slots back, or the leaked
            # capacity eventually deadlocks placement.
            for pool, _item, _task in self._running.values():
                pool.release()
            self._running.clear()
        self._loop = loop
        self._wake = asyncio.Event()
        self._pump_task = loop.create_task(self._pump())

    async def _pump(self) -> None:
        """The one placement loop: pop fairly, place greedily, park politely."""
        while not self._closing:
            if self.queue.depth == 0:
                await self._wake.wait()
                self._wake.clear()
                continue
            placed = self._place_next()
            if placed:
                continue
            # Backlog exists but nothing is placeable (all pools full or
            # breaker-open/health-quarantined): sleep a short tick so
            # breaker cooldowns can promote OPEN -> HALF_OPEN and canary
            # probes can readmit quarantined workers; a slot release
            # wakes us sooner.
            for pool in self.registry.pools():
                probes = getattr(pool, "schedule_health_probes", None)
                if probes is not None:
                    probes()
            try:
                await asyncio.wait_for(self._wake.wait(), _BLOCKED_TICK_S)
            except asyncio.TimeoutError:
                pass
            else:
                self._wake.clear()

    def _has_placeable(self) -> bool:
        """Whether ANY pool could take an electron right now (cheap: no
        ranking) — the guard that keeps DRR pops slot-backed."""
        return any(
            pool.free_slots > 0
            and not pool.breaker_open
            and not pool.health_quarantined
            for pool in self.registry.pools()
        )

    def _place_next(self) -> bool:
        """Place the fairest queued electron; False when nothing placeable."""
        if not self._has_placeable():
            return False
        item = self.queue.pop()
        if item is None:
            return False
        if item.future is not None and item.future.done():
            # Cancelled while queued (cancel() races the pump): skip it.
            return True
        pool, rerouted = self._select_pool(item)
        if pool is None:
            # Unreachable without an await between the placeable check
            # and selection; requeue defensively rather than lose the
            # electron (put preserves its original enqueue stamp).
            self.queue.put(item)
            return False
        outcome = "rerouted" if rerouted else "placed"
        self._count(outcome)
        queue_wait_s = max(0.0, self._clock() - item.enqueued_at)
        journal.record(
            "task", op=item.operation_id, pool=pool.name,
            tenant=item.tenant, rerouted=rerouted,
        )
        obs_events.emit(
            "fleet.placed",
            operation_id=item.operation_id,
            tenant=item.tenant,
            pool=pool.name,
            rerouted=rerouted,
            queue_wait_s=round(queue_wait_s, 4),
            depth=self.queue.depth,
        )
        carrier = item.task_metadata.get("trace")
        if isinstance(carrier, dict) and carrier.get("trace_id"):
            record_span(
                "fleet.queue_wait",
                trace_id=str(carrier["trace_id"]),
                parent_id=(
                    str(carrier["span_id"])
                    if carrier.get("span_id") else None
                ),
                start_ts=time.time() - queue_wait_s,
                duration_s=queue_wait_s,
                attributes={
                    "operation_id": item.operation_id,
                    "pool": pool.name,
                    "segment": "queue_wait",
                },
            )
        pool.place()
        task = self._loop.create_task(self._run_item(pool, item))
        self._running[item.operation_id] = (pool, item, task)
        return True

    def _select_pool(
        self, item: WorkItem | None
    ) -> tuple[Pool | None, bool]:
        """``(chosen pool, rerouted?)`` for one electron (None = wait).

        Preference: pinned pool first, accelerator pools before the
        fallback, warm gangs before cold, then **function-digest
        affinity** — a pool whose resident runtimes already registered
        the electron's function (RPC dispatch) invokes by digest with
        zero staging round trips, so affinity beats the bin-pack
        most-free tiebreak — then most free slots.  ``rerouted`` is True
        when a pool with free slots was skipped because a worker breaker
        is OPEN — placement routed around the quarantine instead of
        dialing into it.
        """
        available = [
            pool for pool in self.registry.pools() if pool.free_slots > 0
        ]
        if not available:
            return None, False
        preferred = (
            item.task_metadata.get("pool") if item is not None else None
        )
        # Digest affinity is only worth computing when some pool actually
        # holds registrations: cloudpickling the function (potentially
        # megabytes of closed-over state) runs synchronously on this
        # loop, and with launch-mode-only traffic no pool ever holds any.
        digest = ""
        if item is not None and any(
            pool.rpc_digest_count() for pool in available
        ):
            digest = self._fn_digest_of(item)
        # Spot-capacity hint: stable pools win for electrons that did not
        # opt into preemptible placement (``spot_ok`` metadata) — spot
        # pools carry checkpoint-tolerant work, SLO-critical work pins to
        # stable capacity.  The preference is SYMMETRIC: a ``spot_ok``
        # electron is actively PUSHED onto spot pools (batch traffic
        # belongs on cheap capacity, keeping stable slots free for the
        # SLO-critical serving the autoscale controller pins there), not
        # merely allowed on them.  Subordinate to the accelerator-over-
        # fallback preference: a spot TPU still beats the CPU fallback.
        spot_ok = bool(
            item is not None and item.task_metadata.get("spot_ok")
        )
        # Serving-artifact affinity, the fn-digest rank's adapter analog:
        # an electron (or replica placement) that declares the CAS
        # digests of the adapter bundles it will attach prefers pools
        # whose gangs already staged them — a LoRA fine-tune promoting
        # into the live fleet re-attaches with zero staging round trips
        # on a holding gang.  Neutral (same rank everywhere) when the
        # item declares none.
        adapter_digests: tuple = ()
        if item is not None:
            adapter_digests = tuple(
                str(d)
                for d in (item.task_metadata.get("adapter_digests") or ())
                if d
            )

        def rank(pool: Pool):
            return (
                0 if pool.name == preferred else 1,
                1 if pool.fallback else 0,
                0 if pool.preemptible == spot_ok else 1,
                0 if pool.warm else 1,
                0 if pool.holds_fn_digest(digest) else 1,
                0 if not adapter_digests or any(
                    pool.holds_serve_digest(d) for d in adapter_digests
                ) else 1,
                # Gray-failure grade: a degraded (but not quarantined)
                # pool still places, just after every healthier
                # alternative — below affinity (a warm digest-holding
                # gang beats a pristine cold one), above the bin-pack
                # most-free tiebreak.
                pool.health_rank(),
                -pool.free_slots,
                pool.name,
            )

        ranked = sorted(available, key=rank)
        placeable = [
            pool for pool in ranked
            if not pool.breaker_open and not pool.health_quarantined
        ]
        for pool in ranked:
            # A quarantined pool skipped while healthy peers absorb the
            # traffic still needs its readmission canary — allow_probe's
            # single-flight dwell gate keeps this a no-op almost always.
            if pool not in placeable and pool.health_quarantined:
                probes = getattr(pool, "schedule_health_probes", None)
                if probes is not None:
                    probes()
        if not placeable:
            return None, False
        # Rerouted means the quarantine CHANGED the decision: the pool we
        # picked is not the one ranking would have picked — an open pool
        # ranked below the winner diverted nothing and counts as placed.
        rerouted = placeable[0] is not ranked[0]
        return placeable[0], rerouted

    @staticmethod
    def _fn_digest_of(item: WorkItem) -> str:
        """The electron's function digest, computed once per item.

        The same ``cloudpickle.dumps(fn)`` sha256 the RPC dispatch path
        registers under, so affinity matches what a gang actually holds.
        Unpicklable callables rank with no affinity rather than failing
        placement; the digest is cached on the item because ranking runs
        once per placement attempt, not once per electron.
        """
        cached = getattr(item, "_fn_digest", None)
        if cached is not None:
            return cached
        try:
            import cloudpickle

            from ..cache import bytes_digest

            digest = bytes_digest(cloudpickle.dumps(item.fn))
        except Exception:  # noqa: BLE001 - arbitrary user callables
            digest = ""
        item._fn_digest = digest  # type: ignore[attr-defined]
        return digest

    async def _run_item(self, pool: Pool, item: WorkItem) -> None:
        operation_id = item.operation_id
        try:
            result = await pool.executor.run(
                item.fn, item.args, item.kwargs, item.task_metadata
            )
        except asyncio.CancelledError:
            if item.future is not None and not item.future.done():
                item.future.cancel()
            raise
        except BaseException as err:  # noqa: BLE001 - relayed to the caller
            if item.future is not None and not item.future.done():
                item.future.set_exception(err)
        else:
            if item.future is not None and not item.future.done():
                item.future.set_result(result)
        finally:
            pool.release()
            self._running.pop(operation_id, None)
            if len(self._ran) > 1024:  # unread (direct API use)
                self._ran.pop(next(iter(self._ran)))
            self._ran[operation_id] = pool
            if self._wake is not None:
                self._wake.set()
            self._check_low_watermark()

    # -- watermarks ---------------------------------------------------------

    def _high_mark(self) -> int:
        if self.high_watermark > 0:
            return self.high_watermark
        return max(4, 2 * self.registry.total_capacity())

    def _check_high_watermark(self) -> None:
        depth = self.queue.depth
        if not self._above_high and depth >= self._high_mark():
            self._above_high = True
            obs_events.emit(
                "fleet.watermark_high",
                depth=depth,
                high_watermark=self._high_mark(),
            )
            try:
                self.autoscale.on_high(depth, self.registry)
            except Exception as err:  # noqa: BLE001 - hooks are advisory
                app_log.warning("autoscale on_high failed: %s", err)

    def _check_low_watermark(self) -> None:
        depth = self.queue.depth
        if self._above_high and depth <= self.low_watermark:
            self._above_high = False
            obs_events.emit(
                "fleet.watermark_low",
                depth=depth,
                low_watermark=self.low_watermark,
            )
            try:
                self.autoscale.on_low(depth, self.registry)
            except Exception as err:  # noqa: BLE001 - hooks are advisory
                app_log.warning("autoscale on_low failed: %s", err)

    # -- executor-compatible surface ---------------------------------------

    async def prewarm(self) -> bool:
        """Best-effort warm-up of every non-fallback pool's gang."""
        pools = [p for p in self.registry.pools() if not p.fallback]
        if not pools:
            return False
        results = await asyncio.gather(
            *(pool.prewarm() for pool in pools), return_exceptions=True
        )
        return any(r is True for r in results)

    async def cancel(self, operation_id: str | None = None) -> None:
        """Cancel queued (never-placed) and in-flight electrons.

        Queued items resolve their futures cancelled without ever
        touching a pool; placed items delegate to the owning executor's
        ``cancel`` so remote process groups die too.
        """

        def matches(item: WorkItem) -> bool:
            return operation_id is None or item.operation_id == operation_id

        for item in self.queue.remove(matches):
            if item.future is not None and not item.future.done():
                item.future.cancel()
            obs_events.emit(
                "fleet.cancelled_queued",
                operation_id=item.operation_id,
                tenant=item.tenant,
            )
        for op, (pool, _item, _task) in list(self._running.items()):
            if operation_id is not None and op != operation_id:
                continue
            canceller = getattr(pool.executor, "cancel", None)
            if canceller is not None:
                try:
                    await canceller(op)
                except Exception as err:  # noqa: BLE001 - best-effort kill
                    app_log.warning(
                        "fleet cancel %s on pool %s: %s", op, pool.name, err
                    )

    def attempts_of(self, operation_id: str) -> int:
        """Delegate per-operation attempt counts to the pool that ran it."""
        pool = self._ran.pop(operation_id, None)
        if pool is None or not pool.started:
            return 1
        getter = getattr(pool.executor, "attempts_of", None)
        return getter(operation_id) if getter is not None else 1

    async def close(self) -> None:
        """Stop the pump, fail queued work, close every pool executor."""
        self._closing = True
        unregister_status_provider(self._ops_name)
        for item in self.queue.drain():
            if item.future is not None and not item.future.done():
                item.future.cancel()
        if self._pump_task is not None:
            self._pump_task.cancel()
            try:
                await self._pump_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._pump_task = None
        running = [task for _pool, _item, task in self._running.values()]
        if running:
            await asyncio.gather(*running, return_exceptions=True)
        await self.registry.close()

    # -- observability ------------------------------------------------------

    def _count(self, outcome: str) -> None:
        SCHED_DECISIONS_TOTAL.labels(outcome=outcome).inc()
        self.decisions[outcome] = self.decisions.get(outcome, 0) + 1

    def status(self) -> dict[str, Any]:
        """The ``fleet`` section of the ops ``/status`` payload."""
        return {
            "queue": {
                "depth": self.queue.depth,
                "max_depth": self.queue.max_depth,
                "policy": self.queue.policy,
                "oldest_age_s": round(self.queue.oldest_age(), 3),
                "tenants": self.queue.backlog(),
            },
            "pools": {
                pool.name: pool.status() for pool in self.registry.pools()
            },
            # list() snapshots in one C-level (GIL-atomic) step: this is
            # read from the ops HTTP thread while the pump mutates.
            "running": sorted(list(self._running)),
            "decisions": dict(self.decisions),
            "autoscale": {
                "high_watermark": self._high_mark(),
                "low_watermark": self.low_watermark,
                "above_high": self._above_high,
                "hook": type(self.autoscale).__name__,
            },
        }
