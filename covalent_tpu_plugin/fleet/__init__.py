"""Fleet scheduler tier: pool registry, fair work queue, placement engine.

The executor layer (``tpu.py``) dispatches ONE electron onto ONE gang as
fast and as safely as the transport allows; this package is the tier above
it that operates a *fleet* under sustained traffic (ROADMAP item 1; the
Podracer architectures — Anakin/Sebulba, arXiv:2104.06272 — are the
blueprint: centralized queues feeding pools of TPU workers, with placement
decoupled from execution):

* :mod:`fleet.lease` — the :class:`GangLease` seam splitting the
  executor's run-attempt state machine from gang *ownership*
  (acquire / pre-flight / discard), so a scheduler — not the executor —
  can own placement.
* :mod:`fleet.pools` — named executor pools (slice shape, capacity, a
  CPU/local fallback), registrable from config/env
  (``COVALENT_TPU_POOLS``) or from ``discovery.py`` endpoints.
* :mod:`fleet.queue` — bounded admission-controlled work queue with
  per-tenant weighted fairness (deficit round-robin).
* :mod:`fleet.scheduler` — bin-packed placement of queued electrons onto
  *warm* gangs, breaker-aware rerouting, autoscale watermark hooks.
* :mod:`fleet.executor` — the :class:`FleetExecutor` facade keeping the
  ``@ct.electron(executor=...)`` surface: electrons submitted through it
  ride the queue instead of mapping 1:1 onto gangs.
* :mod:`fleet.autoscale` — the closed sensor→actuator loop: the
  :class:`AutoscaleController` turns history-ring trends and SLO burn
  alerts into predictive pool-capacity and replica-count targets, with
  hysteresis, cooldowns, scale-to-zero, and stable-pool pinning.
"""

from .autoscale import AutoscaleController, PoolPolicy, ReplicaSetPolicy
from .executor import FleetExecutor, default_scheduler, reset_default_scheduler
from .health import HEALTH, HealthMonitor
from .lease import GangLease
from .pools import Pool, PoolRegistry, PoolSpec, parse_pool_specs
from .queue import FairWorkQueue, QueueFullError, WorkItem
from .scheduler import AutoscaleHook, FleetScheduler, LocalPoolAutoscaler

__all__ = [
    "AutoscaleController",
    "AutoscaleHook",
    "FairWorkQueue",
    "FleetExecutor",
    "FleetScheduler",
    "GangLease",
    "HEALTH",
    "HealthMonitor",
    "LocalPoolAutoscaler",
    "Pool",
    "PoolPolicy",
    "PoolRegistry",
    "PoolSpec",
    "QueueFullError",
    "ReplicaSetPolicy",
    "WorkItem",
    "default_scheduler",
    "parse_pool_specs",
    "reset_default_scheduler",
]
