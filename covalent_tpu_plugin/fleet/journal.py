"""Control-plane write-ahead journal: crash-safe intent log + replayable state.

Every other robustness arc hardened the *data* plane (retries/breakers,
checkpoint-resume, warm handoff, autoscale revive); this module is the
durable backbone for the *control* plane.  The dispatcher appends one
record per control-plane intent — electron placement and terminal
outcome, session open/close, per-stream token high-water marks, pool
registry and autoscaler targets, the dispatcher epoch itself — and a
restarted dispatcher replays the log into a :class:`JournalState` it can
re-adopt the still-warm fleet from (``fleet/recovery.py``).

Design points, in the order a crash meets them:

* **Framing** — each record is ``>I`` payload length + raw 32-byte
  sha256 of the payload + compact-JSON payload.  The digest makes a
  bit-flip detectable (the record is *skipped*, replay continues on the
  intact length prefix); the length prefix makes a torn tail detectable
  (replay *truncates* at the last whole record and the next append
  resumes there).  Replay NEVER raises on corrupt input — counters
  record what was dropped.
* **Fsync batching** — appends land in the OS page cache immediately
  (``flush``) and a background flusher fsyncs every
  ``COVALENT_TPU_JOURNAL_FSYNC_MS`` (default 20ms), so the hot path
  pays a buffered write, not a disk round-trip.  Records that gate
  correctness (epoch bumps, terminal outcomes) pass ``sync=True`` and
  take the fsync inline.
* **Rotation + compaction** — segments roll at
  ``COVALENT_TPU_JOURNAL_SEGMENT_BYTES``; rotation writes a
  ``snapshot.<seq>.json`` of the *replayed state so far* (its own
  sha256 embedded), and only after that snapshot is fsynced are the
  segments it covers deleted.  Replay = newest valid snapshot + the
  tail segments after it; a corrupt snapshot falls back to the previous
  one (or a full-log replay) rather than failing.
* **Epoch fencing** — :meth:`Journal.open` replays, bumps the
  dispatcher epoch, and appends the new epoch synchronously before
  returning.  Workers record the highest epoch they have seen and
  refuse mutating commands from lower ones — the split-brain guard a
  sharded control plane (ROADMAP item 3) will need.

The module-level singleton (:func:`configure` / :func:`record`) keeps
call sites one-liners that compile to a no-op when
``COVALENT_TPU_JOURNAL_DIR`` is unset — journaling is strictly opt-in.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import struct
import threading
import time
from typing import Any

from ..obs.metrics import REGISTRY
from ..utils.log import app_log

__all__ = [
    "Journal",
    "JournalState",
    "configure",
    "get_journal",
    "record",
    "reset",
]

_LEN = struct.Struct(">I")
_DIGEST_BYTES = 32
_HEADER_BYTES = _LEN.size + _DIGEST_BYTES
#: Hard per-record payload ceiling.  A torn/bit-flipped length prefix can
#: decode to anything up to 4GiB; bounding it keeps replay from trying to
#: slurp garbage lengths and misclassifying the whole tail as one record.
_MAX_RECORD_BYTES = 8 * 1024 * 1024

_SEGMENT_RE = re.compile(r"^journal\.(\d{8})\.wal$")
_SNAPSHOT_RE = re.compile(r"^snapshot\.(\d{8})\.json$")

JOURNAL_RECORDS_TOTAL = REGISTRY.counter(
    "covalent_tpu_journal_records_total",
    "Control-plane journal records appended, by record type",
    ("type",),
)

JOURNAL_BYTES_TOTAL = REGISTRY.counter(
    "covalent_tpu_journal_bytes_total",
    "Bytes appended to the control-plane journal (frames included)",
)

JOURNAL_FSYNCS_TOTAL = REGISTRY.counter(
    "covalent_tpu_journal_fsyncs_total",
    "fsync calls issued by the journal (batched flusher + sync appends)",
)

JOURNAL_REPLAY_TOTAL = REGISTRY.counter(
    "covalent_tpu_journal_replay_total",
    "Replay outcomes per record: applied, skipped_corrupt, truncated_tail",
    ("outcome",),
)

JOURNAL_SEGMENTS = REGISTRY.gauge(
    "covalent_tpu_journal_segments",
    "Live (uncompacted) journal segment files on disk",
)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or not str(raw).strip():
        return default
    try:
        return int(float(str(raw).strip()))
    except (TypeError, ValueError):
        return default


def _frame(payload: bytes) -> bytes:
    return _LEN.pack(len(payload)) + hashlib.sha256(payload).digest() + payload


class JournalState:
    """The replayed control-plane picture: what the dispatcher *intended*.

    A pure reducer over record dicts — no I/O — so the same class serves
    replay, snapshot compaction (a snapshot is just a serialized state),
    and the fuzz tests' equivalence checks.  Every map keys on the
    stable caller-facing id (pool name, handle ``sid``, operation id),
    never per-generation remote ids.
    """

    def __init__(self) -> None:
        self.epoch = 0
        #: pool name -> spec dict (registration intent)
        self.pools: dict[str, dict] = {}
        #: pool name -> autoscaler capacity target
        self.pool_targets: dict[str, int] = {}
        #: replica-set name -> {"replicas": target, "sids": {...}}
        self.replica_sets: dict[str, dict] = {}
        #: handle sid -> session record (address, sid_g, digest, options…)
        self.sessions: dict[str, dict] = {}
        #: (sid, rid) -> stream record with ``hwm`` token high-water mark
        self.streams: dict[tuple[str, str], dict] = {}
        #: operation id -> dispatch/placement record (lineage, spec path)
        self.tasks: dict[str, dict] = {}
        self.applied = 0

    # -- reducer ------------------------------------------------------------

    def apply(self, rec: dict) -> None:
        kind = rec.get("t")
        if kind == "epoch":
            self.epoch = max(self.epoch, int(rec.get("epoch") or 0))
        elif kind == "pool":
            name = str(rec.get("name") or "")
            if name:
                self.pools[name] = dict(rec.get("spec") or {})
        elif kind == "pool_target":
            name = str(rec.get("name") or "")
            if name:
                self.pool_targets[name] = int(rec.get("capacity") or 0)
        elif kind == "replica_set":
            name = str(rec.get("name") or "")
            if name:
                entry = self.replica_sets.setdefault(
                    name, {"replicas": 0, "sids": {}}
                )
                if "replicas" in rec:
                    entry["replicas"] = int(rec.get("replicas") or 0)
        elif kind == "replica":
            name = str(rec.get("set") or "")
            sid = str(rec.get("sid") or "")
            if name and sid:
                entry = self.replica_sets.setdefault(
                    name, {"replicas": 0, "sids": {}}
                )
                if rec.get("state") == "closed":
                    entry["sids"].pop(sid, None)
                else:
                    entry["sids"][sid] = int(rec.get("replica") or 0)
        elif kind == "session":
            sid = str(rec.get("sid") or "")
            if sid:
                entry = self.sessions.setdefault(sid, {})
                entry.update(
                    {k: v for k, v in rec.items() if k not in ("t",)}
                )
        elif kind == "session_adapter":
            sid = str(rec.get("sid") or "")
            name = str(rec.get("adapter") or "")
            if sid and name:
                entry = self.sessions.setdefault(sid, {})
                book = entry.setdefault("adapters", {})
                if rec.get("detached"):
                    book.pop(name, None)
                else:
                    book[name] = {
                        "digest": str(rec.get("digest") or ""),
                        "path": str(rec.get("path") or ""),
                        "content": str(rec.get("content") or ""),
                    }
        elif kind == "session_closed":
            sid = str(rec.get("sid") or "")
            self.sessions.pop(sid, None)
            for key in [k for k in self.streams if k[0] == sid]:
                self.streams.pop(key, None)
        elif kind == "stream":
            sid = str(rec.get("sid") or "")
            rid = str(rec.get("rid") or "")
            if sid and rid:
                entry = self.streams.setdefault((sid, rid), {"hwm": 0})
                entry.update(
                    {k: v for k, v in rec.items() if k not in ("t", "hwm")}
                )
        elif kind == "stream_hwm":
            key = (str(rec.get("sid") or ""), str(rec.get("rid") or ""))
            entry = self.streams.get(key)
            if entry is not None:
                entry["hwm"] = max(
                    int(entry.get("hwm") or 0), int(rec.get("hwm") or 0)
                )
        elif kind == "stream_done":
            self.streams.pop(
                (str(rec.get("sid") or ""), str(rec.get("rid") or "")), None
            )
        elif kind == "task":
            op = str(rec.get("op") or "")
            if op:
                entry = self.tasks.setdefault(op, {})
                entry.update(
                    {k: v for k, v in rec.items() if k not in ("t",)}
                )
        elif kind == "task_terminal":
            self.tasks.pop(str(rec.get("op") or ""), None)
        # Unknown kinds are forward-compat: applied counts them, state
        # ignores them, so an old dispatcher can replay a newer log.
        self.applied += 1

    # -- snapshot (de)serialization -----------------------------------------

    def to_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "pools": self.pools,
            "pool_targets": self.pool_targets,
            "replica_sets": self.replica_sets,
            "sessions": self.sessions,
            "streams": {
                f"{sid}\x00{rid}": entry
                for (sid, rid), entry in self.streams.items()
            },
            "tasks": self.tasks,
        }

    @classmethod
    def from_dict(cls, data: dict) -> "JournalState":
        state = cls()
        state.epoch = int(data.get("epoch") or 0)
        state.pools = {str(k): dict(v) for k, v in (data.get("pools") or {}).items()}
        state.pool_targets = {
            str(k): int(v) for k, v in (data.get("pool_targets") or {}).items()
        }
        state.replica_sets = {
            str(k): dict(v) for k, v in (data.get("replica_sets") or {}).items()
        }
        state.sessions = {
            str(k): dict(v) for k, v in (data.get("sessions") or {}).items()
        }
        for key, entry in (data.get("streams") or {}).items():
            sid, _, rid = str(key).partition("\x00")
            state.streams[(sid, rid)] = dict(entry)
        state.tasks = {str(k): dict(v) for k, v in (data.get("tasks") or {}).items()}
        return state


class Journal:
    """One dispatcher's write-ahead journal over a directory of segments."""

    def __init__(
        self,
        directory: str,
        *,
        fsync_ms: int | None = None,
        max_segment_bytes: int | None = None,
    ) -> None:
        self.directory = directory
        self.fsync_ms = (
            fsync_ms
            if fsync_ms is not None
            else _env_int("COVALENT_TPU_JOURNAL_FSYNC_MS", 20)
        )
        self.max_segment_bytes = (
            max_segment_bytes
            if max_segment_bytes is not None
            else _env_int(
                "COVALENT_TPU_JOURNAL_SEGMENT_BYTES", 4 * 1024 * 1024
            )
        )
        self.state = JournalState()
        #: replayed prior-incarnation state snapshot (set by :meth:`open`).
        self.recovered: dict = {}
        self.replay_applied = 0
        self.replay_skipped = 0
        self.replay_truncated = 0
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._written = 0
        self._dirty = False
        self._closed = False
        self._flusher: threading.Thread | None = None
        self._flush_wake = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    @classmethod
    def open(cls, directory: str, **kwargs: Any) -> "Journal":
        """Replay whatever the directory holds, bump the dispatcher
        epoch durably, and start appending.  The epoch record is the
        first write of the new incarnation and is fsynced before open
        returns — from this instant any surviving worker that hears the
        new epoch must refuse the old dispatcher."""
        journal = cls(directory, **kwargs)
        os.makedirs(directory, exist_ok=True)
        journal._replay()
        # The recovery path reads THIS — the prior incarnation's state as
        # replayed — not the live ``state``, which immediately starts
        # accumulating the new incarnation's records.
        journal.recovered = journal.state.to_dict()
        journal._open_segment(journal._seq + 1)
        journal.state.epoch += 1
        journal.append({"t": "epoch", "epoch": journal.state.epoch}, sync=True)
        journal._start_flusher()
        return journal

    @property
    def epoch(self) -> int:
        return self.state.epoch

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._sync_locked()
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
        self._flush_wake.set()
        if self._flusher is not None:
            self._flusher.join(timeout=1.0)

    # -- append path --------------------------------------------------------

    def append(self, rec: dict, *, sync: bool = False) -> None:
        """Write one record: framed, applied to the live state, and
        either batch-fsynced (default) or fsynced inline (``sync``)."""
        payload = json.dumps(
            rec, separators=(",", ":"), sort_keys=True, default=str
        ).encode("utf-8")
        frame = _frame(payload)
        with self._lock:
            if self._closed or self._fh is None:
                return
            if self._written and self._written + len(frame) > self.max_segment_bytes:
                self._rotate_locked()
            self._fh.write(frame)
            self._fh.flush()
            self._written += len(frame)
            self._dirty = True
            self.state.apply(rec)
            if sync:
                self._sync_locked()
        JOURNAL_RECORDS_TOTAL.labels(type=str(rec.get("t") or "?")).inc()
        JOURNAL_BYTES_TOTAL.inc(len(frame))

    def record(self, kind: str, *, sync: bool = False, **fields: Any) -> None:
        fields["t"] = kind
        self.append(fields, sync=sync)

    def sync(self) -> None:
        with self._lock:
            self._sync_locked()

    def _sync_locked(self) -> None:
        if self._fh is None or not self._dirty:
            return
        try:
            os.fsync(self._fh.fileno())
        except OSError:
            return
        self._dirty = False
        JOURNAL_FSYNCS_TOTAL.inc()

    def _start_flusher(self) -> None:
        if self.fsync_ms <= 0:
            # Every append becomes durable only at sync points/close;
            # callers opted out of the batched flusher explicitly.
            return

        def _run() -> None:
            interval = max(self.fsync_ms, 1) / 1000.0
            while not self._closed:
                self._flush_wake.wait(interval)
                self._flush_wake.clear()
                if self._closed:
                    return
                with self._lock:
                    self._sync_locked()

        self._flusher = threading.Thread(
            target=_run, name="tpu-journal-fsync", daemon=True
        )
        self._flusher.start()

    # -- rotation + compaction ----------------------------------------------

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.directory, f"journal.{seq:08d}.wal")

    def _snapshot_path(self, seq: int) -> str:
        return os.path.join(self.directory, f"snapshot.{seq:08d}.json")

    def _open_segment(self, seq: int) -> None:
        self._seq = seq
        path = self._segment_path(seq)
        self._fh = open(path, "ab")
        self._written = self._fh.tell()
        JOURNAL_SEGMENTS.set(float(len(self._scan()[0])))

    def _rotate_locked(self) -> None:
        """Roll to a fresh segment, snapshot the state so far, and
        delete the segments that snapshot covers.  Ordering is the
        crash-safety contract: snapshot is fully fsynced (tmp + rename)
        BEFORE any segment is unlinked, so every instant in time has a
        complete replay path on disk."""
        closing_seq = self._seq
        self._sync_locked()
        try:
            self._fh.close()
        except OSError:
            pass
        self._open_segment(closing_seq + 1)
        try:
            self._write_snapshot_locked(closing_seq)
        except OSError as err:
            # Snapshot failure is not fatal: replay just walks more
            # segments.  Compaction is skipped so nothing is lost.
            app_log.warning("journal snapshot at seq %d failed: %s",
                            closing_seq, err)
            return
        for seg_seq, seg_path in self._scan()[0]:
            if seg_seq <= closing_seq:
                try:
                    os.unlink(seg_path)
                except OSError:
                    pass
        for snap_seq, snap_path in self._scan()[1]:
            if snap_seq < closing_seq:
                try:
                    os.unlink(snap_path)
                except OSError:
                    pass
        JOURNAL_SEGMENTS.set(float(len(self._scan()[0])))

    def _write_snapshot_locked(self, seq: int) -> None:
        body = json.dumps(
            self.state.to_dict(), separators=(",", ":"), sort_keys=True
        )
        doc = json.dumps(
            {"seq": seq, "sha256": hashlib.sha256(body.encode()).hexdigest(),
             "state": json.loads(body)},
            separators=(",", ":"),
        )
        path = self._snapshot_path(seq)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(doc)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    # -- replay -------------------------------------------------------------

    def _scan(self) -> tuple[list[tuple[int, str]], list[tuple[int, str]]]:
        segments: list[tuple[int, str]] = []
        snapshots: list[tuple[int, str]] = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return [], []
        for name in names:
            m = _SEGMENT_RE.match(name)
            if m:
                segments.append(
                    (int(m.group(1)), os.path.join(self.directory, name))
                )
                continue
            m = _SNAPSHOT_RE.match(name)
            if m:
                snapshots.append(
                    (int(m.group(1)), os.path.join(self.directory, name))
                )
        segments.sort()
        snapshots.sort()
        return segments, snapshots

    def _load_snapshot(self, path: str) -> JournalState | None:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            body = json.dumps(
                doc["state"], separators=(",", ":"), sort_keys=True
            )
            if hashlib.sha256(body.encode()).hexdigest() != doc.get("sha256"):
                raise ValueError("snapshot digest mismatch")
            return JournalState.from_dict(doc["state"])
        except Exception as err:  # noqa: BLE001 - corrupt snapshot: fall back
            app_log.warning("journal snapshot %s unusable (%s); falling back",
                            path, err)
            return None

    def _replay(self) -> None:
        segments, snapshots = self._scan()
        state: JournalState | None = None
        base_seq = 0
        # Newest intact snapshot wins; corrupt ones fall back toward a
        # full-log replay rather than failing recovery outright.
        for snap_seq, snap_path in reversed(snapshots):
            loaded = self._load_snapshot(snap_path)
            if loaded is not None:
                state, base_seq = loaded, snap_seq
                break
        self.state = state if state is not None else JournalState()
        for seq, path in segments:
            if seq <= base_seq:
                continue
            self._replay_segment(path)
            self._seq = max(self._seq, seq)
        if snapshots:
            self._seq = max(self._seq, snapshots[-1][0])
        JOURNAL_SEGMENTS.set(float(len(segments)))

    def _replay_segment(self, path: str) -> None:
        """Replay one segment; truncate at the first torn frame, skip
        (but step past) digest-mismatched records.  Never raises."""
        try:
            fh = open(path, "r+b")
        except OSError:
            return
        with fh:
            data = fh.read()
            offset = 0
            good_end = 0
            while offset < len(data):
                header = data[offset:offset + _HEADER_BYTES]
                if len(header) < _HEADER_BYTES:
                    break  # torn header → truncate here
                (length,) = _LEN.unpack(header[:_LEN.size])
                if length > _MAX_RECORD_BYTES:
                    break  # garbage length → treat as torn tail
                payload = data[
                    offset + _HEADER_BYTES:offset + _HEADER_BYTES + length
                ]
                if len(payload) < length:
                    break  # torn payload → truncate here
                digest = header[_LEN.size:]
                if hashlib.sha256(payload).digest() != digest:
                    # Bit-flip inside an intact frame: the length prefix
                    # still walks us past it, so skip just this record.
                    self.replay_skipped += 1
                    JOURNAL_REPLAY_TOTAL.labels(outcome="skipped_corrupt").inc()
                    offset += _HEADER_BYTES + length
                    good_end = offset
                    continue
                try:
                    rec = json.loads(payload.decode("utf-8"))
                except ValueError:
                    self.replay_skipped += 1
                    JOURNAL_REPLAY_TOTAL.labels(outcome="skipped_corrupt").inc()
                    offset += _HEADER_BYTES + length
                    good_end = offset
                    continue
                self.state.apply(rec)
                self.replay_applied += 1
                JOURNAL_REPLAY_TOTAL.labels(outcome="applied").inc()
                offset += _HEADER_BYTES + length
                good_end = offset
            if good_end < len(data):
                self.replay_truncated += 1
                JOURNAL_REPLAY_TOTAL.labels(outcome="truncated_tail").inc()
                try:
                    fh.truncate(good_end)
                except OSError:
                    pass

    # -- views --------------------------------------------------------------

    def status(self) -> dict[str, Any]:
        segments, snapshots = self._scan()
        return {
            "dir": self.directory,
            "epoch": self.state.epoch,
            "segments": len(segments),
            "snapshots": len(snapshots),
            "replay": {
                "applied": self.replay_applied,
                "skipped_corrupt": self.replay_skipped,
                "truncated_tail": self.replay_truncated,
            },
            "sessions": len(self.state.sessions),
            "streams": len(self.state.streams),
            "tasks": len(self.state.tasks),
            "pools": len(self.state.pools),
        }


# -- module singleton --------------------------------------------------------
#
# Mirrors obs/events.py: one process-wide journal, configured once from the
# environment (or explicitly by the recovery path), with a record() helper
# that is a cheap no-op while unconfigured so the ~15 dispatcher call sites
# stay unconditional one-liners.

_journal: Journal | None = None
_journal_lock = threading.Lock()


def configure(directory: str | None = None, **kwargs: Any) -> Journal | None:
    """Open (or re-open) the process journal.  With no argument, honors
    ``COVALENT_TPU_JOURNAL_DIR``; returns None (journaling off) when
    neither names a directory."""
    global _journal
    directory = directory or os.environ.get("COVALENT_TPU_JOURNAL_DIR") or ""
    with _journal_lock:
        if _journal is not None:
            if _journal.directory == directory:
                return _journal
            _journal.close()
            _journal = None
        if not directory:
            return None
        _journal = Journal.open(directory, **kwargs)
        return _journal


def get_journal(auto_configure: bool = True) -> Journal | None:
    """The process journal, lazily opened from the environment."""
    if _journal is None and auto_configure:
        if os.environ.get("COVALENT_TPU_JOURNAL_DIR"):
            return configure()
        return None
    return _journal


def record(kind: str, *, sync: bool = False, **fields: Any) -> None:
    """Append one control-plane intent; no-op when journaling is off."""
    journal = get_journal()
    if journal is None:
        return
    try:
        journal.record(kind, sync=sync, **fields)
    except Exception:  # noqa: BLE001 - journaling must never break dispatch
        app_log.exception("journal append (%s) failed", kind)


def epoch() -> int:
    """Current dispatcher epoch (0 when journaling is off)."""
    journal = get_journal()
    return journal.epoch if journal is not None else 0


def reset() -> None:
    """Close and forget the process journal (tests)."""
    global _journal
    with _journal_lock:
        if _journal is not None:
            _journal.close()
            _journal = None


def now() -> float:
    """Wall-clock stamp for journal records (monkeypatchable in tests)."""
    return time.time()
