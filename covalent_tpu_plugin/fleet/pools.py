"""Named executor pools: slice shape, capacity, warmth, breaker summary.

A :class:`Pool` wraps ONE executor instance (its pooled transports ARE the
warm gang) plus a capacity — how many electrons may run on that gang
concurrently.  Bin-packing falls out of that shape: the scheduler places
up to ``capacity`` queued electrons onto the same warm executor, so N
electrons pay the gang's dial/pre-flight cost once instead of N times.

Pools come from three places, all landing in one :class:`PoolRegistry`:

* **Declared** — :class:`PoolSpec` built in code, from config
  (``fleet.pools``) or the environment (``COVALENT_TPU_POOLS``); compact
  form ``name=addr1+addr2@capN`` entries separated by ``;``, or a JSON
  list/dict of spec objects.
* **Discovered** — ``discovery.discover_pool_spec()`` resolves a TPU
  name's worker endpoints into a registrable spec, so a fleet stands up
  without hand-listing workers (compact form ``name=tpu:NAME@capN``
  defers discovery to the executor's own ``tpu_name`` path).
* **Fallback** — a CPU/local pool the registry can auto-provide, the
  placement engine's target of last resort when every accelerator pool is
  full or quarantined.
"""

from __future__ import annotations

import asyncio
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable

from ..obs.metrics import REGISTRY
from ..utils.config import get_config
from ..utils.log import app_log
from . import journal
from .health import HEALTH, QUARANTINED

POOL_SLOTS = REGISTRY.gauge(
    "covalent_tpu_pool_slots",
    "Fleet pool slot occupancy by state",
    ("pool", "state"),
)

POOLS_ENV = "COVALENT_TPU_POOLS"

#: capacity applied when a spec (or compact entry) names none.
DEFAULT_CAPACITY = 1
#: capacity of the auto-provided CPU/local fallback pool.
FALLBACK_CAPACITY = 2


@dataclass
class PoolSpec:
    """Declarative description of one executor pool.

    ``workers`` + ``transport`` (or ``tpu_name``/``zone``/``project`` for
    discovery-backed pools) describe the slice; ``capacity`` is the number
    of electrons the pool's warm gang runs concurrently; ``fallback``
    marks the pool placement falls back to when accelerator pools are
    saturated or breaker-quarantined.  ``executor`` carries extra
    ``TPUExecutor`` kwargs verbatim (cache dirs, poll cadence, chaos —
    whatever the deployment needs).
    """

    name: str
    workers: tuple[str, ...] = ()
    tpu_name: str = ""
    zone: str = ""
    project: str = ""
    transport: str = ""
    capacity: int = DEFAULT_CAPACITY
    fallback: bool = False
    #: serving-tier placement hint for disaggregated sets: "prefill"
    #: pools host prefill replicas (compute-heavy batched passes),
    #: "decode" pools pin decode replicas (latency-critical token
    #: loops); "" is role-neutral.  Electrons ignore it entirely.
    role: str = ""
    #: spot/preemptible capacity (compact form: ``!spot``): the scheduler
    #: prefers stable pools for ordinary electrons (an electron opts in
    #: with ``spot_ok`` metadata), and the pool's executor defaults to
    #: checkpoint-heavy dispatch (``checkpoint_interval_s``) so work
    #: placed here survives reclaims by resuming, not recomputing.
    preemptible: bool = False
    executor: dict[str, Any] = field(default_factory=dict)
    #: (external_ip, internal_ip) pairs from registration-time discovery;
    #: seeds the executor's endpoint cache so a discovered pool's first
    #: dispatch skips the duplicate gcloud subprocess.
    endpoints: tuple[tuple[str, str], ...] = ()

    def __post_init__(self) -> None:
        self.workers = tuple(self.workers)
        self.endpoints = tuple(
            (str(external), str(internal))
            for external, internal in self.endpoints
        )
        self.capacity = max(1, int(self.capacity))
        if not self.name:
            raise ValueError("pool spec needs a name")

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "PoolSpec":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        unknown = set(data) - known
        if unknown:
            raise ValueError(
                f"unknown pool spec field(s) {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        return cls(**data)


def _default_executor_factory(spec: PoolSpec) -> Any:
    """Build the pool's executor from its spec (TPUExecutor for every
    kind — ``transport="local"`` IS the CPU fallback shape)."""
    from ..tpu import TPUExecutor  # deferred: tpu.py imports fleet.lease

    kwargs: dict[str, Any] = dict(spec.executor)
    if spec.workers:
        kwargs.setdefault("workers", list(spec.workers))
    if spec.tpu_name:
        kwargs.setdefault("tpu_name", spec.tpu_name)
        if spec.zone:
            kwargs.setdefault("zone", spec.zone)
        if spec.project:
            kwargs.setdefault("project", spec.project)
    if spec.transport:
        kwargs.setdefault("transport", spec.transport)
    elif not (spec.workers or spec.tpu_name or kwargs.get("hostname")):
        # No topology at all: a local pool (the fallback shape).
        kwargs.setdefault("transport", "local")
    if (
        spec.preemptible
        and "checkpoint_interval_s" not in kwargs
        and not os.environ.get("COVALENT_TPU_CHECKPOINT_INTERVAL_S")
    ):
        # Checkpoint-heavy placement: spot capacity WILL be reclaimed, so
        # a preemptible pool's electrons snapshot by default and a reclaim
        # costs one interval of recompute, not the whole run.
        kwargs["checkpoint_interval_s"] = 60.0
    executor = TPUExecutor(**kwargs)
    if spec.endpoints and executor.tpu_name:
        executor.seed_endpoints(spec.endpoints)
    return executor


class Pool:
    """One registered pool: spec + lazily built executor + slot accounting.

    ``executor_factory`` is injectable so tests (and the scheduler's unit
    tier) can vend stub executors; anything with an async
    ``run(fn, args, kwargs, task_metadata)`` works, and warmth/breaker
    views degrade gracefully when the optional surface
    (``is_warm``/``gang_state``/``prewarm``/``close``) is absent.
    """

    def __init__(
        self,
        spec: PoolSpec,
        executor_factory: Callable[[PoolSpec], Any] | None = None,
        executor: Any = None,
    ) -> None:
        self.spec = spec
        self._factory = executor_factory or _default_executor_factory
        self._executor = executor
        self.in_use = 0
        #: electrons ever placed here (per-pool placement breakdown).
        self.placed_total = 0
        if executor is not None:
            self._label_executor(executor)
        self._publish_slots()

    def _label_executor(self, executor: Any) -> None:
        """Stamp the pool name onto the executor so per-pool metrics
        (prewarm cold-start durations) key on this pool."""
        try:
            executor.pool_label = self.name
        except Exception:  # noqa: BLE001 - stub executors may refuse
            pass

    # -- identity -----------------------------------------------------------

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def capacity(self) -> int:
        return self.spec.capacity

    @capacity.setter
    def capacity(self, value: int) -> None:
        """Autoscale hooks resize pools by writing this (min 1)."""
        self.spec.capacity = max(1, int(value))
        journal.record(
            "pool_target", name=self.name, capacity=self.spec.capacity
        )
        self._publish_slots()

    @property
    def fallback(self) -> bool:
        return self.spec.fallback

    @property
    def role(self) -> str:
        return self.spec.role

    @property
    def preemptible(self) -> bool:
        return self.spec.preemptible

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"<Pool {self.name}: {self.in_use}/{self.capacity} in use, "
            f"warm={self.warm}>"
        )

    # -- executor + warmth --------------------------------------------------

    @property
    def started(self) -> bool:
        return self._executor is not None

    @property
    def executor(self) -> Any:
        if self._executor is None:
            self._executor = self._factory(self.spec)
            self._label_executor(self._executor)
        return self._executor

    @property
    def warm(self) -> bool:
        """Whether the pool's gang holds live pre-flighted connections."""
        if self._executor is None:
            return False
        return bool(getattr(self._executor, "is_warm", False))

    def breaker_states(self) -> dict[str, str]:
        """worker address -> circuit state (empty when unavailable)."""
        if self._executor is None:
            return {}
        state_of = getattr(self._executor, "gang_state", None)
        if state_of is None:
            return {}
        try:
            return dict(state_of().get("breakers") or {})
        except Exception:  # noqa: BLE001 - placement must not crash on a view
            return {}

    @property
    def breaker_open(self) -> bool:
        """True when ANY of the pool's workers is breaker-quarantined.

        A gang launch is all-or-nothing, so one open worker makes the
        whole pool unplaceable until its cooldown: placement routes
        around it instead of burning the dial + retry envelope.
        """
        return any(
            state == "open" for state in self.breaker_states().values()
        )

    def _worker_keys(self) -> list[str]:
        """Addresses the health monitor keys this pool's workers by:
        the breaker view's keys when the gang exposes them, else the
        executor's static worker list."""
        keys = list(self.breaker_states().keys())
        if keys:
            return keys
        if self._executor is None:
            return []
        try:
            return [str(w) for w in getattr(self._executor, "workers", [])]
        except Exception:  # noqa: BLE001 - placement must not crash on a view
            return []

    def health_rank(self) -> int:
        """Worst health rank across this pool's workers (0 healthy … 3
        quarantined) — a gang launch is all-or-nothing, so the slowest
        member's gray-failure grade IS the pool's placement grade."""
        ranks = [HEALTH.rank(key) for key in self._worker_keys()]
        return max(ranks) if ranks else 0

    @property
    def health_quarantined(self) -> bool:
        """True when any worker is health-quarantined (gray-failing hard
        enough to drain) — placement routes around the pool exactly as
        it does for an OPEN breaker, but on *degradation* signals a
        binary crash-stop breaker never sees."""
        return self.health_rank() >= 3

    def schedule_health_probes(self) -> None:
        """Fire single-flight canary probes for quarantined workers.

        The scheduler calls this on its blocked tick (the same cadence
        that lets breaker cooldowns promote OPEN -> HALF_OPEN): each
        quarantined worker whose probe dwell has elapsed gets ONE cheap
        executor ping; success readmits it to PROBATION.  Executors
        without a ``health_canary`` probe simply never quarantine-drain
        this way (their workers only feed scores through serving)."""
        if self._executor is None:
            return
        probe = getattr(self._executor, "health_canary", None)
        if probe is None:
            return
        for key in self._worker_keys():
            if HEALTH.state(key) != QUARANTINED or not HEALTH.allow_probe(key):
                continue

            async def _run(worker: str = key) -> None:
                ok = False
                try:
                    ok = bool(await probe(worker))
                finally:
                    HEALTH.record_probe(worker, ok)

            coro = _run()
            try:
                task = asyncio.ensure_future(coro)
            except RuntimeError:
                # No running loop (sync status path): close the unstarted
                # coroutine and release the probe slot — verdict-free, no
                # probe ran — for the next tick.
                coro.close()
                HEALTH.release_probe(key)
                continue
            task.add_done_callback(
                lambda t: None if t.cancelled() else t.exception()
            )

    def holds_fn_digest(self, digest: str) -> bool:
        """Whether this pool's warm gang registered the electron's function
        digest (RPC dispatch) — placement affinity: a holding gang invokes
        by digest with zero staging/registration round trips."""
        if self._executor is None or not digest:
            return False
        probe = getattr(self._executor, "holds_fn_digest", None)
        if probe is None:
            return False
        try:
            return bool(probe(digest))
        except Exception:  # noqa: BLE001 - placement must not crash on a view
            return False

    def holds_serve_digest(self, digest: str) -> bool:
        """Whether this pool's gang already staged a serving factory's
        CAS payload — replica warm-up affinity: placement prefers pools
        that re-open a session of that factory with zero staging."""
        if self._executor is None or not digest:
            return False
        probe = getattr(self._executor, "holds_serve_digest", None)
        if probe is None:
            return False
        try:
            return bool(probe(digest))
        except Exception:  # noqa: BLE001 - placement must not crash on a view
            return False

    def rpc_digest_count(self) -> int:
        """Distinct function digests this pool's resident runtimes hold
        (0 on stub/cold executors) — the scheduler's cheap pre-check that
        affinity ranking could matter at all before it pays a cloudpickle
        of the electron's function."""
        if self._executor is None:
            return 0
        probe = getattr(self._executor, "rpc_digest_count", None)
        if probe is None:
            return 0
        try:
            return int(probe())
        except Exception:  # noqa: BLE001 - placement must not crash on a view
            return 0

    # -- slot accounting ----------------------------------------------------

    @property
    def free_slots(self) -> int:
        return max(0, self.capacity - self.in_use)

    def place(self) -> None:
        self.in_use += 1
        self.placed_total += 1
        self._publish_slots()

    def release(self) -> None:
        self.in_use = max(0, self.in_use - 1)
        self._publish_slots()

    def _publish_slots(self) -> None:
        POOL_SLOTS.labels(pool=self.name, state="in_use").set(self.in_use)
        POOL_SLOTS.labels(pool=self.name, state="free").set(self.free_slots)

    def serve_session_count(self) -> int:
        """Live serving sessions pinned to this pool's gang (0 on cold
        or stub executors) — the autoscale controller's idle probe: a
        pool with sessions is never scale-to-zero eligible."""
        if self._executor is None:
            return 0
        probe = getattr(self._executor, "serve_sessions", None)
        if probe is None:
            return 0
        try:
            return len(probe())
        except Exception:  # noqa: BLE001 - idle probes must not crash
            return 0

    # -- lifecycle ----------------------------------------------------------

    async def prewarm(self) -> bool:
        """Best-effort gang warm-up (breaker-gated inside the executor)."""
        warmer = getattr(self.executor, "prewarm", None)
        if warmer is None:
            return False
        return bool(await warmer())

    async def teardown(self) -> bool:
        """Scale-to-zero actuator: drop this pool's warm gang.

        Refuses while any capacity slot is in use (the executor
        additionally refuses while electrons or serving sessions are
        live); a cold or stub executor has nothing to tear down.  The
        next placement — or a controller-driven :meth:`prewarm` ahead of
        predicted demand — re-dials the gang from cold.
        """
        if self._executor is None or self.in_use > 0:
            return False
        down = getattr(self._executor, "teardown_gang", None)
        if down is None:
            return False
        try:
            return bool(await down())
        except Exception as err:  # noqa: BLE001 - teardown is best-effort
            app_log.warning("pool %s gang teardown failed: %s", self.name, err)
            return False

    async def close(self) -> None:
        if self._executor is None:
            return
        closer = getattr(self._executor, "close", None)
        if closer is not None:
            await closer()

    def status(self) -> dict[str, Any]:
        """This pool's contribution to the ops ``/status`` fleet view."""
        view = {
            "capacity": self.capacity,
            "in_use": self.in_use,
            "free": self.free_slots,
            "warm": self.warm,
            "fallback": self.fallback,
            **({"role": self.role} if self.role else {}),
            **({"preemptible": True} if self.preemptible else {}),
            "placed_total": self.placed_total,
            "workers": list(self.spec.workers)
            or ([self.spec.tpu_name] if self.spec.tpu_name else ["local"]),
            "breakers": self.breaker_states(),
            "health_rank": self.health_rank(),
        }
        if self._executor is not None:
            # RPC dispatch views (absent on stub executors): how many
            # function digests this gang's resident runtimes hold, and
            # which dispatch mode each in-flight electron is riding.
            counter = getattr(self._executor, "rpc_digest_count", None)
            modes = getattr(self._executor, "in_flight_modes", None)
            sessions = getattr(self._executor, "serve_sessions", None)
            try:
                if counter is not None:
                    view["registered_digests"] = int(counter())
                if modes is not None:
                    view["in_flight_modes"] = dict(modes())
                if sessions is not None:
                    # Serving sessions are long-lived capacity: each pins
                    # one slot (already counted in in_use) and reports its
                    # live queue depth and tokens/s here.
                    view["serve_sessions"] = dict(sessions())
            except Exception:  # noqa: BLE001 - status must not crash a view
                pass
        return view

    async def open_session(self, factory: Any, **options: Any):
        """Open a resident serving session pinned to one of this pool's
        capacity slots (released when the handle closes).  Forwards to
        :func:`covalent_tpu_plugin.serving.open_session`."""
        from ..serving import open_session as _open_session

        return await _open_session(self, factory, **options)

    async def capture_profile(
        self, duration_s: float = 2.0, sid: str = ""
    ) -> "dict[str, Any] | None":
        """Capture a resident-runtime profiler trace on this pool's gang.

        Forwards to ``TPUExecutor.capture_profile`` — the fleet-level
        surface for on-demand introspection of a pool carrying live RPC
        or serving traffic.  None when the pool holds no warm resident
        runtime (or its executor type has no profiling support)."""
        if self._executor is None:
            # A never-built executor has no resident runtime to profile;
            # observability probes must not cold-start one (same guard
            # as is_warm/gang_state/holds_fn_digest).
            return None
        capture = getattr(self.executor, "capture_profile", None)
        if capture is None:
            return None
        return await capture(duration_s=duration_s, sid=sid)


def parse_pool_specs(text: str) -> list[PoolSpec]:
    """Parse ``COVALENT_TPU_POOLS`` / ``fleet.pools`` into specs.

    Two forms:

    * JSON — a list of spec objects (or one object), field names matching
      :class:`PoolSpec`: ``[{"name": "v5e", "workers": ["w1", "w2"],
      "capacity": 4}, {"name": "cpu", "fallback": true}]``.
    * Compact — ``;``-separated ``name=target@capN`` entries where
      ``target`` is ``+``-joined worker addresses, ``tpu:NAME`` (deferred
      gcloud discovery), or ``local`` (CPU fallback pool — implies
      ``fallback`` unless other pools also claim it):
      ``v5e=10.0.0.1+10.0.0.2@4;spare=tpu:my-v5e-8@2;cpu=local@2``.
      Addresses may carry a login (``edge=ubuntu@10.0.0.9``): a trailing
      ``@suffix`` is only read as capacity when it is numeric (or
      ``cap``-prefixed, which always claims to be one).  A trailing
      ``!role`` marks the pool's serving role for disaggregated
      placement (``pre=10.0.0.1@2!prefill;dec=10.0.0.2@4!decode``), and
      ``!spot`` (or ``!preemptible``) marks spot capacity — the scheduler
      prefers stable pools unless an electron opts in (``spot_ok``
      metadata), and the pool's executor defaults to checkpoint-heavy
      dispatch so reclaims resume instead of recomputing.  Tags stack:
      ``cheap=10.0.0.3@4!decode!spot``.
    """
    text = (text or "").strip()
    if not text:
        return []
    if text[0] in "[{":
        data = json.loads(text)
        if isinstance(data, dict):
            data = [data]
        return [PoolSpec.from_dict(dict(entry)) for entry in data]
    specs: list[PoolSpec] = []
    for entry in text.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, target = entry.partition("=")
        if not sep or not name.strip() or not target.strip():
            raise ValueError(
                f"bad pool entry {entry!r} (want name=target[@capN])"
            )
        target = target.strip()
        role = ""
        preemptible = False
        # A target may carry several ``!tag`` suffixes (e.g.
        # ``@2!prefill!spot``): "spot"/"preemptible" flag the pool's
        # capacity class, anything else is the serving role.
        while True:
            head_tag, sep_tag, tag_text = target.rpartition("!")
            tag = tag_text.strip()
            if not (sep_tag and tag.isalpha() and head_tag.strip()):
                break
            if tag.lower() in ("spot", "preemptible"):
                preemptible = True
            elif role:
                break  # one serving role only; stop consuming
            else:
                role = tag
            target = head_tag.strip()
        capacity = DEFAULT_CAPACITY
        head, sep, cap_text = target.rpartition("@")
        if sep:
            cap_text = cap_text.strip()
            digits = (
                cap_text[len("cap"):]
                if cap_text.startswith("cap")
                else cap_text
            )
            if digits.isdigit() and head.strip():
                target, capacity = head.strip(), int(digits)
            elif not head.strip() or not cap_text or cap_text.startswith("cap"):
                raise ValueError(
                    f"bad capacity in pool entry {entry!r}"
                )
            # else: the '@' belongs to a user@host worker address —
            # capacity stays default unless an explicit @capN follows.
        spec_kwargs: dict[str, Any] = {
            "name": name.strip(), "capacity": capacity,
        }
        if role:
            spec_kwargs["role"] = role
        if preemptible:
            spec_kwargs["preemptible"] = True
        if target == "local":
            spec_kwargs.update(transport="local", fallback=True)
        elif target.startswith("tpu:"):
            spec_kwargs["tpu_name"] = target[len("tpu:"):]
        else:
            spec_kwargs["workers"] = tuple(
                w.strip() for w in target.split("+") if w.strip()
            )
        specs.append(PoolSpec(**spec_kwargs))
    return specs


class PoolRegistry:
    """Named pools + the fallback, the placement engine's world view."""

    def __init__(
        self,
        executor_factory: Callable[[PoolSpec], Any] | None = None,
    ) -> None:
        self._factory = executor_factory
        self._pools: dict[str, Pool] = {}

    def __len__(self) -> int:
        return len(self._pools)

    def __contains__(self, name: str) -> bool:
        return name in self._pools

    def get(self, name: str) -> Pool | None:
        return self._pools.get(name)

    def pools(self) -> list[Pool]:
        return list(self._pools.values())

    def register(
        self,
        spec: "PoolSpec | dict[str, Any]",
        executor: Any = None,
    ) -> Pool:
        """Register (or replace) one pool; returns the live :class:`Pool`.

        A replaced pool's started executor is closed (its pooled
        transports and resident agents would otherwise leak for the
        process lifetime) — asynchronously when an event loop is running,
        with a logged warning otherwise.
        """
        if isinstance(spec, dict):
            spec = PoolSpec.from_dict(spec)
        displaced = self._pools.get(spec.name)
        pool = Pool(spec, executor_factory=self._factory, executor=executor)
        self._pools[spec.name] = pool
        try:
            from dataclasses import asdict

            journal.record("pool", name=spec.name, spec=asdict(spec))
        except TypeError:
            journal.record("pool", name=spec.name, spec={})
        if displaced is not None and displaced.started:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                app_log.warning(
                    "pool %s replaced outside an event loop; the previous "
                    "executor's connections could not be closed",
                    spec.name,
                )
            else:
                task = loop.create_task(displaced.close())
                task.add_done_callback(
                    lambda t: None if t.cancelled() else t.exception()
                )
        return pool

    def register_tpu(
        self,
        tpu_name: str,
        zone: str = "",
        project: str = "",
        capacity: int = DEFAULT_CAPACITY,
        name: str | None = None,
        prefer_external: bool = True,
        timeout: float = 60.0,
        **spec_kwargs: Any,
    ) -> Pool:
        """Resolve a TPU's workers via ``discovery.py`` and register them.

        The satellite wiring: ``discover_tpu_endpoints()`` results become
        a registrable pool spec, so a fleet stands up from TPU names
        alone.  ``prefer_external``/``timeout`` forward to discovery (a
        dispatcher inside the VPC wants internal IPs); remaining kwargs
        land on the :class:`PoolSpec`.  Discovery failures propagate (a
        pool that silently registered empty would be a placement black
        hole).
        """
        from ..discovery import discover_pool_spec

        data = discover_pool_spec(
            tpu_name, zone=zone, project=project,
            capacity=capacity, name=name,
            prefer_external=prefer_external, timeout=timeout,
        )
        data.update(spec_kwargs)
        return self.register(data)

    def ensure_fallback(
        self, capacity: int = FALLBACK_CAPACITY, **executor_kwargs: Any
    ) -> Pool:
        """The fallback pool, auto-registering a local/CPU one if absent."""
        existing = self.fallback_pool()
        if existing is not None:
            return existing
        return self.register(
            PoolSpec(
                name="local-fallback",
                transport="local",
                capacity=capacity,
                fallback=True,
                executor=dict(executor_kwargs),
            )
        )

    def fallback_pool(self) -> Pool | None:
        for pool in self._pools.values():
            if pool.fallback:
                return pool
        return None

    def total_capacity(self) -> int:
        return sum(pool.capacity for pool in self._pools.values())

    async def close(self) -> None:
        for pool in self._pools.values():
            try:
                await pool.close()
            except Exception as err:  # noqa: BLE001 - best-effort teardown
                app_log.warning("pool %s close failed: %s", pool.name, err)

    @classmethod
    def from_environment(
        cls,
        env_value: str | None = None,
        executor_factory: Callable[[PoolSpec], Any] | None = None,
    ) -> "PoolRegistry":
        """Registry from ``COVALENT_TPU_POOLS`` (or the ``fleet.pools``
        config key when the env var is unset)."""
        import os

        if env_value is None:
            env_value = os.environ.get(POOLS_ENV)
        if env_value is None:
            env_value = str(get_config("fleet.pools", "") or "")
        registry = cls(executor_factory=executor_factory)
        for spec in parse_pool_specs(env_value):
            registry.register(spec)
        return registry
