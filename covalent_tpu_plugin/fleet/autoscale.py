"""Closed-loop predictive autoscaling: the SLO plane drives the fleet.

Every sensor already exists — the history ring's windowed queries and
trend slopes (:mod:`..obs.history`), the SLO engine's multi-window burn
alerts (:mod:`..obs.slo`), the scheduler's live queue depth — and every
actuator exists too: :class:`~.pools.Pool` capacity, ``ReplicaSet.
scale_to`` (including scale-to-zero), gang teardown and ``prewarm()``.
This module is the loop that connects them: one
:class:`AutoscaleController` periodically turns *trends* into per-pool
capacity targets and per-replica-set replica counts, then actuates.

Three properties make the loop production-shaped rather than a
thermostat:

* **Predictive, not edge-triggered.**  Demand is projected a *lead
  time* ahead — ``predicted = now + max(0, slope) × lead`` — where the
  slope comes from ``HISTORY.query(..., agg="trend")`` (queue depth for
  pools, per-replica in-flight for serving sets) and the lead is the
  **measured** cold start: the ``covalent_tpu_prewarm_seconds``
  histogram's per-pool mean, recorded by every real ``prewarm()``.
  Capacity that takes 8 s to warm starts warming when the trend says
  demand is 8 s away, not when the latency SLO is already burning.
* **Flap-free.**  Scale-ups take a short cooldown; scale-downs require
  utilization *sustained* below the release threshold for the full
  down-cooldown AND no relevant SLO burning — a queue oscillating
  around a watermark moves capacity at most once per dwell, asserted
  under a fake clock in the test tier.
* **SLO-driven.**  The controller subscribes to the SLO engine's alert
  hooks: a burning serving SLO forces a replica scale-up on its managed
  SLO-critical sets immediately (and pins their placement to stable,
  non-spot pools via ``prefer_stable``); a burning dispatch/queue SLO
  forces pool capacity up.  Burn state also vetoes every scale-down —
  shedding capacity during an incident is how incidents get worse.

Scale-to-zero rides the same loop: a pool whose gang sits warm with
nothing placed and no serving sessions past ``idle_ttl_s`` is torn down
(``Pool.teardown()``); an idle managed set whose policy allows
``min_replicas=0`` suspends via ``scale_to(0)``.  Both re-warm on
demand — the set transparently on its next request, the pool on its
next placement or the controller's own predictive ``prewarm()`` when
the trend turns positive again.

Environment knobs (all overridable per-controller):

========================================  ====================================
``COVALENT_TPU_AUTOSCALE_INTERVAL_S``     evaluation tick (default 1.0)
``COVALENT_TPU_AUTOSCALE_UP_COOLDOWN_S``  min dwell between scale-ups (3.0)
``COVALENT_TPU_AUTOSCALE_COOLDOWN_S``     sustained-below dwell before any
                                          scale-down (30.0)
``COVALENT_TPU_AUTOSCALE_IDLE_TTL_S``     idle seconds before scale-to-zero
                                          (300.0; 0 disables)
``COVALENT_TPU_AUTOSCALE_LEAD_S``         predictive lead override (0 =
                                          measured from prewarm durations)
``COVALENT_TPU_AUTOSCALE_TREND_WINDOW_S`` trend-fit window (30.0)
========================================  ====================================
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import json
import math
import os
import time
import weakref
from dataclasses import dataclass
from typing import Any, Callable

from ..obs import events as obs_events
from ..obs.history import HISTORY, MetricsHistory
from ..obs.metrics import REGISTRY
from ..obs.opsserver import (
    ensure_ops_server,
    register_status_provider,
    unregister_status_provider,
)
from ..utils.log import app_log
from . import journal
from .pools import Pool, PoolRegistry

__all__ = [
    "AutoscaleController",
    "PoolPolicy",
    "ReplicaSetPolicy",
    "AUTOSCALE_DECISIONS_TOTAL",
]

AUTOSCALE_DECISIONS_TOTAL = REGISTRY.counter(
    "covalent_tpu_autoscale_decisions_total",
    "Autoscale controller actuations by action",
    ("action",),
)

#: Gauge of the controller's most recent capacity target per resource —
#: the dashboard view of "what the loop is steering toward".
AUTOSCALE_TARGET = REGISTRY.gauge(
    "covalent_tpu_autoscale_target",
    "Autoscale controller capacity target per managed resource",
    ("resource",),
)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip().lower()
    if not raw:
        return default
    # Same off-words the sibling knobs accept (obs.history._env_float):
    # COVALENT_TPU_AUTOSCALE_IDLE_TTL_S=off must DISABLE scale-to-zero,
    # not silently fall back to the enabled default.
    if raw in ("0", "off", "false", "no", "none"):
        return 0.0
    try:
        return float(raw)
    except ValueError:
        return default


@dataclass
class PoolPolicy:
    """Scaling bounds for one managed pool."""

    min_capacity: int = 1
    max_capacity: int = 8
    step: int = 1
    #: None rides the controller default; 0 disables scale-to-zero.
    idle_ttl_s: float | None = None

    def __post_init__(self) -> None:
        self.min_capacity = max(1, int(self.min_capacity))
        self.max_capacity = max(self.min_capacity, int(self.max_capacity))
        self.step = max(1, int(self.step))


@dataclass
class ReplicaSetPolicy:
    """Scaling bounds + utilization targets for one managed replica set."""

    min_replicas: int = 1  # 0 allows scale-to-zero suspension
    max_replicas: int = 4
    #: scale up when predicted load exceeds this fraction of the live
    #: decode-slot capacity (the hysteresis high band).
    target_utilization: float = 0.75
    #: scale down only when utilization sits below this fraction for the
    #: whole down-cooldown (the hysteresis low band).
    scale_down_utilization: float = 0.3
    #: SLO-critical: serving burn alerts force scale-ups here and the
    #: set's placement pins to stable (non-spot) pools.
    slo_critical: bool = True
    #: trend/load scale-ups require the desired count to exceed the live
    #: count for this many CONSECUTIVE ticks (1 = act immediately) — a
    #: one-tick in-flight spike is not demand.  Burn-driven scale-ups
    #: bypass the stabilization entirely: an incident does not wait.
    up_stabilization_ticks: int = 1
    idle_ttl_s: float | None = None

    def __post_init__(self) -> None:
        self.min_replicas = max(0, int(self.min_replicas))
        self.max_replicas = max(
            max(1, self.min_replicas), int(self.max_replicas)
        )
        self.up_stabilization_ticks = max(1, int(self.up_stabilization_ticks))
        if not 0.0 < self.target_utilization <= 1.0:
            raise ValueError(
                f"target_utilization must be in (0, 1], got "
                f"{self.target_utilization}"
            )
        if not 0.0 <= self.scale_down_utilization < self.target_utilization:
            raise ValueError(
                "scale_down_utilization must be below target_utilization "
                f"(got {self.scale_down_utilization} vs "
                f"{self.target_utilization})"
            )


class _ResourceState:
    """Per-resource actuation memory: cooldowns, dwell, idle tracking."""

    __slots__ = (
        "last_up", "last_down", "below_since", "idle_since",
        "last_prewarm", "up_pending",
    )

    def __init__(self) -> None:
        self.last_up: float | None = None
        self.last_down: float | None = None
        self.below_since: float | None = None
        self.idle_since: float | None = None
        self.last_prewarm: float | None = None
        #: consecutive ticks the desired count exceeded the live count.
        self.up_pending = 0


class AutoscaleController:
    """The sensor→actuator loop over one fleet's pools and replica sets.

    Construct with the scheduler whose fleet it drives (or a bare
    registry), then :meth:`manage_pool` / :meth:`manage_replica_set` the
    resources it owns and :meth:`start` the tick task.  Tests drive
    :meth:`tick` directly under an injected clock — every decision the
    loop can make is reachable without sleeping.
    """

    _ids = itertools.count()

    def __init__(
        self,
        scheduler: Any = None,
        registry: PoolRegistry | None = None,
        *,
        history: MetricsHistory | None = None,
        slo_engine: Any = None,
        interval_s: float | None = None,
        up_cooldown_s: float | None = None,
        down_cooldown_s: float | None = None,
        idle_ttl_s: float | None = None,
        lead_s: float | None = None,
        trend_window_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.scheduler = scheduler
        self.registry = registry or (
            scheduler.registry if scheduler is not None else None
        )
        self.history = history if history is not None else HISTORY
        self._engine = slo_engine
        self._clock = clock
        self.interval_s = (
            _env_float("COVALENT_TPU_AUTOSCALE_INTERVAL_S", 1.0)
            if interval_s is None else float(interval_s)
        )
        self.up_cooldown_s = (
            _env_float("COVALENT_TPU_AUTOSCALE_UP_COOLDOWN_S", 3.0)
            if up_cooldown_s is None else float(up_cooldown_s)
        )
        self.down_cooldown_s = (
            _env_float("COVALENT_TPU_AUTOSCALE_COOLDOWN_S", 30.0)
            if down_cooldown_s is None else float(down_cooldown_s)
        )
        self.idle_ttl_s = (
            _env_float("COVALENT_TPU_AUTOSCALE_IDLE_TTL_S", 300.0)
            if idle_ttl_s is None else float(idle_ttl_s)
        )
        self.lead_override_s = (
            _env_float("COVALENT_TPU_AUTOSCALE_LEAD_S", 0.0)
            if lead_s is None else float(lead_s)
        )
        self.trend_window_s = (
            _env_float("COVALENT_TPU_AUTOSCALE_TREND_WINDOW_S", 30.0)
            if trend_window_s is None else float(trend_window_s)
        )
        #: lead-time fallback before any prewarm has been measured, and
        #: the bounds the measurement is clamped into.
        self.default_lead_s = 2.0
        self.max_lead_s = 30.0

        self._pools: dict[str, PoolPolicy] = {}
        self._sets: list[tuple[Any, ReplicaSetPolicy]] = []
        self._state: dict[str, _ResourceState] = {}
        #: SLO name -> (state, metric) updated by the alert hook (the
        #: engine evaluates on the history sampler thread) and refreshed
        #: from the engine's last evaluation each tick.
        self._burning: dict[str, str] = {}
        self._decisions: collections.deque = collections.deque(maxlen=64)
        self.decision_counts: dict[str, int] = {}
        self._prewarm_tasks: dict[str, asyncio.Task] = {}
        self._suspended_seen: set[str] = set()
        self._closing = False
        self._task: asyncio.Task | None = None
        self._wake: asyncio.Event | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._hooked_engine: Any = None

        ensure_ops_server()
        self._ops_name = f"autoscale:{next(self._ids)}"
        ops_name = self._ops_name
        self_ref = weakref.ref(
            self, lambda _ref: unregister_status_provider(ops_name)
        )

        def _ops_provider():
            controller = self_ref()
            return controller.status() if controller is not None else None

        register_status_provider(ops_name, _ops_provider)
        self._attach_engine(slo_engine)

    # -- wiring -------------------------------------------------------------

    def _attach_engine(self, engine: Any) -> None:
        """Subscribe the burn hook once an engine exists (lazy: the
        process-wide engine may start after the controller)."""
        if engine is None or engine is self._hooked_engine:
            return
        self._engine = engine
        self._hooked_engine = engine
        engine.add_alert_hook(self._on_slo_alert)

    def _on_slo_alert(self, name: str, state: str, info: dict) -> None:
        """SLO engine alert hook (called from the history sampler
        thread): record the burn and wake the tick loop immediately —
        an incident should not wait out the remainder of an interval."""
        if self._closing:
            return
        if state == "burning":
            self._burning[name] = str(info.get("metric") or "")
        else:
            self._burning.pop(name, None)
        loop, wake = self._loop, self._wake
        if loop is not None and wake is not None and not loop.is_closed():
            try:
                loop.call_soon_threadsafe(wake.set)
            except RuntimeError:
                pass

    def manage_pool(
        self, pool: "Pool | str", **policy: Any
    ) -> PoolPolicy:
        """Put one pool under closed-loop capacity control."""
        name = pool if isinstance(pool, str) else pool.name
        if self.registry is None or self.registry.get(name) is None:
            raise ValueError(f"unknown pool {name!r}")
        pol = PoolPolicy(**policy)
        self._pools[name] = pol
        return pol

    def manage_replica_set(
        self, replica_set: Any, **policy: Any
    ) -> ReplicaSetPolicy:
        """Put one serving replica set under closed-loop replica control.

        ``slo_critical=True`` (the default) additionally pins the set's
        future replica placement to stable pools (``prefer_stable``) —
        SLO-critical serving must not sit on capacity that spot reclaims
        can yank mid-burn.
        """
        pol = ReplicaSetPolicy(**policy)
        self._sets = [
            (rset, p) for rset, p in self._sets if rset is not replica_set
        ] + [(replica_set, pol)]
        if pol.slo_critical:
            try:
                replica_set.prefer_stable = True
            except Exception:  # noqa: BLE001 - duck-typed stubs
                pass
        return pol

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Start the tick loop on the running event loop (idempotent)."""
        loop = asyncio.get_running_loop()
        if self._task is not None and not self._task.done():
            return
        self._loop = loop
        self._wake = asyncio.Event()
        self._task = loop.create_task(self._run())

    async def _run(self) -> None:
        while not self._closing:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as err:  # noqa: BLE001 - loop must survive
                app_log.warning("autoscale tick failed: %s", err)
            try:
                await asyncio.wait_for(
                    self._wake.wait(), self.interval_s
                )
            except asyncio.TimeoutError:
                pass
            else:
                self._wake.clear()

    async def close(self) -> None:
        self._closing = True
        unregister_status_provider(self._ops_name)
        if self._hooked_engine is not None:
            # Detach the alert hook: the bound method strongly
            # references this controller, so a process-wide engine would
            # otherwise keep every closed controller (and its fleet)
            # alive and keep feeding it burn transitions forever.
            remover = getattr(
                self._hooked_engine, "remove_alert_hook", None
            )
            if remover is not None:
                remover(self._on_slo_alert)
            self._hooked_engine = None
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None
        for task in list(self._prewarm_tasks.values()):
            task.cancel()
        self._prewarm_tasks.clear()

    # -- signals ------------------------------------------------------------

    def _refresh_burning(self) -> None:
        """Fold the engine's last evaluation into the hook-fed state (the
        hook only sees *transitions*; a controller attached mid-burn
        must still see it)."""
        if self._engine is None:
            from ..obs import slo as _slo

            self._attach_engine(_slo.get_engine())
        engine = self._engine
        if engine is None:
            return
        try:
            view = engine.status()
        except Exception:  # noqa: BLE001 - observability never fatal
            return
        for name, info in (view.get("slos") or {}).items():
            if info.get("state") == "burning":
                self._burning[name] = str(info.get("metric") or "")
            else:
                self._burning.pop(name, None)

    def _burning_kinds(self) -> tuple[bool, bool]:
        """(serving SLO burning, dispatch/queue SLO burning).

        ``dict()`` snapshots in one C-level (GIL-atomic) step: the SLO
        alert hook mutates ``_burning`` from the history sampler thread,
        and iterating the live dict here would raise "changed size
        during iteration" exactly when a burn transition fires — losing
        the one tick that was supposed to react to it.
        """
        metrics = list(dict(self._burning).values())
        serving = any(
            metric.startswith("covalent_tpu_serve") for metric in metrics
        )
        dispatch = any(
            not metric.startswith("covalent_tpu_serve")
            for metric in metrics
        )
        return serving, dispatch

    def _slope(
        self, metric: str, label_filter: dict[str, str] | None = None
    ) -> float:
        """Summed per-second trend slope across a metric's series."""
        try:
            view = self.history.query(
                metric, window_s=self.trend_window_s, agg="trend"
            )
        except Exception:  # noqa: BLE001 - sensors must not crash the loop
            return 0.0
        total = 0.0
        for key, stats in (view.get("series") or {}).items():
            if label_filter:
                try:
                    labels = json.loads(key) if key else {}
                except ValueError:
                    labels = {}
                if any(
                    str(labels.get(k)) != str(v)
                    for k, v in label_filter.items()
                ):
                    continue
            total += float(stats.get("slope_per_s") or 0.0)
        return total

    def _lead_for(self, pool_name: str = "") -> float:
        """Predictive lead time: the measured cold start for this pool.

        Reads the ``covalent_tpu_prewarm_seconds`` histogram — per-pool
        mean when that pool has measurements, the all-pools mean
        otherwise, the shipped default when nothing was ever measured.
        An explicit override (``COVALENT_TPU_AUTOSCALE_LEAD_S`` /
        ``lead_s=``) wins unconditionally.
        """
        if self.lead_override_s > 0:
            return self.lead_override_s
        hist = REGISTRY.get("covalent_tpu_prewarm_seconds")
        if hist is None:
            return self.default_lead_s
        pool_mean = total_sum = 0.0
        pool_count = total_count = 0
        try:
            for labels, child in hist._series():
                total_sum += child.sum
                total_count += child.count
                if labels.get("pool") == pool_name and child.count:
                    pool_mean = child.sum / child.count
                    pool_count = child.count
        except Exception:  # noqa: BLE001 - metrics views are best-effort
            return self.default_lead_s
        if pool_count:
            measured = pool_mean
        elif total_count:
            measured = total_sum / total_count
        else:
            return self.default_lead_s
        return min(self.max_lead_s, max(self.interval_s, measured))

    def _queue_signals(self) -> tuple[int, float]:
        """(current fleet queue depth, trend slope in items/s)."""
        depth = 0
        if self.scheduler is not None:
            try:
                depth = int(self.scheduler.queue.depth)
            except Exception:  # noqa: BLE001 - duck-typed schedulers
                depth = 0
        return depth, self._slope("covalent_tpu_queue_depth")

    # -- the loop body -------------------------------------------------------

    async def tick(self) -> list[dict[str, Any]]:
        """One sensor→decision→actuation round; returns the decisions."""
        now = self._clock()
        self._refresh_burning()
        serving_burn, dispatch_burn = self._burning_kinds()
        decisions: list[dict[str, Any]] = []
        decisions += await self._tick_pools(now, dispatch_burn)
        decisions += await self._tick_sets(now, serving_burn)
        return decisions

    def _record(
        self, action: str, resource: str, target: int | None,
        reason: str, now: float,
    ) -> dict[str, Any]:
        decision = {
            "action": action,
            "resource": resource,
            **({"target": target} if target is not None else {}),
            "reason": reason,
            "ts": round(now, 3),
        }
        AUTOSCALE_DECISIONS_TOTAL.labels(action=action).inc()
        if target is not None:
            # Durable intent: a restarted dispatcher restores the last
            # journaled target instead of re-deriving it from a history
            # ring that died with the process.
            journal.record("pool_target", name=resource, capacity=target)
        self.decision_counts[action] = (
            self.decision_counts.get(action, 0) + 1
        )
        self._decisions.append(decision)
        obs_events.emit("autoscale.decision", **decision)
        return decision

    def _state_of(self, resource: str) -> _ResourceState:
        state = self._state.get(resource)
        if state is None:
            state = self._state[resource] = _ResourceState()
        return state

    def _up_ready(self, state: _ResourceState, now: float) -> bool:
        return (
            state.last_up is None
            or now - state.last_up >= self.up_cooldown_s
        )

    def _down_ready(
        self, state: _ResourceState, now: float, below: bool
    ) -> bool:
        """Scale-down gate: utilization must sit below the release band
        for the whole down-cooldown (sustained, not instantaneous), and
        the down itself re-arms the dwell."""
        if not below:
            state.below_since = None
            return False
        if state.below_since is None:
            state.below_since = now
        if now - state.below_since < self.down_cooldown_s:
            return False
        return (
            state.last_down is None
            or now - state.last_down >= self.down_cooldown_s
        ) and (
            state.last_up is None
            or now - state.last_up >= self.down_cooldown_s
        )

    # -- pools ---------------------------------------------------------------

    async def _tick_pools(
        self, now: float, dispatch_burn: bool
    ) -> list[dict[str, Any]]:
        decisions: list[dict[str, Any]] = []
        if not self._pools or self.registry is None:
            return decisions
        depth, slope = self._queue_signals()
        managed = [
            (name, self.registry.get(name), pol)
            for name, pol in self._pools.items()
        ]
        managed = [(n, p, pol) for n, p, pol in managed if p is not None]
        if not managed:
            return decisions
        in_use = sum(p.in_use for _n, p, _pol in managed)
        capacity = sum(p.capacity for _n, p, _pol in managed)
        # The predictive demand: everything running plus the backlog the
        # trend says will exist once fresh capacity could be warm.
        lead = max(self._lead_for(n) for n, _p, _pol in managed)
        predicted_backlog = max(0.0, depth + max(0.0, slope) * lead)
        demand = in_use + math.ceil(predicted_backlog)
        if dispatch_burn:
            # A burning dispatch/queue SLO is a demand signal in itself:
            # force at least one step of growth past current capacity.
            demand = max(demand, capacity + 1)
        AUTOSCALE_TARGET.labels(resource="pools").set(demand)

        if demand > capacity:
            # Demand is high: every pool's sustained-below dwell re-arms,
            # even for pools whose up-cooldown blocks action this tick —
            # otherwise an oscillating queue could bank "below" time
            # across spikes and flap a scale-down in between.
            for name, _p, _pol in managed:
                self._state_of(f"pool:{name}").below_since = None
            # Scale-up order: spot pools first — batch/electron overflow
            # belongs on cheap capacity, keeping stable slots free for
            # the serving tier pinned there.
            deficit = demand - capacity
            for name, pool, pol in sorted(
                managed, key=lambda entry: (not entry[1].preemptible,
                                            entry[0]),
            ):
                if deficit <= 0:
                    break
                state = self._state_of(f"pool:{name}")
                if pool.capacity >= pol.max_capacity:
                    continue
                if not self._up_ready(state, now):
                    continue
                # One full step per pool per tick (never a partial step
                # even when the deficit is smaller: capacity is cheap to
                # shed later, a second reaction round trip is not).
                target = min(pol.max_capacity, pool.capacity + pol.step)
                grown = target - pool.capacity
                if grown <= 0:
                    continue
                pool.capacity = target
                state.last_up = now
                state.below_since = None
                deficit -= grown
                decisions.append(self._record(
                    "pool_up", name, target,
                    "slo_burn" if dispatch_burn else "queue_trend", now,
                ))
        elif demand < capacity and not dispatch_burn:
            # Hysteresis: released capacity only after the demand sat a
            # full dwell below (capacity - step) — never mid-burn.
            for name, pool, pol in sorted(
                managed, key=lambda entry: -entry[1].free_slots,
            ):
                state = self._state_of(f"pool:{name}")
                below = demand <= capacity - pol.step
                if pool.capacity <= pol.min_capacity:
                    state.below_since = None
                    continue
                if not self._down_ready(state, now, below):
                    continue
                target = max(pol.min_capacity, pool.capacity - pol.step)
                shrunk = pool.capacity - target  # may be < step (clamped)
                pool.capacity = target
                state.last_down = now
                state.below_since = None
                capacity -= shrunk
                decisions.append(self._record(
                    "pool_down", name, target, "idle_capacity", now,
                ))
        else:
            # demand == capacity (or a burn): not "below" — every pool's
            # sustained-below dwell re-arms.  Without this, a fleet
            # pinned at max capacity under oscillating demand would bank
            # quiet ticks across spikes and flap a scale-down.
            for name, _p, _pol in managed:
                self._state_of(f"pool:{name}").below_since = None
        decisions += await self._scale_pools_to_zero(
            now, depth, slope, dispatch_burn, managed
        )
        return decisions

    async def _scale_pools_to_zero(
        self,
        now: float,
        depth: int,
        slope: float,
        dispatch_burn: bool,
        managed: list,
    ) -> list[dict[str, Any]]:
        """Idle-TTL gang teardown + predictive re-warm per pool."""
        decisions: list[dict[str, Any]] = []
        demand_coming = (
            depth > 0 or slope > 0 or dispatch_burn
        )
        for name, pool, pol in managed:
            state = self._state_of(f"pool:{name}")
            ttl = self.idle_ttl_s if pol.idle_ttl_s is None else pol.idle_ttl_s
            idle = (
                pool.in_use == 0
                and pool.warm
                and pool.serve_session_count() == 0
                and not demand_coming
            )
            if not idle:
                state.idle_since = None
            elif ttl > 0:
                if state.idle_since is None:
                    state.idle_since = now
                elif now - state.idle_since >= ttl:
                    if await pool.teardown():
                        decisions.append(self._record(
                            "gang_teardown", name, None,
                            f"idle>{ttl:g}s", now,
                        ))
                    state.idle_since = None
            # Predictive re-warm: demand is trending in and this pool's
            # gang is cold — start the dial/pre-flight/agent warm-up now
            # so the lead time is already paid when placement needs it.
            # The up-cooldown paces retries when the dial keeps failing.
            if (
                demand_coming
                and not pool.warm
                and not pool.fallback
                and name not in self._prewarm_tasks
                and (
                    state.last_prewarm is None
                    or now - state.last_prewarm >= self.up_cooldown_s
                )
            ):
                state.last_prewarm = now
                task = asyncio.ensure_future(pool.prewarm())
                self._prewarm_tasks[name] = task
                task.add_done_callback(
                    lambda t, n=name: (
                        self._prewarm_tasks.pop(n, None),
                        None if t.cancelled() else t.exception(),
                    )
                )
                decisions.append(self._record(
                    "prewarm", name, None,
                    "slo_burn" if dispatch_burn else "queue_trend", now,
                ))
        return decisions

    # -- replica sets --------------------------------------------------------

    async def _tick_sets(
        self, now: float, serving_burn: bool
    ) -> list[dict[str, Any]]:
        decisions: list[dict[str, Any]] = []
        for rset, pol in list(self._sets):
            try:
                decisions += await self._tick_one_set(
                    rset, pol, now, serving_burn
                )
            except Exception as err:  # noqa: BLE001 - one bad set
                app_log.warning(
                    "autoscale: replica set %s tick failed: %s",
                    getattr(rset, "name", "?"), err,
                )
        return decisions

    async def _tick_one_set(
        self, rset: Any, pol: ReplicaSetPolicy, now: float,
        serving_burn: bool,
    ) -> list[dict[str, Any]]:
        decisions: list[dict[str, Any]] = []
        name = getattr(rset, "name", "set")
        resource = f"set:{name}"
        state = self._state_of(resource)
        if getattr(rset, "state", "") == "closed":
            self._sets = [
                (r, p) for r, p in self._sets if r is not rset
            ]
            return decisions
        live = int(getattr(rset, "live_replicas", 0))
        suspended = bool(getattr(rset, "suspended", False))
        if resource in self._suspended_seen and live > 0:
            # The set re-warmed itself on demand (scale-to-zero exit
            # happens in the request path, not here): account for it so
            # operators see the resume in the same decision stream.
            self._suspended_seen.discard(resource)
            decisions.append(self._record(
                "set_resume", name, live, "demand_rewarm", now,
            ))
        load = int(getattr(rset, "in_flight", 0)) + int(
            getattr(rset, "queued", 0)
        )
        slots = int(getattr(rset, "decode_slots", 0))
        per_replica = (slots / live) if live and slots else 0.0
        slope = self._slope(
            "covalent_tpu_serve_replica_in_flight", {"set": name}
        )
        lead = self._lead_for("")
        predicted = load + max(0.0, slope) * lead
        desired = (
            math.ceil(predicted / (per_replica * pol.target_utilization))
            if per_replica else live
        )
        desired = min(pol.max_replicas, max(pol.min_replicas, desired))
        if serving_burn and pol.slo_critical:
            # The burn path: a burning serving SLO forces one step of
            # growth regardless of what the trend predicts — clearing
            # the burn is the point of having warm headroom.
            desired = max(desired, min(pol.max_replicas, live + 1))
        AUTOSCALE_TARGET.labels(resource=resource).set(desired)

        if live == 0:
            if suspended:
                # Suspended set: demand re-warms it through its own
                # request path; the controller only tracks it.
                self._suspended_seen.add(resource)
                return decisions
            # Every replica died WITHOUT a suspension (all past their
            # retry budgets): the request path raises for such a set, so
            # the controller is the only thing that can honor the
            # policy's replica floor — re-open to it, paced by the
            # up-cooldown so a dead fleet is retried, not hammered.
            if self._up_ready(state, now):
                target = max(1, pol.min_replicas)
                try:
                    revived = int(await rset.scale_to(target))
                except Exception as err:  # noqa: BLE001 - retried next tick
                    app_log.warning(
                        "autoscale: reviving dead set %s failed: %s",
                        name, err,
                    )
                    revived = 0
                state.last_up = now
                if revived:
                    decisions.append(self._record(
                        "set_up", name, target, "revive_dead", now,
                    ))
            return decisions
        if desired > live:
            # High demand re-arms the sustained-below dwell regardless of
            # whether the up-cooldown lets this tick act (no flapping on
            # oscillating load).
            state.below_since = None
            state.idle_since = None
            state.up_pending += 1
            burn_driven = serving_burn and pol.slo_critical
            # Trend/load scale-ups wait out the stabilization window (a
            # one-tick in-flight spike is not demand); a burning SLO
            # acts immediately — that is what the headroom is FOR.
            if (
                not burn_driven
                and state.up_pending < pol.up_stabilization_ticks
            ):
                return decisions
            if self._up_ready(state, now):
                await rset.scale_to(desired)
                state.last_up = now
                state.up_pending = 0
                decisions.append(self._record(
                    "set_up", name, desired,
                    "slo_burn" if serving_burn else "load_trend", now,
                ))
            return decisions
        state.up_pending = 0
        # Scale-down / scale-to-zero side: vetoed outright mid-burn.
        if serving_burn and pol.slo_critical:
            state.below_since = None
            state.idle_since = None
            return decisions
        utilization = (load / slots) if slots else 0.0
        ttl = self.idle_ttl_s if pol.idle_ttl_s is None else pol.idle_ttl_s
        if pol.min_replicas == 0 and ttl > 0 and load == 0 and slope <= 0:
            if state.idle_since is None:
                state.idle_since = now
            elif now - state.idle_since >= ttl:
                await rset.scale_to(0)
                self._suspended_seen.add(resource)
                state.idle_since = None
                state.below_since = None
                state.last_down = now
                decisions.append(self._record(
                    "set_suspend", name, 0, f"idle>{ttl:g}s", now,
                ))
                return decisions
        else:
            state.idle_since = None
        if desired < live:
            below = utilization < pol.scale_down_utilization
            if self._down_ready(state, now, below):
                target = max(desired, max(1, pol.min_replicas), live - 1)
                if target < live:
                    await rset.scale_to(target)
                    state.last_down = now
                    state.below_since = None
                    decisions.append(self._record(
                        "set_down", name, target, "low_utilization", now,
                    ))
        else:
            state.below_since = None
        return decisions

    # -- observability -------------------------------------------------------

    def status(self) -> dict[str, Any]:
        """The ``autoscaler`` section of the ops ``/status`` payload."""
        now = self._clock()

        def cooldown_view(resource: str) -> dict[str, Any]:
            state = self._state.get(resource)
            if state is None:
                return {}
            view: dict[str, Any] = {}
            if state.last_up is not None:
                view["since_up_s"] = round(now - state.last_up, 3)
            if state.last_down is not None:
                view["since_down_s"] = round(now - state.last_down, 3)
            if state.below_since is not None:
                view["below_for_s"] = round(now - state.below_since, 3)
            if state.idle_since is not None:
                view["idle_for_s"] = round(now - state.idle_since, 3)
            return view

        pools: dict[str, Any] = {}
        for name, pol in self._pools.items():
            pool = self.registry.get(name) if self.registry else None
            pools[name] = {
                "capacity": pool.capacity if pool else None,
                "in_use": pool.in_use if pool else None,
                "warm": pool.warm if pool else None,
                "min": pol.min_capacity,
                "max": pol.max_capacity,
                "lead_s": round(self._lead_for(name), 3),
                "cooldown": cooldown_view(f"pool:{name}"),
            }
        sets: dict[str, Any] = {}
        for rset, pol in self._sets:
            name = getattr(rset, "name", "set")
            sets[name] = {
                "replicas": int(getattr(rset, "live_replicas", 0)),
                "suspended": bool(getattr(rset, "suspended", False)),
                "in_flight": int(getattr(rset, "in_flight", 0)),
                "queued": int(getattr(rset, "queued", 0)),
                "min": pol.min_replicas,
                "max": pol.max_replicas,
                "slo_critical": pol.slo_critical,
                "cooldown": cooldown_view(f"set:{name}"),
            }
        return {
            "interval_s": self.interval_s,
            "up_cooldown_s": self.up_cooldown_s,
            "down_cooldown_s": self.down_cooldown_s,
            "idle_ttl_s": self.idle_ttl_s,
            # dict() snapshot: the alert hook writes from another thread.
            "burning": sorted(dict(self._burning)),
            "pools": pools,
            "sets": sets,
            "decisions": list(self._decisions)[-16:],
            "decision_counts": dict(self.decision_counts),
        }
