"""The GangLease seam: gang *ownership* split from the run-attempt machine.

Historically ``TPUExecutor._run_attempt`` both *owned* its gang (dialing
every worker, running pre-flight, warming agents, discarding channels on
failure) and *drove* the attempt state machine over it (stage, upload,
launch, poll, fetch, retry classification).  A fleet scheduler needs those
concerns apart: placement — which pool's warm gang an electron lands on —
belongs to the tier above the executor, while the attempt machine stays
where the transport knowledge lives.

:class:`GangLease` is that seam.  ``TPUExecutor.lease_gang()`` acquires a
fully warmed gang (pooled connections + pre-flight + resident agents) and
returns a lease; the attempt machine consumes the lease's channels, and the
scheduler can hold/warm leases independently of any electron.  Ownership
operations route through the lease:

* ``lease.conns`` / ``lease.addresses`` — the gang's live channels.
* ``lease.discard()`` — drop exactly these channels (a concurrent
  electron's fresh redial under the same keys survives).

The lease holds only a weak contract with its owner (duck-typed
``_discard_workers``), so fakes/stub executors in tests can vend leases
too.
"""

from __future__ import annotations

from typing import Any, Sequence


class GangLease:
    """Ownership handle for one warm gang of workers.

    Produced by ``TPUExecutor.lease_gang()`` after connect + pre-flight +
    agent warm-up all succeeded; the holder may run one (or, bin-packed
    over time, many) electrons over ``conns`` and must route teardown
    through :meth:`discard` rather than closing channels directly.
    """

    __slots__ = ("_owner", "conns", "addresses")

    def __init__(
        self, owner: Any, conns: Sequence[Any], addresses: Sequence[str]
    ) -> None:
        self._owner = owner
        self.conns = list(conns)
        self.addresses = list(addresses)

    def __len__(self) -> int:
        return len(self.conns)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<GangLease {len(self.conns)} worker(s): {self.addresses}>"

    @property
    def owner(self) -> Any:
        """The executor that vended this lease."""
        return self._owner

    async def discard(self) -> None:
        """Drop exactly this lease's channels from the owner's pool.

        Scoped the same way mid-run error teardown is: only the channels
        this lease actually holds are discarded, so a concurrent
        electron's fresh redial under the same pool key survives.
        """
        await self._owner._discard_workers(self.conns)
