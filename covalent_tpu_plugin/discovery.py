"""TPU pod worker discovery (SURVEY §5 config: ``tpu_name``/``zone``/``project``).

The reference takes exactly one ``hostname`` (``covalent_ssh_plugin/
ssh.py:77``); a TPU pod slice is N workers whose addresses live in GCP
metadata.  Given a TPU name, this module resolves every worker's control-
plane address with ``gcloud compute tpus tpu-vm describe`` so users write

    TPUExecutor(tpu_name="my-v5e-16", zone="us-west4-a", project="p")

instead of enumerating worker IPs by hand.  The gcloud invocation is
overridable via ``COVALENT_TPU_GCLOUD_CMD`` (tests substitute a recorder;
air-gapped deployments can point at a wrapper).
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess

from .transport.base import TransportError


class DiscoveryError(TransportError):
    """gcloud missing/failed or returned no usable worker endpoints.

    A :class:`TransportError` so the executor's could-not-reach-workers
    routing (local fallback / RuntimeError policy) applies uniformly.
    """


def discover_tpu_endpoints(
    tpu_name: str,
    zone: str = "",
    project: str = "",
    timeout: float = 60.0,
) -> list[tuple[str, str]]:
    """``(external_ip, internal_ip)`` per worker, in worker index order.

    Worker order matters: worker 0 hosts the ``jax.distributed``
    coordinator, and GCP's ``networkEndpoints`` list is already in worker
    index order.  Callers pick per plane: the SSH control plane usually
    needs the external IP (dispatcher outside the VPC), while the
    coordinator address must be the *internal* IP — default GCP firewalls
    only allow arbitrary ports within the VPC, so workers dialing worker
    0's external IP would hang in ``jax.distributed.initialize``.
    """
    base = shlex.split(os.environ.get("COVALENT_TPU_GCLOUD_CMD", "")) or ["gcloud"]
    argv = base + ["compute", "tpus", "tpu-vm", "describe", tpu_name, "--format=json"]
    if zone:
        argv += [f"--zone={zone}"]
    if project:
        argv += [f"--project={project}"]
    try:
        proc = subprocess.run(argv, capture_output=True, text=True, timeout=timeout)
    except FileNotFoundError as err:
        raise DiscoveryError(
            f"cannot discover workers for {tpu_name!r}: {base[0]} not found "
            "(install the Google Cloud SDK or set `workers` explicitly)"
        ) from err
    except subprocess.TimeoutExpired as err:
        raise DiscoveryError(f"{base[0]} describe timed out for {tpu_name!r}") from err
    if proc.returncode != 0:
        raise DiscoveryError(
            f"{base[0]} describe failed for {tpu_name!r}: {proc.stderr.strip()}"
        )
    try:
        description = json.loads(proc.stdout)
    except ValueError as err:
        raise DiscoveryError(
            f"unparseable describe output for {tpu_name!r}"
        ) from err

    endpoints: list[tuple[str, str]] = []
    for endpoint in description.get("networkEndpoints") or []:
        external = (endpoint.get("accessConfig") or {}).get("externalIp", "")
        internal = endpoint.get("ipAddress", "")
        if external or internal:
            endpoints.append((external, internal))
    if not endpoints:
        raise DiscoveryError(
            f"TPU {tpu_name!r} reports no network endpoints "
            f"(state: {description.get('state', 'unknown')})"
        )
    return endpoints


def discover_pool_spec(
    tpu_name: str,
    zone: str = "",
    project: str = "",
    capacity: int = 1,
    name: "str | None" = None,
    prefer_external: bool = True,
    timeout: float = 60.0,
) -> dict:
    """A fleet pool spec dict resolved from one TPU's live endpoints.

    The fleet-registry wiring: ``discover_tpu_endpoints()`` results become
    a registrable pool spec (``PoolRegistry.register`` /
    ``register_tpu``), so a fleet is stood up from TPU names without
    hand-listing workers.  The control plane keeps the same external-IP
    preference the executor uses; discovery failures propagate as
    :class:`DiscoveryError` rather than registering an empty pool.
    """
    endpoints = discover_tpu_endpoints(
        tpu_name, zone=zone, project=project, timeout=timeout
    )
    workers = [
        (ext or int_) if prefer_external else (int_ or ext)
        for ext, int_ in endpoints
    ]
    return {
        "name": name or tpu_name,
        "workers": tuple(workers),
        "capacity": max(1, int(capacity)),
        "tpu_name": tpu_name,
        "zone": zone,
        "project": project,
        # The raw (external, internal) pairs ride along so the pool's
        # executor can seed its discovery cache: one gcloud subprocess
        # per registration, not a second at first dispatch (which could
        # also disagree with the registered workers if the TPU was
        # re-created in between).
        "endpoints": tuple(endpoints),
    }


def discover_tpu_workers(
    tpu_name: str,
    zone: str = "",
    project: str = "",
    prefer_external: bool = True,
    timeout: float = 60.0,
) -> list[str]:
    """Flat address list for one plane; see :func:`discover_tpu_endpoints`."""
    return [
        (ext or int_) if prefer_external else (int_ or ext)
        for ext, int_ in discover_tpu_endpoints(
            tpu_name, zone=zone, project=project, timeout=timeout
        )
    ]
