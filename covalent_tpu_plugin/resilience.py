"""Resilience layer: fault classification, retries, circuit breakers, deadlines.

The reference plugin treats every dispatch failure the same way — one shot,
then either a RuntimeError or a silent local-CPU fallback
(``covalent_ssh_plugin/ssh.py:181-208``).  On a production TPU fleet that is
exactly backwards: preemption, dropped SSH channels, and flaky preflights
are *routine* (Podracer, arXiv:2104.06272, treats preemption-tolerant
restart as table stakes), while a user-code exception must never be retried.
This module gives every dispatch layer a shared vocabulary for that
distinction:

* :func:`classify_error` — transient (channel death, connect/preflight
  failure, agent RPC loss, worker death without a result) vs permanent
  (user-code exception, digest mismatch, cancellation, config errors).
* :class:`RetryPolicy` — exponential backoff with full jitter under an
  attempt + wall-clock budget (the AWS-style ``random(0, min(cap, base·2ⁿ))``
  schedule, deterministic when seeded).
* :class:`CircuitBreaker` / :class:`CircuitBreakerRegistry` — per-worker-
  address quarantine: CLOSED → OPEN after N consecutive transient failures,
  cooldown, HALF_OPEN probe, with a state gauge and transition events so a
  quarantined host is visible, not silent.
* :class:`Deadline` — wall-clock budget propagation, so ``task_timeout``
  *escalates* (kill the gang, classify, retry) instead of abandoning
  RUNNING remote processes.

Everything here is transport-agnostic and imports only ``transport.base``
and the obs layer, so the executor, the pool, and the workflow runner can
all consult the same breaker/policy objects without import cycles.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable

from .obs import events as obs_events
from .obs.metrics import REGISTRY
from .transport.base import TransportError

__all__ = [
    "CircuitBreaker",
    "CircuitBreakerRegistry",
    "CircuitOpenError",
    "CircuitState",
    "Deadline",
    "FaultClass",
    "RetryPolicy",
    "WorkerPreemptedError",
    "WorkerStalledError",
    "classify_error",
    "TASK_RETRIES_TOTAL",
]


TASK_RETRIES_TOTAL = REGISTRY.counter(
    "covalent_tpu_task_retries_total",
    "Electron dispatch retries by transient-failure reason",
    ("reason",),
)
_CIRCUIT_STATE = REGISTRY.gauge(
    "covalent_tpu_circuit_state",
    "Per-worker circuit state (0=closed, 1=half_open, 2=open)",
    ("address",),
)
_CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "covalent_tpu_circuit_transitions_total",
    "Circuit-breaker state transitions by destination state",
    ("to",),
)


# --------------------------------------------------------------------------
# Fault classification
# --------------------------------------------------------------------------


class WorkerStalledError(TransportError):
    """Liveness failure: a worker that was heartbeating went silent past
    its stall threshold while its process still looks alive (or its state
    is unknowable).  Raised by the missed-heartbeat detector
    (``obs.heartbeat.MONITOR`` via the executor's pollers) so a wedged
    worker is classified and retried *before* the hard ``task_timeout``
    fires.  Transient by construction — a gang restart on fresh state is
    exactly the remedy for a hang."""


class WorkerPreemptedError(TransportError):
    """A worker died *after announcing a preemption notice*
    (``worker.preempt_notice``, the SIGTERM the cloud delivers before
    reclaiming a spot TPU VM).  Transient — and kept distinguishable from
    an ordinary channel death or worker crash because the remedy differs:
    the retry should resume from the cooperative checkpoint the notice
    handler just published, and an operator watching
    ``covalent_tpu_task_retries_total{reason="worker_preempted"}`` is
    watching their spot-reclaim rate, not a bug."""


class FaultClass(str, Enum):
    """Whether a failure is worth retrying."""

    TRANSIENT = "transient"   # infrastructure: retry may succeed
    PERMANENT = "permanent"   # deterministic: retrying re-runs the failure


def classify_error(error: BaseException) -> tuple[FaultClass, str]:
    """``(fault class, reason label)`` for one dispatch-layer exception.

    The reason label feeds ``covalent_tpu_task_retries_total{reason}`` and
    retry events, so it stays low-cardinality.  Classification is by
    exception *type*: the dispatch layers raise ``TransportError`` (and its
    subclasses) for every control-plane fault, while user-code exceptions
    arrive as arbitrary types re-raised from the remote result pickle — and
    anything unrecognized is deliberately PERMANENT, because retrying an
    unknown failure repeats work without evidence it can ever succeed.
    """
    if isinstance(error, asyncio.CancelledError):
        return FaultClass.PERMANENT, "cancelled"
    # Duck-typed self-classification: layers above the transport (e.g. the
    # fleet queue's admission shed) tag their exceptions with fault_label/
    # fault_transient instead of importing this module — admission control
    # must read PERMANENT (retrying amplifies the very overload that shed
    # the work) without resilience.py depending on the scheduler tier.
    label = getattr(error, "fault_label", None)
    if isinstance(label, str) and label:
        transient = bool(getattr(error, "fault_transient", False))
        return (
            FaultClass.TRANSIENT if transient else FaultClass.PERMANENT
        ), label
    # Follow the cause chain: aggregation layers (e.g. _connect_all's
    # "failed to connect to N workers" TransportError) wrap the breaker's
    # fail-fast, and quarantine-driven failures must stay distinguishable.
    cause: BaseException | None = error
    for _ in range(8):
        if cause is None:
            break
        if isinstance(cause, CircuitOpenError):
            # Retrying (with backoff) is how a caller waits out the
            # cooldown into the half-open probe.
            return FaultClass.TRANSIENT, "circuit_open"
        cause = cause.__cause__
    if isinstance(error, WorkerStalledError):
        # Missed-heartbeat liveness failures keep their own label so an
        # operator can tell a wedged worker from a dropped channel.
        return FaultClass.TRANSIENT, "worker_stalled"
    if isinstance(error, WorkerPreemptedError):
        # Spot reclaim: transient, resumable from the notice-triggered
        # checkpoint, and its own label (capacity churn is not a bug).
        return FaultClass.TRANSIENT, "worker_preempted"
    if isinstance(error, TransportError):
        # Covers AgentError (agent RPC loss) and chaos-injected faults too.
        return FaultClass.TRANSIENT, "transport"
    if isinstance(
        error,
        (FileNotFoundError, PermissionError, IsADirectoryError,
         NotADirectoryError),
    ):
        # Deterministic filesystem errors (a staged artifact missing on
        # the dispatcher, an unreadable key): retrying — with gang
        # teardown, backoff, and redial — repeats the identical failure.
        # Remote-side path problems never reach here raw; the transports
        # wrap them in TransportError.
        return FaultClass.PERMANENT, type(error).__name__
    if isinstance(error, (ConnectionError, TimeoutError, OSError)):
        return FaultClass.TRANSIENT, "connection"
    return FaultClass.PERMANENT, type(error).__name__


# --------------------------------------------------------------------------
# Retry policy
# --------------------------------------------------------------------------


@dataclass
class RetryPolicy:
    """Exponential backoff + full jitter under attempt/wall-clock budgets.

    ``max_retries`` counts *re*-submissions (0 = single attempt, today's
    behavior).  ``wall_budget`` is the elapsed time after which no NEW
    attempt may start — backoff sleeps are capped to it, but an in-flight
    attempt is never killed by it (0 disables).  ``seed`` pins the jitter
    RNG so tests and chaos runs are deterministic.
    """

    max_retries: int = 0
    base_delay: float = 0.25
    max_delay: float = 10.0
    wall_budget: float = 0.0
    seed: int | None = None
    _rng: random.Random = field(init=False, repr=False)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def delay(self, attempt: int) -> float:
        """Full-jitter backoff for the sleep *before* attempt ``attempt+1``."""
        ceiling = min(self.max_delay, self.base_delay * (2 ** attempt))
        return self._rng.uniform(0.0, ceiling)

    def should_retry(
        self, attempt: int, fault: FaultClass, deadline: "Deadline"
    ) -> bool:
        """May attempt ``attempt`` (0-based) be followed by another?"""
        if fault is not FaultClass.TRANSIENT:
            return False
        if attempt >= self.max_retries:
            return False
        return not deadline.expired


# --------------------------------------------------------------------------
# Deadlines
# --------------------------------------------------------------------------


class Deadline:
    """A started wall-clock budget; ``budget <= 0`` means unbounded.

    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self, budget: float = 0.0, clock: Callable[[], float] = time.monotonic
    ) -> None:
        self.budget = float(budget)
        self._clock = clock
        self._start = clock()

    @property
    def bounded(self) -> bool:
        return self.budget > 0

    def elapsed(self) -> float:
        return self._clock() - self._start

    def remaining(self) -> float | None:
        """Seconds left, or None when unbounded.  Never negative."""
        if not self.bounded:
            return None
        return max(0.0, self.budget - self.elapsed())

    @property
    def expired(self) -> bool:
        return self.bounded and self.elapsed() >= self.budget


# --------------------------------------------------------------------------
# Circuit breakers
# --------------------------------------------------------------------------


class CircuitOpenError(TransportError):
    """Fail-fast: the worker's circuit is open; no dial was attempted."""


class CircuitState(str, Enum):
    CLOSED = "closed"          # normal operation
    OPEN = "open"              # quarantined: fail fast, no dialing
    HALF_OPEN = "half_open"    # cooldown elapsed: one probe in flight

    @property
    def gauge_value(self) -> int:
        return {"closed": 0, "half_open": 1, "open": 2}[self.value]


class CircuitBreaker:
    """Per-worker-address failure quarantine.

    CLOSED → OPEN after ``failure_threshold`` *consecutive* transient
    failures; OPEN → HALF_OPEN once ``cooldown`` elapses (the next
    :meth:`check` lets exactly one probe through); HALF_OPEN → CLOSED on
    success, back to OPEN on failure.  Not thread-safe by design: all
    dispatch paths run on the one dispatcher event loop.
    """

    def __init__(
        self,
        address: str,
        failure_threshold: int = 3,
        cooldown: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.address = address
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown = float(cooldown)
        self._clock = clock
        self._state = CircuitState.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        _CIRCUIT_STATE.labels(address=address).set(0)

    @property
    def state(self) -> CircuitState:
        """Current state, promoting OPEN → HALF_OPEN after the cooldown."""
        if (
            self._state is CircuitState.OPEN
            and self._clock() - self._opened_at >= self.cooldown
        ):
            self._transition(CircuitState.HALF_OPEN)
        return self._state

    def _transition(self, to: CircuitState) -> None:
        if to is self._state:
            return
        obs_events.emit(
            "circuit.state",
            address=self.address,
            from_state=self._state.value,
            to_state=to.value,
            consecutive_failures=self._consecutive_failures,
        )
        _CIRCUIT_TRANSITIONS.labels(to=to.value).inc()
        _CIRCUIT_STATE.labels(address=self.address).set(to.gauge_value)
        self._state = to
        if to is CircuitState.OPEN:
            self._opened_at = self._clock()

    def check(self) -> None:
        """Raise :class:`CircuitOpenError` when the host is quarantined.

        In HALF_OPEN the first check passes (the probe) and the breaker
        re-opens optimistically only on the probe's reported outcome — a
        concurrent second caller during the probe window fails fast.
        """
        state = self.state
        if state is CircuitState.OPEN:
            raise CircuitOpenError(
                f"circuit open for {self.address} "
                f"({self._consecutive_failures} consecutive failures; "
                f"retrying after {self.cooldown:.0f}s cooldown)"
            )
        if state is CircuitState.HALF_OPEN:
            # One probe at a time: record_success/record_failure from the
            # in-flight probe settles the real outcome; concurrent callers
            # during the probe window fail fast.
            if self._probe_in_flight:
                raise CircuitOpenError(
                    f"circuit half-open for {self.address}; probe in flight"
                )
            self._probe_in_flight = True

    def record_success(self) -> None:
        self._consecutive_failures = 0
        self._probe_in_flight = False
        self._transition(CircuitState.CLOSED)

    def record_failure(self) -> None:
        self._consecutive_failures += 1
        self._probe_in_flight = False
        if (
            self._state is CircuitState.HALF_OPEN
            or self._consecutive_failures >= self.failure_threshold
        ):
            # A failed half-open probe re-opens immediately; in CLOSED the
            # threshold governs.
            self._transition(CircuitState.OPEN)


class CircuitBreakerRegistry:
    """One :class:`CircuitBreaker` per worker address, created on demand."""

    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}

    def get(self, address: str) -> CircuitBreaker:
        breaker = self._breakers.get(address)
        if breaker is None:
            breaker = CircuitBreaker(
                address,
                failure_threshold=self.failure_threshold,
                cooldown=self.cooldown,
                clock=self._clock,
            )
            self._breakers[address] = breaker
        return breaker

    def states(self) -> dict[str, str]:
        """address -> state snapshot (telemetry / debugging)."""
        return {a: b.state.value for a, b in self._breakers.items()}
