"""covalent_tpu_plugin — TPU-native Covalent executor framework.

Public surface mirrors the reference package export
(``covalent_ssh_plugin/__init__.py:17`` re-exports ``SSHExecutor``): here the
executor is :class:`TPUExecutor`, used as ``@ct.electron(executor="tpu")``
once registered, or constructed directly.

Beyond the executor, the package ships the TPU compute stack the north star
requires: ``parallel`` (meshes, shardings, jax.distributed bootstrap),
``ops`` (attention kernels, ring attention), ``models`` (Flax MNIST +
transformer LM), and — when the upstream ``covalent`` package is absent — a
built-in minimal workflow layer (``electron``/``lattice``/``dispatch``/
``get_result``) so the framework runs standalone.
"""

from . import obs
from .cache import CASIndex, ResultCache
from .fleet import FleetExecutor, FleetScheduler, PoolRegistry, PoolSpec
from .resilience import CircuitBreaker, Deadline, RetryPolicy
from .tpu import EXECUTOR_PLUGIN_NAME, TPUExecutor
from .transport import ChaosPlan, ChaosTransport

__all__ = [
    "TPUExecutor",
    "EXECUTOR_PLUGIN_NAME",
    "obs",
    "CASIndex",
    "ResultCache",
    "RetryPolicy",
    "CircuitBreaker",
    "Deadline",
    "ChaosPlan",
    "ChaosTransport",
    "FleetExecutor",
    "FleetScheduler",
    "PoolRegistry",
    "PoolSpec",
]

__version__ = "0.1.0"
