"""One supervised serving session: open, stream, reconnect, replay.

The serving tier splits into two concerns that PR 9 originally fused
inside ``ServeHandle``:

* **Supervision** (this module) — owning ONE remote session generation:
  lease a gang, ship the factory by CAS digest, watch the side-band,
  reconnect on channel death with jittered bounded retries, and replay
  in-flight requests with the exactly-once ``idx`` splice.
* **Routing / multiplexing** (``handle.py``, ``replicas.py``) — deciding
  WHICH supervised session a caller's request lands on.  A
  :class:`~.handle.ServeHandle` fronts one supervisor; a
  :class:`~.replicas.ReplicaSet` fronts N of them behind a
  session-aware router — neither re-implements any replay machinery.

A :class:`SessionSupervisor` registers itself in the executor's
``_serve_handles`` book (so ``/status``, ``pool.status()`` and the
profile-target pinning see every live session, replica or not), pins one
fleet capacity slot when opened through a pool, and reaps its gauge
series through ``_drop_live`` on every terminal path.

Because a replayed (or re-routed) stream restarts from token 0 and is
spliced on the request's token high-water mark, any supervisor can pick
up any :class:`ServeRequest` mid-stream: the request object carries the
splice state, not the session.  That is what lets a replica set drain a
dying session's callers onto survivors without duplicating a token.
"""

from __future__ import annotations

import asyncio
import os
import time
import uuid
from typing import Any, AsyncIterator, Callable

from ..agent import HARNESS_BASENAME, AgentClient, AgentError
from ..cache import bytes_digest, cas_path
from ..fleet import journal as journal_mod
from ..fleet.health import HEALTH
from ..obs import events as obs_events
from ..obs.trace import Span, context_of, record_span
from ..resilience import FaultClass, RetryPolicy, classify_error
from ..transport.base import TransportError
from ..utils.log import app_log
from .metrics import (
    SERVE_ADAPTER_ATTACH_SECONDS,
    SERVE_ADAPTER_ATTACHES_TOTAL,
    SERVE_ADAPTER_REQUESTS_TOTAL,
    SERVE_ADAPTER_TOKENS,
    SERVE_ADAPTERS,
    SERVE_HANDOFFS_TOTAL,
    SERVE_MODE_TOKENS,
    SERVE_PREFILL_POSITIONS,
    SERVE_PREFIX_HITS,
    SERVE_PREFIX_MISSES,
    SERVE_QUEUE_DEPTH,
    SERVE_RECONNECTS_TOTAL,
    SERVE_REPLICA_IN_FLIGHT,
    SERVE_REPLICA_REQUESTS_TOTAL,
    SERVE_REQUEST_SECONDS,
    SERVE_REQUESTS_TOTAL,
    SERVE_SESSIONS,
    SERVE_SPEC_ACCEPT_RATE,
    SERVE_TOKENS_PER_S,
    SERVE_TOKENS_TOTAL,
    SERVE_TTFT_SECONDS,
    SERVE_WORKER_SLOTS,
)

#: Mirror of ``models.quant.SERVING_MODES``: the closed decode-mode set
#: the per-mode token gauge is labelled with.  Mirrored rather than
#: imported — the dispatcher-side serving tier deliberately never
#: imports the models package (it would drag jax into processes that
#: only route) — and the reap in :meth:`SessionSupervisor._drop_live`
#: enumerates it, which is only sound because the set is closed.
_SERVING_MODES = ("fp", "int8", "kv_quant", "full_quant")

__all__ = [
    "ServeError",
    "ServeRequest",
    "ServeRequestRejected",
    "SessionSupervisor",
]


def _env_number(name: str, default: float, cast=float):
    value = os.environ.get(name)
    if value is None:
        return default
    try:
        return cast(value)
    except (TypeError, ValueError):
        app_log.warning("ignoring non-numeric %s=%r", name, value)
        return default


class ServeError(RuntimeError):
    """Session-level failure (open refused, stream torn, handle closed)."""


class ServeRequestRejected(ServeError):
    """One request refused by the worker (shed, unknown session, engine).

    Duck-tagged for :func:`~..resilience.classify_error`: an admission
    shed is PERMANENT under the ``serve_admission_shed`` label — the
    bounded queue refused the work *because* the session is overloaded,
    and a gang retry would amplify exactly that.  A lost session
    (``unknown_session`` racing a worker restart) stays transient: the
    handle's reconnect re-opens it.
    """

    def __init__(self, rid: str, code: str, message: str) -> None:
        super().__init__(f"request {rid} rejected ({code}): {message}")
        self.rid = rid
        self.code = code
        if code == "serve_admission_shed":
            self.fault_label = "serve_admission_shed"
            self.fault_transient = False
        elif code == "unknown_session":
            self.fault_label = "serve_session_lost"
            self.fault_transient = True
        else:
            self.fault_label = f"serve_{code or 'rejected'}"
            self.fault_transient = False


class ServeRequest:
    """One in-flight request's stream state (created by the front-end).

    ``stream()`` yields token chunks as they arrive; ``result()`` awaits
    the final token list.  A request that hit its deadline completes
    normally with the partial stream and ``error == "deadline_exceeded"``
    (the tokens generated before the reclaim are real); a *rejected*
    request raises :class:`ServeRequestRejected` from both surfaces.

    The request carries its own splice state (the ``tokens`` high-water
    mark), so a replayed — or re-routed — stream can be picked up by a
    different supervisor with exactly-once delivery intact.
    """

    def __init__(
        self,
        rid: str,
        prompt: list[int],
        params: dict | None,
        deadline_s: float,
        tenant: str,
    ) -> None:
        self.rid = rid
        self.prompt = prompt
        self.params = dict(params or {})
        self.deadline_s = float(deadline_s)
        self.tenant = tenant
        #: the caller's multi-turn session key (set by a replica set);
        #: rides the request so a drain-on-death re-route keeps the pin.
        self.sticky = ""
        #: (bundle bytes, sha256) attached by a disaggregated front: the
        #: decode replica admits from this KV instead of prefilling.  It
        #: rides the request so a replay — or a re-route onto another
        #: replica — keeps the prefill-tier work.
        self.kv: tuple[bytes, str] | None = None
        #: prefix-affinity routing key (digest of the prompt's reusable
        #: prefix): the router steers requests sharing it to the replica
        #: whose engine-side prefix tree is already warm for it.
        self.prefix_key = ""
        self.tokens: list[int] = []
        #: absolute stream offset this request resumed from (crash
        #: recovery): the prefix ``[0, resumed_from)`` was delivered by a
        #: PRIOR dispatcher incarnation and is not re-collected here, so
        #: every splice compares worker ``idx`` against
        #: ``resumed_from + len(tokens)``, not ``len(tokens)`` alone.
        self.resumed_from = 0
        self.error: str = ""
        #: sid of the supervisor whose stream fed this request's FIRST
        #: fresh tokens.  With a hedge in flight two supervisors hold the
        #: same request object; whichever feeds first is the winner and
        #: the other arm is cancelled.  Duplicate chunks from the loser
        #: splice to nothing, so the stream stays byte-equal regardless.
        self.served_by = ""
        #: True once a hedge copy of this request was issued (budget
        #: accounting + at-most-one-hedge-per-request).
        self.hedged = False
        #: sid -> monotonic submit time for every supervisor currently
        #: holding this request (a hedge puts TWO arms in flight).  A
        #: terminal (reject, error, done) on one arm consults this to
        #: decide whether another arm still owns the stream — and a
        #: hedge winner's health feed reads its OWN dispatch time here,
        #: not the original submit, so the winner is not charged the
        #: primary's stall.
        self.arms: dict[str, float] = {}
        self.t_submit = time.monotonic()
        self.t_first: float | None = None
        self.t_done: float | None = None
        #: lifecycle checkpoints (monotonic) between submit and first
        #: token: each adjacent pair becomes one tiling waterfall segment
        #: under :attr:`span` at finalize, so the trace store can show
        #: where a request's TTFT went.  Stamped once — a replay or a
        #: re-route re-sends the SAME request object, and re-stamping
        #: would erase the latency the retry actually cost.
        self.t_prefill_done: float | None = None
        self.t_dispatched: float | None = None
        self.t_sent: float | None = None
        #: wall time the engine spent in fused speculative verify steps
        #: on this request's behalf (harness-attributed share, rides the
        #: final token chunk).  Not a checkpoint stamp: it becomes a
        #: ``spec_verify`` waterfall tile carved out of the decode-stream
        #: window at finalize.
        self.spec_verify_s: float | None = None
        #: root span of this request's trace.  Entered at construction
        #: (``activate=False``: feeding happens in callbacks, the ambient
        #: context must not capture it) and closed LAST by
        #: :meth:`_finalize_trace` — the root arriving is what tells the
        #: tail-sampling store the trace is complete.  Because the span
        #: lives on the request, not the session, one trace follows the
        #: stream across reconnect replays, re-routes, and warm handoffs.
        self.span = Span(
            "serve.request",
            {"rid": rid, "tenant": tenant} if tenant else {"rid": rid},
            activate=False,
        ).__enter__()
        self._trace_done = False
        #: set the moment the first fresh tokens (or any terminal) land —
        #: the hedge watcher's TTFT deadline races this event.
        self.first_token = asyncio.Event()
        self._chunks: asyncio.Queue = asyncio.Queue()
        self._done: asyncio.Future = asyncio.get_event_loop().create_future()
        # Unawaited failures must not warn at GC: a caller may only ever
        # consume stream(), or fire-and-forget a best-effort request.
        self._done.add_done_callback(
            lambda f: None if f.cancelled() else f.exception()
        )

    @property
    def done(self) -> bool:
        return self._done.done()

    @property
    def ttft_s(self) -> float | None:
        """Submit -> first streamed token (None until one arrived)."""
        if self.t_first is None:
            return None
        return self.t_first - self.t_submit

    @property
    def latency_s(self) -> float | None:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit

    async def result(self, timeout: float | None = None) -> list[int]:
        """The full token stream (prompt excluded); raises on rejection."""
        return await asyncio.wait_for(asyncio.shield(self._done), timeout)

    async def stream(self) -> AsyncIterator[list[int]]:
        """Yield token chunks in arrival order until the stream closes."""
        while True:
            item = await self._chunks.get()
            if item is None:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    # -- supervisor-side feeding (event-loop context only) -----------------

    def _feed(self, tokens: list[int], done: bool, error: str = "") -> None:
        if self._done.done():
            return
        if tokens:
            if self.t_first is None:
                self.t_first = time.monotonic()
            self.tokens.extend(tokens)
            self._chunks.put_nowait(list(tokens))
            self.first_token.set()
        if done:
            self.t_done = time.monotonic()
            self.error = error
            self._chunks.put_nowait(None)
            self._done.set_result(list(self.tokens))
            self.first_token.set()
            self._finalize_trace()

    def _fail(self, err: BaseException) -> None:
        if self._done.done():
            return
        self.t_done = time.monotonic()
        self._chunks.put_nowait(err)
        self._done.set_exception(err)
        self.first_token.set()
        self.span.record_error(err)
        self._finalize_trace()

    def _finalize_trace(self) -> None:
        """Close this request's trace: turn the monotonic checkpoints
        into tiling segment spans, then end the root.

        Each adjacent checkpoint pair becomes one child span tagged with
        a ``segment`` attribute — the store's waterfall view sums those
        into the per-request latency attribution, and because they tile
        (every segment starts where the previous ended) the sum matches
        the request's end-to-end latency.  Checkpoints a given request
        never hit (no prefill tier, rejected before dispatch) simply
        drop out; the next segment absorbs the span of wall time.  The
        root closes LAST so a trace never finalizes in the store with
        its segments still in flight.
        """
        if self._trace_done:
            return
        self._trace_done = True
        span = self.span
        cursor = self.t_submit
        # The spec_verify tile is synthesized, not stamped: the engine's
        # fused verify passes interleave with streaming, so the harness
        # ships an attributed duration and the tile carves that much out
        # of the FRONT of the decode window.  Clamped to t_done so the
        # tiling sum still equals end-to-end latency exactly.
        t_spec: float | None = None
        if (
            self.spec_verify_s is not None
            and self.t_first is not None
            and self.t_done is not None
        ):
            t_spec = min(self.t_first + self.spec_verify_s, self.t_done)
        tiles: list[tuple[str, float, float]] = []
        for name, stamp in (
            ("prefill", self.t_prefill_done),
            ("route", self.t_dispatched),
            ("dispatch", self.t_sent),
            ("ttft_wait", self.t_first),
            ("spec_verify", t_spec),
            ("decode_stream", self.t_done),
            ("stream_flush", time.monotonic()),
        ):
            if stamp is None:
                continue
            tiles.append((name, cursor, stamp))
            cursor = stamp
        for name, t0, t1 in tiles:
            if t1 <= t0:
                continue
            record_span(
                f"serve.{name}",
                trace_id=span.trace_id,
                parent_id=span.span_id,
                start_ts=span.start_ts + (t0 - self.t_submit),
                duration_s=t1 - t0,
                attributes={"segment": name, "rid": self.rid},
            )
        span.set_attribute("tokens", len(self.tokens))
        if self.ttft_s is not None:
            span.set_attribute("ttft_s", round(self.ttft_s, 6))
        if self.error:
            span.record_error(self.error)
        span.end()


class SessionSupervisor:
    """One resident serving session, supervised for its whole life.

    Owns the session's remote generations (lease, open, watch, reconnect,
    replay, drain-close), the in-flight requests ASSIGNED to it, and the
    per-session accounting (metrics series, fleet capacity pin, the
    executor ``_serve_handles`` registration).  It does NOT decide which
    requests it gets — that is the front-end's job (a handle's trivial
    routing, or a replica set's router).

    ``on_change(supervisor)`` fires on every state transition and request
    completion (a router's pump signal); ``on_failed(supervisor, error)``
    fires when the session dies past its retry budget — a front-end that
    returns ``True`` from it has taken ownership of the in-flight
    requests (via :meth:`detach_requests`) and re-routes them itself;
    otherwise the supervisor fails them with the cause.

    All methods must run on the executor's event loop.
    """

    def __init__(
        self,
        executor: Any,
        *,
        sid: str = "",
        queue_max: int | None = None,
        default_deadline_s: float | None = None,
        stats_interval_s: float | None = None,
        open_timeout_s: float | None = None,
        retries: int | None = None,
        pool: Any = None,
        replica_of: tuple[str, str] | None = None,
        on_change: Callable[["SessionSupervisor"], None] | None = None,
        on_failed: Callable[
            ["SessionSupervisor", BaseException], bool
        ] | None = None,
    ) -> None:
        self.executor = executor
        self.sid = sid or f"serve-{uuid.uuid4().hex[:10]}"
        self.queue_max = int(
            queue_max
            if queue_max is not None
            else _env_number("COVALENT_TPU_SERVE_QUEUE_MAX", 64, int)
        )
        self.default_deadline_s = float(
            default_deadline_s
            if default_deadline_s is not None
            else _env_number("COVALENT_TPU_SERVE_DEADLINE_S", 0.0)
        )
        self.stats_interval_s = float(
            stats_interval_s
            if stats_interval_s is not None
            else _env_number("COVALENT_TPU_SERVE_STATS_INTERVAL_S", 1.0)
        )
        self.open_timeout_s = float(
            open_timeout_s
            if open_timeout_s is not None
            else _env_number("COVALENT_TPU_SERVE_OPEN_TIMEOUT_S", 120.0)
        )
        self.retries = int(
            retries
            if retries is not None
            else _env_number("COVALENT_TPU_SERVE_RETRIES", 2, int)
        )
        self._pool = pool
        #: (set name, replica id) when owned by a ReplicaSet — keys the
        #: per-replica metric series; None for a standalone handle.
        self.replica_of = replica_of
        self._on_change = on_change
        self._on_failed = on_failed
        self.slots = 0
        self.generation = 0
        self.served = 0
        self.reconnects = 0
        #: warm handoffs completed (drain-and-reopen before gang death).
        self.handoffs = 0
        self._gen_counter = 0
        self._in_handoff = False
        self._handoff_task: asyncio.Task | None = None
        #: a worker preemption notice (serve.preempt on the side-band)
        #: auto-triggers a warm handoff; COVALENT_TPU_SERVE_HANDOFF=0
        #: disables and leaves recovery to the reconnect path.
        self._auto_handoff = str(
            os.environ.get("COVALENT_TPU_SERVE_HANDOFF", "1")
        ).strip().lower() not in ("0", "off", "false", "no")
        self.opened_at = 0.0
        self.stats: dict[str, Any] = {}
        self.address = ""
        self._payload: bytes | None = None
        self._digest = ""
        self._local_payload = ""
        self._client: AgentClient | None = None
        self._conns: list = []
        self._sid_g = ""
        self._requests: dict[str, ServeRequest] = {}
        #: name -> adapter record ({digest, content, path, ...}) for every
        #: adapter attached to THIS session, in attach order — the replay
        #: set a reconnect/handoff re-splices into the fresh generation.
        self._adapters: dict[str, dict] = {}
        #: (session, adapter) metric series this supervisor created; the
        #: adapter label set is OPEN (operators name adapters), so the
        #: stale-series reap in :meth:`_drop_live` replays exactly this
        #: set instead of enumerating.
        self._adapter_series: set[str] = set()
        self._closed = False
        self._failed: BaseException | None = None
        self._ready = asyncio.Event()
        self._supervisor: asyncio.Task | None = None
        self._counted_live = False
        #: fire-and-forget wire tasks (hedge loser cancels) held here so
        #: they are not collected mid-await.
        self._bg_tasks: set = set()

    # -- identity / views ---------------------------------------------------

    @property
    def _health_group(self) -> str:
        """Peer group for differential health scoring: the replica set
        name when owned by one (peers = sibling replicas), else ''."""
        return self.replica_of[0] if self.replica_of is not None else ""

    @property
    def state(self) -> str:
        if self._failed is not None:
            return "failed"
        if self._closed:
            return "closed"
        if not self._ready.is_set():
            return "reconnecting"
        return "open"

    @property
    def in_flight(self) -> int:
        return len(self._requests)

    @property
    def routable(self) -> bool:
        """Whether a router may assign NEW requests here right now."""
        return self.state == "open"

    @property
    def alive(self) -> bool:
        """Open or recovering — a sticky pin to this session still holds."""
        return self.state in ("open", "reconnecting")

    def status(self) -> dict[str, Any]:
        """This session's contribution to ``/status`` / ``pool.status()``."""
        view: dict[str, Any] = {
            "state": self.state,
            "address": self.address,
            "slots": self.slots,
            "generation": self.generation,
            "served": self.served,
            "in_flight": self.in_flight,
            "reconnects": self.reconnects,
            "handoffs": self.handoffs,
            "age_s": (
                round(time.time() - self.opened_at, 3) if self.opened_at else 0
            ),
        }
        if self.replica_of is not None:
            view["replica_set"] = self.replica_of[0]
            view["replica"] = self.replica_of[1]
        if self._adapters:
            view["adapters"] = self.adapters
        view["health_score"] = HEALTH.score(self.sid)
        view["health_state"] = HEALTH.state(self.sid)
        for field in ("busy", "queued", "tokens_per_s", "tokens_total"):
            if field in self.stats:
                view[field] = self.stats[field]
        return view

    def _changed(self) -> None:
        if self._on_change is not None:
            try:
                self._on_change(self)
            except Exception:  # noqa: BLE001 - router hooks never fatal
                app_log.exception("serve on_change hook failed")

    # -- open ---------------------------------------------------------------

    async def open(
        self, payload: bytes, digest: str = ""
    ) -> "SessionSupervisor":
        """First open: stage the factory payload, lease a gang, supervise.

        ``payload`` is the cloudpickled factory; ``digest`` (its sha256)
        may be precomputed by a replica set staging the same bytes N
        times.
        """
        self._payload = payload
        self._digest = digest or bytes_digest(payload)
        self._local_payload = os.path.join(
            self.executor.cache_dir, f"serve_{self._digest}.pkl"
        )
        await asyncio.to_thread(
            self._write_payload, self._local_payload, self._payload
        )
        await self._open_generation()
        self.opened_at = time.time()
        handles = getattr(self.executor, "_serve_handles", None)
        if handles is not None:
            handles[self.sid] = self
        if self._pool is not None:
            # A session IS long-lived load: pin one capacity slot so the
            # fleet scheduler bin-packs electrons around it, not into it.
            self._pool.place()
        SERVE_SESSIONS.inc()
        self._counted_live = True
        if self.replica_of is not None:
            SERVE_REPLICA_IN_FLIGHT.labels(
                set=self.replica_of[0], replica=self.replica_of[1]
            ).set(0)
        self._supervisor = asyncio.ensure_future(self._supervise())
        self._ready.set()
        obs_events.emit(
            "serve.session_opened",
            sid=self.sid,
            address=self.address,
            slots=self.slots,
        )
        return self

    async def adopt(
        self,
        *,
        client: AgentClient,
        conns: list,
        address: str,
        sid_g: str,
        slots: int = 1,
        digest: str = "",
        payload_path: str = "",
    ) -> "SessionSupervisor":
        """Bind to a SURVIVING remote session instead of opening one.

        The crash-recovery path: the worker held this session through
        the dispatcher's death (orphan mode) and a successor dispatcher
        re-attached its channel; the supervisor adopts the existing
        ``sid_g`` — no lease, no staging, no ``serve_open`` — and the
        usual supervision (reconnect, replay, stats, close) takes over
        from there.  Journaled in-flight streams are re-attached one by
        one via :meth:`resume_stream`.
        """
        self._digest = digest
        self._local_payload = payload_path
        self._client = client
        self._conns = list(conns)
        self._sid_g = sid_g
        self.address = address
        self.slots = int(slots or 1)
        self.generation = 1
        # Future reconnects mint fresh generation sids AFTER the adopted
        # one: "serve-x.g2" resumes counting at 3, not at a collision.
        tail = sid_g.rsplit(".g", 1)
        try:
            self._gen_counter = int(tail[1]) + 1 if len(tail) == 2 else 1
        except ValueError:
            self._gen_counter = 1
        client.watch_serve(sid_g, self._sink)
        self.opened_at = time.time()
        handles = getattr(self.executor, "_serve_handles", None)
        if handles is not None:
            handles[self.sid] = self
        if self._pool is not None:
            self._pool.place()
        SERVE_SESSIONS.inc()
        self._counted_live = True
        if self.replica_of is not None:
            SERVE_REPLICA_IN_FLIGHT.labels(
                set=self.replica_of[0], replica=self.replica_of[1]
            ).set(0)
        self._journal_binding()
        # A re-adopted session starts at a NEUTRAL health score: the
        # journal deliberately does not persist pre-crash scores, and a
        # recovered fleet must never inherit a stale quarantine from its
        # predecessor's (possibly fault-storm-polluted) view.
        HEALTH.neutral(self.sid, group=self._health_group)
        self._supervisor = asyncio.ensure_future(self._supervise())
        self._ready.set()
        obs_events.emit(
            "serve.session_adopted",
            sid=self.sid,
            address=self.address,
            sid_g=sid_g,
            slots=self.slots,
        )
        return self

    async def resume_stream(self, request: ServeRequest) -> str:
        """Re-attach one journaled in-flight stream to this session.

        ``request.resumed_from`` holds the journal's token high-water
        mark; the worker re-emits its history from that offset (the
        splice in :meth:`_on_token` guards the overlap) and live chunks
        follow.  Returns the worker's resume state — a stream the worker
        never saw (``unknown``: it died in the dead pipe between journal
        and wire) is re-sent in full from the journaled prompt.
        """
        if self._client is None:
            raise ServeError(f"session {self.sid} has no live runtime")
        request.span.set_attribute("sid", self.sid)
        # Register BEFORE the wire write: re-emitted history races the
        # resume ack on the side-band.
        self._requests[request.rid] = request
        self._publish_in_flight()
        try:
            ack = await self._client.serve_resume(
                self._sid_g, request.rid, request.resumed_from
            )
        except BaseException:
            self._requests.pop(request.rid, None)
            self._publish_in_flight()
            raise
        state = str(ack.get("state") or "")
        if state == "refused":
            self._finish(request.rid, "error")
            request._fail(ServeError(
                f"resume of {request.rid} refused: worker fenced this "
                "dispatcher as stale"
            ))
        elif state == "unknown":
            # The prior dispatcher journaled the intent but died before
            # (or during) the wire write: send it as a fresh stream.
            request.resumed_from = 0
            await self._send_request(request)
        return state

    @staticmethod
    def _write_payload(path: str, payload: bytes) -> None:
        if os.path.exists(path):
            return
        tmp = f"{path}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)

    async def _open_generation(self) -> None:
        """Open one remote session generation on a freshly leased gang.

        Failures discard whatever channels the attempt dialed (the
        ``lease_gang(dialed=)`` contract): a pre-flight or ``serve_open``
        refusal would otherwise leave the just-proved-broken transports
        pooled, and every reconnect retry would silently reuse them.
        """
        self._adopt(await self._dial_generation())

    async def _dial_generation(self) -> dict:
        """Dial + open one fresh session generation WITHOUT touching the
        current binding; returns it for :meth:`_adopt`.

        The split is what makes the warm handoff possible: the old
        generation keeps streaming while the replacement leases, stages,
        and opens — the swap at adopt time is a few field writes.
        """
        dialed: list = []
        try:
            return await self._dial_generation_on(dialed)
        except BaseException:
            if dialed:
                try:
                    await self.executor._discard_workers(dialed)
                except Exception:  # noqa: BLE001 - teardown is best-effort
                    pass
            raise

    def _adopt(self, binding: dict) -> None:
        self._client = binding["client"]
        self._conns = binding["conns"]
        self._sid_g = binding["sid_g"]
        self.address = binding["address"]
        self.slots = binding["slots"]
        self.generation += 1
        self._journal_binding()

    def _journal_binding(self) -> None:
        """Journal this session's current remote binding — everything a
        successor dispatcher needs to find (or re-open) the session."""
        journal_mod.record(
            "session", sid=self.sid, sid_g=self._sid_g,
            address=self.address, digest=self._digest,
            payload=self._local_payload, slots=self.slots,
            queue_max=self.queue_max,
            default_deadline_s=self.default_deadline_s,
            stats_interval_s=self.stats_interval_s,
            replica_of=list(self.replica_of) if self.replica_of else None,
            sync=True,
        )

    async def _dial_generation_on(self, dialed: list) -> dict:
        executor = self.executor
        lease = await executor.lease_gang(dialed=dialed)
        conns, addresses = lease.conns, lease.addresses
        if len(conns) != 1:
            raise ServeError(
                f"serving sessions target single-worker gangs, got "
                f"{len(conns)} workers (shard inside the engine instead)"
            )
        conn, address = conns[0], addresses[0]
        client = executor._agents.get(conn.address)
        if client is None or not client.alive:
            raise AgentError(
                f"no resident agent runtime on {address} "
                "(serving needs use_agent enabled)"
            )
        key = executor._pool_key(address)
        remote = cas_path(executor.remote_cache, self._digest, ".pkl")
        codec = executor._codec_for(key, conn)
        await executor._cas.ensure_probed(
            key, conn, [(self._digest, remote)]
        )
        await executor._cas.ensure(
            key, conn, self._digest, self._local_payload, remote,
            codec=codec, python_path=executor.python_path,
        )
        runner = None
        if client.mode != "pool":
            # The native C++ agent only switches lines: it forks this
            # runner argv to host the session (stdin pipe held open).
            from .. import harness as harness_module

            remote_harness = f"{executor.remote_cache}/{HARNESS_BASENAME}"
            await conn.put(harness_module.__file__, remote_harness)
            runner = [
                executor.python_path, remote_harness, "--serve-child",
            ]
        sid_g = f"{self.sid}.g{self._gen_counter}"
        self._gen_counter += 1
        spec: dict[str, Any] = {"operation_id": sid_g}
        if executor.task_env:
            spec["env"] = dict(executor.task_env)
        client.watch_serve(sid_g, self._sink)
        try:
            opened = await client.serve_open(
                sid_g,
                self._digest,
                remote,
                options={
                    "queue_max": self.queue_max,
                    "default_deadline_s": self.default_deadline_s,
                    "stats_interval_s": self.stats_interval_s,
                },
                spec=spec,
                runner=runner,
                timeout=self.open_timeout_s,
            )
        except BaseException:
            client.unwatch_serve(sid_g)
            raise
        return {
            "client": client,
            "conns": list(conns),
            "sid_g": sid_g,
            "address": address,
            "slots": int(opened.get("slots") or 1),
        }

    # -- requests -----------------------------------------------------------

    async def submit(
        self,
        request: ServeRequest,
        *,
        fail_on_error: bool = True,
        wait_ready: bool = True,
    ) -> ServeRequest:
        """Assign one request to this session and write its wire line.

        Fire-and-stream: tokens arrive on the side-band.  Raises when
        the write cannot be made (waiting out an in-progress reconnect
        first by default); ``wait_ready=False`` refuses a non-routable
        session IMMEDIATELY instead — a router must not head-of-line
        block a whole assignment batch behind one replica's reconnect
        when survivors are idle.  ``fail_on_error=False`` leaves the
        request itself unfailed so that router can re-route it instead
        of surfacing the error to the caller.
        """
        try:
            if wait_ready:
                await self._await_ready()
            elif not self.routable:
                raise ServeError(
                    f"session {self.sid} is not routable ({self.state})"
                )
            if request.t_dispatched is None:
                request.t_dispatched = time.monotonic()
            request.span.set_attribute("sid", self.sid)
            self._requests[request.rid] = request
            request.arms[self.sid] = time.monotonic()
            self._publish_in_flight()
            # Write-ahead: the intent is durable BEFORE the wire write,
            # so a dispatcher crash between the two replays the request
            # rather than losing it.
            journal_mod.record(
                "stream", sid=self.sid, rid=request.rid,
                prompt=list(request.prompt), params=request.params,
                deadline_s=request.deadline_s, tenant=request.tenant,
                resumed_from=request.resumed_from,
            )
            try:
                await self._send_request(request)
            except BaseException:
                self._requests.pop(request.rid, None)
                request.arms.pop(self.sid, None)
                self._publish_in_flight()
                raise
        except BaseException as err:
            if fail_on_error:
                SERVE_REQUESTS_TOTAL.labels(outcome="error").inc()
                request._fail(
                    err
                    if isinstance(err, ServeError)
                    else ServeError(f"request submit failed: {err!r}")
                )
            raise
        if self.replica_of is not None:
            SERVE_REPLICA_REQUESTS_TOTAL.labels(
                set=self.replica_of[0], replica=self.replica_of[1]
            ).inc()
        return request

    def detach_requests(self) -> list[ServeRequest]:
        """Hand every in-flight request back WITHOUT failing or counting
        it — the drain-on-death path: a replica set re-routes these onto
        surviving sessions, and the requests' own token high-water marks
        keep the splice exactly-once across the move."""
        detached = list(self._requests.values())
        self._requests.clear()
        for request in detached:
            request.arms.pop(self.sid, None)
        self._publish_in_flight()
        return detached

    async def _send_request(self, request: ServeRequest) -> None:
        assert self._client is not None
        t_send = time.monotonic()
        kv_bytes: bytes | None = None
        kv_digest = ""
        kv_path = ""
        if request.kv is not None:
            kv_bytes, kv_digest = request.kv
            if not self._client.frames_active:
                # Cross-pool road: a JSONL channel would pay ~33% base64
                # inflation per send (and per replay), so the bundle
                # ships ONCE into the worker's remote CAS — digest-named,
                # single-flighted, deduped across identical prompts —
                # and the request references it by path.  Any staging
                # failure just drops the KV: the worker's full-prefill
                # fallback owns correctness.
                try:
                    kv_path = await self._stage_kv(kv_bytes, kv_digest)
                    kv_bytes = None
                except Exception as err:  # noqa: BLE001 - degrade
                    app_log.debug(
                        "KV staging for %s failed (%s); degrading to "
                        "full prefill", request.rid, err,
                    )
                    kv_bytes, kv_digest = None, ""
        await self._client.serve_request(
            self._sid_g,
            request.rid,
            request.prompt,
            params=request.params,
            deadline_s=request.deadline_s,
            tenant=request.tenant,
            kv_bytes=kv_bytes,
            kv_digest=kv_digest,
            kv_path=kv_path,
            trace=context_of(request.span, rid=request.rid),
        )
        now = time.monotonic()
        if request.kv is not None:
            # The KV data plane is its own waterfall row: shipping a
            # multi-megabyte bundle (CAS stage or inline frame body) is
            # exactly the cost disaggregation trades for prefill reuse,
            # and it must be attributable per request.
            record_span(
                "serve.kv_ship",
                trace_id=request.span.trace_id,
                parent_id=request.span.span_id,
                start_ts=request.span.start_ts + (t_send - request.t_submit),
                duration_s=now - t_send,
                attributes={
                    "rid": request.rid,
                    "kv_bytes": len(request.kv[0]),
                    "staged": bool(kv_path),
                },
            )
        if request.t_sent is None:
            request.t_sent = now

    async def _stage_kv(self, data: bytes, digest: str) -> str:
        """Ship one KV bundle into this session's worker CAS; returns the
        remote path.  Content-addressed: a repeated prompt's identical
        bundle is a present-set hit, zero wire bytes."""
        executor = self.executor
        local = os.path.join(
            executor.cache_dir, "cas", f"{digest}.kv"
        )
        if not os.path.exists(local):
            os.makedirs(os.path.dirname(local), exist_ok=True)
            await asyncio.to_thread(self._write_payload, local, data)
        conn = self._conns[0]
        key = executor._pool_key(self.address)
        remote = cas_path(executor.remote_cache, digest, ".kv")
        await executor._cas.ensure(
            key, conn, digest, local, remote,
            codec=executor._codec_for(key, conn),
            python_path=executor.python_path,
        )
        return remote

    async def prefill_kv(
        self,
        prompt,
        params: dict | None = None,
        rid: str = "",
        timeout_s: float = 60.0,
        trace: dict | None = None,
    ) -> dict:
        """Run a prefill-only pass on this session's resident engine and
        return the ``serve_kv`` event (bundle under ``data_bytes``,
        worker-announced sha256 under ``digest``).

        The disaggregated front calls this on a prefill-tier replica;
        the caller owns digest verification of the received bytes and
        the degrade-to-full-prefill decision on any failure.
        """
        await self._await_ready()
        client = self._client
        if client is None:
            raise ServeError(f"session {self.sid} has no live runtime")
        rid = rid or f"kv-{uuid.uuid4().hex[:8]}"
        return await client.serve_prefill(
            self._sid_g, rid, [int(t) for t in prompt],
            params=params, timeout=timeout_s, trace=trace,
        )

    # -- multi-adapter registry (live attach / detach / replay) --------------

    def _adapter_registry(self):
        """The executor-scoped adapter book (built through the
        executor's accessor when it has one, so every session on one
        executor shares one registry; stub executors in tests get a
        lazily attached instance)."""
        accessor = getattr(self.executor, "adapter_registry", None)
        if callable(accessor):
            return accessor()
        registry = getattr(self.executor, "_adapter_registry", None)
        if registry is None:
            from .registry import AdapterRegistry

            registry = AdapterRegistry(self.executor.cache_dir)
            self.executor._adapter_registry = registry
        return registry

    @property
    def adapters(self) -> dict[str, str]:
        """name -> content digest of every adapter attached here."""
        return {
            name: str(record.get("content") or "")
            for name, record in self._adapters.items()
        }

    async def attach_adapter(
        self,
        name: str,
        payload: Any = None,
        *,
        path: str = "",
        digest: str = "",
        rank: int | None = None,
        alpha: float = 16.0,
        timeout_s: float | None = None,
    ) -> dict:
        """Splice a named LoRA adapter into this RUNNING session.

        Three sources, first match wins: ``payload`` (bundle bytes, a
        bundle dict, or an ordered leaf list — packed and registered
        here), ``path`` (a packed bundle file, e.g. a journaled CAS
        path; ``digest`` cross-checks it when given), or the executor's
        adapter registry by ``name``.  The bundle ships into the
        worker's CAS sha256-verified, the engine splices it in between
        decode waves (a re-attach of an existing name is a hot swap:
        in-flight requests finish on the old generation), and the
        attachment is journaled sync so a successor dispatcher
        re-attaches it after a crash.  Returns the worker's ack
        (content ``digest``, ``attach_s``).
        """
        await self._await_ready()
        client = self._client
        if client is None:
            raise ServeError(f"session {self.sid} has no live runtime")
        t0 = time.monotonic()
        timeout = float(
            timeout_s
            if timeout_s is not None
            else _env_number("COVALENT_TPU_SERVE_ATTACH_TIMEOUT_S", 60.0)
        )
        registry = self._adapter_registry()
        if payload is not None:
            record = await asyncio.to_thread(
                registry.put, name, payload, rank, alpha
            )
        elif path:
            data = await asyncio.to_thread(self._read_payload, path)
            record = await asyncio.to_thread(registry.put, name, data)
            if digest and record["digest"] != digest:
                SERVE_ADAPTER_ATTACHES_TOTAL.labels(
                    op="attach", outcome="digest_mismatch"
                ).inc()
                raise ServeError(
                    f"adapter {name!r} bundle at {path} hashes to "
                    f"{record['digest'][:12]}, journal says {digest[:12]} "
                    "(torn or tampered artifact)"
                )
        else:
            record = registry.get(name)
            if record is None:
                raise ServeError(
                    f"no adapter {name!r} in the registry (register it, "
                    "or pass payload=/path=)"
                )
        try:
            remote = await self._stage_adapter(record)
            ack = await client.serve_attach(
                self._sid_g, name, record["digest"], remote,
                timeout=timeout,
            )
        except BaseException as err:
            SERVE_ADAPTER_ATTACHES_TOTAL.labels(
                op="attach", outcome="error"
            ).inc()
            obs_events.emit(
                "serve.adapter_attach_failed",
                sid=self.sid, adapter=str(name), error=repr(err),
            )
            raise self._adapter_refusal(err, "attach", str(name))
        elapsed = time.monotonic() - t0
        record = dict(record)
        record["content"] = str(
            ack.get("digest") or record.get("content") or ""
        )
        self._adapters[str(name)] = record
        SERVE_ADAPTER_ATTACHES_TOTAL.labels(op="attach", outcome="ok").inc()
        SERVE_ADAPTER_ATTACH_SECONDS.observe(elapsed)
        SERVE_ADAPTERS.labels(session=self.sid).set(
            float(len(self._adapters))
        )
        journal_mod.record(
            "session_adapter", sid=self.sid, adapter=str(name),
            digest=record["digest"], path=record["path"],
            content=record["content"], sync=True,
        )
        obs_events.emit(
            "serve.adapter_attached",
            sid=self.sid, adapter=str(name),
            digest=record["content"], attach_s=round(elapsed, 4),
        )
        self._changed()
        return ack

    async def detach_adapter(
        self, name: str, timeout_s: float = 30.0
    ) -> dict:
        """Remove a named adapter from the running session; its decode
        slot frees once requests pinned to it drain.  Journaled sync so
        recovery does not resurrect the detached name."""
        await self._await_ready()
        client = self._client
        if client is None:
            raise ServeError(f"session {self.sid} has no live runtime")
        try:
            ack = await client.serve_detach(
                self._sid_g, name, timeout=timeout_s
            )
        except BaseException as err:
            SERVE_ADAPTER_ATTACHES_TOTAL.labels(
                op="detach", outcome="error"
            ).inc()
            raise self._adapter_refusal(err, "detach", str(name))
        self._adapters.pop(str(name), None)
        SERVE_ADAPTER_ATTACHES_TOTAL.labels(op="detach", outcome="ok").inc()
        SERVE_ADAPTERS.labels(session=self.sid).set(
            float(len(self._adapters))
        )
        journal_mod.record(
            "session_adapter", sid=self.sid, adapter=str(name),
            detached=True, sync=True,
        )
        obs_events.emit(
            "serve.adapter_detached", sid=self.sid, adapter=str(name),
        )
        self._changed()
        return ack

    def _adapter_refusal(
        self, err: BaseException, op: str, name: str
    ) -> BaseException:
        """A classified worker refusal (it carries a ``fault_label``)
        becomes a :class:`ServeError` with the SAME duck tags, so
        callers catch the serving tier's exception while
        ``classify_error`` still sees the worker's permanence verdict.
        Channel faults and cancellations pass through untouched — the
        reconnect machinery owns those."""
        label = str(getattr(err, "fault_label", "") or "")
        if not label:
            return err
        wrapped = ServeError(
            f"{op} of adapter {name!r} on {self.sid} refused: {err}"
        )
        wrapped.fault_label = label
        wrapped.fault_transient = bool(
            getattr(err, "fault_transient", True)
        )
        wrapped.__cause__ = err
        return wrapped

    def note_adapter(
        self, name: str, *, digest: str, path: str, content: str = ""
    ) -> None:
        """Record an adapter that is ALREADY resident in the remote
        engine (crash recovery: the worker held it through the
        dispatcher's death) without re-shipping anything."""
        self._adapters[str(name)] = {
            "name": str(name), "digest": str(digest),
            "path": str(path), "content": str(content),
        }
        SERVE_ADAPTERS.labels(session=self.sid).set(
            float(len(self._adapters))
        )
        journal_mod.record(
            "session_adapter", sid=self.sid, adapter=str(name),
            digest=str(digest), path=str(path), content=str(content),
            sync=True,
        )

    async def _stage_adapter(self, record: dict) -> str:
        """Ship one packed bundle into this generation's worker CAS;
        returns the remote path (digest-named, single-flighted — a
        replay after reconnect onto the same worker is a present-set
        hit, zero wire bytes)."""
        executor = self.executor
        conn = self._conns[0]
        key = executor._pool_key(self.address)
        digest = str(record["digest"])
        remote = cas_path(executor.remote_cache, digest, ".lora")
        await executor._cas.ensure(
            key, conn, digest, str(record["path"]), remote,
            codec=executor._codec_for(key, conn),
            python_path=executor.python_path,
        )
        return remote

    async def _replay_adapters(self) -> None:
        """Re-splice every attached adapter into a FRESH generation
        (reconnect / warm handoff): the new engine starts with an empty
        bank, and a request naming an un-replayed adapter would refuse.
        Per-adapter degrade: one failed replay logs and keeps going —
        the other adapters (and the base lane) must not die with it.
        """
        client = self._client
        if client is None or not self._adapters:
            return
        for name, record in list(self._adapters.items()):
            try:
                remote = await self._stage_adapter(record)
                await client.serve_attach(
                    self._sid_g, name, str(record["digest"]), remote,
                    timeout=_env_number(
                        "COVALENT_TPU_SERVE_ATTACH_TIMEOUT_S", 60.0
                    ),
                )
            except asyncio.CancelledError:
                raise
            except BaseException as err:  # noqa: BLE001 - degrade per name
                app_log.warning(
                    "adapter %r replay onto %s generation %d failed: %r",
                    name, self.sid, self.generation, err,
                )
                obs_events.emit(
                    "serve.adapter_replay_failed",
                    sid=self.sid, adapter=str(name), error=repr(err),
                )

    @staticmethod
    def _read_payload(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    async def _await_ready(self) -> None:
        if self._closed:
            raise ServeError(f"session {self.sid} is closed")
        while not self._ready.is_set():
            await self._ready.wait()
        if self._failed is not None:
            raise ServeError(
                f"session {self.sid} failed: {self._failed}"
            ) from self._failed
        if self._closed:
            raise ServeError(f"session {self.sid} is closed")

    def _publish_in_flight(self) -> None:
        if self.replica_of is not None:
            SERVE_REPLICA_IN_FLIGHT.labels(
                set=self.replica_of[0], replica=self.replica_of[1]
            ).set(float(len(self._requests)))

    # -- side-band routing --------------------------------------------------

    def _sink(self, _sid: str, data: dict) -> None:
        """One telemetry record for this session (event-loop context)."""
        kind = data.get("type")
        if kind == "serve.token":
            self._on_token(data)
        elif kind == "serve.reject":
            self._on_reject(data)
        elif kind == "serve.stats":
            self._on_stats(data)
        elif kind == "serve.preempt":
            self._on_preempt(data)
        elif kind == "span":
            self._on_remote_span(data)

    def _on_remote_span(self, data: dict) -> None:
        """One worker-recorded span off the telemetry side-band.

        The worker has no event sink of ours, so it times its segments
        (queue wait, admission, decode, prefill) locally and ships them
        as ``span`` telemetry records; re-emitting through
        :func:`record_span` with the ORIGINAL ids preserved is what
        makes worker time appear inside the request's own waterfall
        rather than in a disconnected worker-local trace.
        """
        try:
            record_span(
                str(data.get("name") or "serve.worker"),
                trace_id=data.get("trace_id") or None,
                parent_id=data.get("parent_id") or None,
                span_id=data.get("span_id") or None,
                start_ts=data.get("start_ts"),
                duration_s=float(data.get("duration_s") or 0.0),
                status=str(data.get("status") or "OK"),
                attributes=(
                    data.get("attributes")
                    if isinstance(data.get("attributes"), dict)
                    else None
                ),
            )
        except Exception:  # noqa: BLE001 - observability never fatal
            pass

    def _on_preempt(self, data: dict) -> None:
        """The worker hosting this session announced a preemption notice
        (SIGTERM): start the warm handoff NOW, while the old runtime is
        still serving inside its grace window."""
        obs_events.emit(
            "serve.preempt_notice",
            sid=self.sid,
            address=self.address,
            reason=str(data.get("reason") or ""),
        )
        if not self._auto_handoff or self._closed or self._in_handoff:
            return

        async def _run() -> None:
            try:
                await self.handoff(reason="preempt_notice")
            except Exception:  # noqa: BLE001 - reconnect path still guards
                app_log.exception(
                    "preemption-notice handoff for %s failed", self.sid
                )

        # Hold the reference: an unreferenced task can be collected
        # mid-await, silently dropping the handoff.
        self._handoff_task = asyncio.ensure_future(_run())
        self._handoff_task.add_done_callback(
            lambda _t: setattr(self, "_handoff_task", None)
        )

    def _on_token(self, data: dict) -> None:
        rid = str(data.get("rid") or "")
        request = self._requests.get(rid)
        if request is None:
            return
        idx = int(data.get("idx") or 0)
        tokens = list(data.get("tokens") or ())
        have = request.resumed_from + len(request.tokens)
        if idx > have:
            # A chunk went missing (idx jumped past our high-water mark):
            # the exactly-once contract is broken for this stream, fail
            # it loudly rather than splice around a hole.
            self._finish(rid, "error")
            request._fail(ServeError(
                f"token stream gap for {rid}: chunk starts at {idx}, "
                f"have {have}"
            ))
            return
        # Replay splice: after a reconnect (or a re-route onto another
        # replica) the fresh session re-streams from idx 0; everything
        # at-or-below our high-water mark is a duplicate and drops here,
        # so callers see each token exactly once.
        fresh = tokens[have - idx:] if idx < have else tokens
        first = request.t_first is None and bool(fresh)
        if first and not request.served_by:
            # Hedge arbitration: the FIRST arm to feed fresh tokens wins
            # the request; the replica set cancels the other arm.
            request.served_by = self.sid
        done = bool(data.get("done"))
        error = str(data.get("error") or "")
        hedge_loser = bool(
            request.hedged
            and request.served_by
            and request.served_by != self.sid
        )
        if hedge_loser and error:
            # A terminal error on the hedge-losing arm — the cancel ack,
            # or the loser dying mid-drain — must never fail (or even
            # reach) the SHARED request: the winning stream owns the
            # request's terminal record; this arm only releases its claim.
            self.abandon(rid)
            return
        spec_s = data.get("spec_verify_s")
        if spec_s is not None:
            # Rides the final chunk from a speculative engine's harness;
            # captured BEFORE _feed so _finalize_trace (which _feed calls
            # on done) sees it and tiles the spec_verify segment.
            request.spec_verify_s = float(spec_s)
        request._feed(fresh, done, error=error)
        if fresh:
            SERVE_TOKENS_TOTAL.inc(len(fresh))
            # The stream's durable high-water mark: a successor
            # dispatcher resumes the stream from here exactly-once.
            journal_mod.record(
                "stream_hwm", sid=self.sid, rid=rid,
                hwm=request.resumed_from + len(request.tokens),
            )
        # The trace id rides as the bucket exemplar: a p99 spike on the
        # serving dashboards resolves straight to this request's
        # waterfall at /traces/<id>.
        if first and request.ttft_s is not None:
            SERVE_TTFT_SECONDS.observe(
                request.ttft_s, trace_id=request.span.trace_id
            )
            # Differential health feed: TTFT vs sibling replicas is the
            # straggler signal a binary breaker never sees.  For a hedged
            # request this arm's latency is measured from its OWN
            # dispatch: the caller-visible ttft_s includes the primary's
            # stall plus the hedge threshold wait, and charging that to
            # the healthy winner would pollute the very differential
            # signal that routed around the straggler.
            arm_lat = request.ttft_s
            if request.hedged:
                sent = request.arms.get(self.sid)
                if sent is not None and request.t_first is not None:
                    arm_lat = max(0.0, request.t_first - sent)
            HEALTH.record_latency(
                self.sid, arm_lat, group=self._health_group
            )
        if done:
            if hedge_loser:
                # The losing arm completed normally before its cancel
                # drained: its chunks already spliced as duplicates and
                # request._feed ignored the second done — but the outcome
                # accounting (request counters, latency histogram, health
                # credit) belongs to the winner alone.  Release the claim
                # without counting anything.
                self.abandon(rid)
                return
            outcome = "ok"
            if error == "deadline_exceeded":
                outcome = "deadline"
            elif error:
                outcome = "error"
            if outcome == "ok":
                HEALTH.record_success(self.sid, group=self._health_group)
            elif outcome == "error":
                HEALTH.record_fault(
                    self.sid, label=error[:40], group=self._health_group
                )
            self._finish(rid, outcome)
            if request.latency_s is not None:
                SERVE_REQUEST_SECONDS.observe(
                    request.latency_s, trace_id=request.span.trace_id
                )

    def _on_reject(self, data: dict) -> None:
        rid = str(data.get("rid") or "")
        request = self._requests.get(rid)
        if request is None:
            return
        code = str(data.get("code") or "rejected")
        if code == "unknown_session" and not self._ready.is_set():
            # Raced a dying generation; the reconnect replay will re-send
            # this request on the fresh session.
            return
        HEALTH.record_fault(self.sid, label=code, group=self._health_group)
        if request.hedged and request.served_by != self.sid and (
            request.served_by or request.arms.keys() - {self.sid}
        ):
            # Hedge guard: a wire-level reject of one arm (e.g. the
            # speculative copy shed under the same load that triggered
            # the hedge) must not fail the SHARED request while the other
            # arm still holds it — that arm owns the terminal.  The
            # reject was still a real fault for THIS replica (recorded
            # above); only the request survives it.
            self.abandon(rid)
            return
        self._finish(
            rid, "shed" if code == "serve_admission_shed" else "rejected"
        )
        request._fail(ServeRequestRejected(
            rid, code, str(data.get("message") or "")
        ))

    def _on_stats(self, data: dict) -> None:
        self.stats = {
            k: v for k, v in data.items()
            if k in (
                "slots", "busy", "queued", "served",
                "tokens_total", "tokens_per_s",
                "prefix_hits", "prefix_misses", "prefill_positions",
                "prefix_evictions", "kv_admits", "kv_fallbacks",
                "kv_exports", "prefills",
                "spec_rounds", "spec_proposed", "spec_accepted",
                "spec_refusals", "spec_accept_rate", "mode_refusals",
            )
            # Per-lane token counters arrive as one key per configured
            # mode (and one per attached adapter); pass the families
            # through rather than enumerating them.
            or k.startswith("mode_tokens_")
            or k.startswith("adapter_")
        }
        SERVE_QUEUE_DEPTH.labels(session=self.sid).set(
            float(self.stats.get("queued") or 0)
        )
        HEALTH.record_queue_depth(
            self.sid, float(self.stats.get("queued") or 0),
            group=self._health_group,
        )
        SERVE_TOKENS_PER_S.labels(session=self.sid).set(
            float(self.stats.get("tokens_per_s") or 0.0)
        )
        # Engine prefix counters ride the same stats record; only engines
        # that report them (ContinuousEngine) create the series, so stub
        # engines leave no dead zero gauges behind.
        for key, gauge in (
            ("prefix_hits", SERVE_PREFIX_HITS),
            ("prefix_misses", SERVE_PREFIX_MISSES),
            ("prefill_positions", SERVE_PREFILL_POSITIONS),
        ):
            if key in self.stats:
                gauge.labels(session=self.sid).set(
                    float(self.stats[key] or 0)
                )
        # Speculative / lane-mode series: again only engines that report
        # them create the series (stale-series reap in _drop_live must
        # enumerate modes, which is fine — the mode set is closed).
        if "spec_accept_rate" in self.stats:
            SERVE_SPEC_ACCEPT_RATE.labels(session=self.sid).set(
                float(self.stats["spec_accept_rate"] or 0.0)
            )
        for key, value in self.stats.items():
            if key.startswith("mode_tokens_"):
                SERVE_MODE_TOKENS.labels(
                    session=self.sid, mode=key[len("mode_tokens_"):]
                ).set(float(value or 0))
            elif key.startswith("adapter_tokens_"):
                adapter = key[len("adapter_tokens_"):]
                self._adapter_series.add(adapter)
                SERVE_ADAPTER_TOKENS.labels(
                    session=self.sid, adapter=adapter
                ).set(float(value or 0))
            elif key.startswith("adapter_requests_"):
                adapter = key[len("adapter_requests_"):]
                self._adapter_series.add(adapter)
                SERVE_ADAPTER_REQUESTS_TOTAL.labels(
                    session=self.sid, adapter=adapter
                ).set(float(value or 0))

    def _finish(self, rid: str, outcome: str) -> None:
        request = self._requests.pop(rid, None)
        if request is not None:
            request.arms.pop(self.sid, None)
            self.served += 1
            SERVE_REQUESTS_TOTAL.labels(outcome=outcome).inc()
            journal_mod.record(
                "stream_done", sid=self.sid, rid=rid, outcome=outcome,
                sync=True,
            )
            self._publish_in_flight()
            self._changed()

    def abandon(self, rid: str) -> None:
        """Drop one request ASSIGNMENT without failing the request object
        or counting an outcome — the hedge-loser path: the same request
        lives on (and completes) under the winning supervisor, so this
        arm only releases its claim and frees the worker lane with a
        fire-and-forget ``serve_cancel``.  Journaled as a ``stream_done``
        so a successor dispatcher does not resume the dead arm."""
        request = self._requests.pop(rid, None)
        if request is None:
            return
        request.arms.pop(self.sid, None)
        journal_mod.record(
            "stream_done", sid=self.sid, rid=rid, outcome="hedge_abandoned",
        )
        self._publish_in_flight()
        client, sid_g = self._client, self._sid_g
        if client is not None and client.alive and not self._closed:
            task = asyncio.ensure_future(client.serve_cancel(sid_g, rid))
            self._bg_tasks.add(task)
            task.add_done_callback(
                lambda t: (
                    self._bg_tasks.discard(t),
                    None if t.cancelled() else t.exception(),
                )
            )
        self._changed()

    async def canary(self, timeout: float = 10.0) -> bool:
        """Cheap readmission probe for a quarantined replica: one agent
        ping round trip (no model work, no lane taken).  True means the
        channel answers promptly — enough to readmit to PROBATION, where
        real traffic re-earns (or re-loses) the health score."""
        client = self._client
        if client is None or not client.alive or self.state != "open":
            return False
        try:
            await client.ping(timeout=timeout)
            return True
        except (AgentError, TransportError, asyncio.TimeoutError, OSError):
            return False

    # -- warm handoff ---------------------------------------------------------

    async def handoff(self, reason: str = "planned") -> bool:
        """Drain-and-reopen: move this session to a FRESH gang with zero
        dropped tokens.

        The replacement generation is leased, staged, and opened while the
        old one is still serving (planned churn — a preemption notice, a
        rebalance — gives us that window); the swap then re-sends every
        in-flight request on the new session, whose restart-from-0 streams
        are spliced on each request's token high-water mark, so callers
        observe exactly-once delivery across the move.  The old session is
        closed best-effort afterwards — it is about to die anyway.

        Returns True when the session now runs on the new generation;
        False when no handoff was possible (closed/failed/already moving,
        or the replacement open failed — the reconnect path still guards
        the latter when the old gang eventually dies).
        """
        if (
            self._closed
            or self._failed is not None
            or self._in_handoff
            or not self._ready.is_set()
        ):
            return False
        self._in_handoff = True
        try:
            old_client, old_sid = self._client, self._sid_g
            old_conns, old_address = list(self._conns), self.address
            obs_events.emit(
                "serve.handoff_started",
                sid=self.sid,
                address=old_address,
                reason=reason,
                in_flight=self.in_flight,
            )
            try:
                binding = await self._dial_generation()
            except asyncio.CancelledError:
                raise
            except BaseException as err:  # noqa: BLE001 - degrade, not fail
                SERVE_HANDOFFS_TOTAL.labels(outcome="failed").inc()
                obs_events.emit(
                    "serve.handoff_failed",
                    sid=self.sid,
                    address=old_address,
                    reason=reason,
                    error=repr(err),
                )
                app_log.warning(
                    "warm handoff of %s failed (%s); the reconnect path "
                    "recovers when the old gang dies", self.sid, err,
                )
                return False
            # Swap: stop the old generation's feed BEFORE replaying so the
            # splice sees one stream at a time, then re-send everything
            # in flight on the fresh session.
            self._adopt(binding)
            if old_client is not None:
                old_client.unwatch_serve(old_sid)
            await self._replay_adapters()
            await self._replay_in_flight()
            self.handoffs += 1
            SERVE_HANDOFFS_TOTAL.labels(outcome="ok").inc()
            obs_events.emit(
                "serve.handoff_complete",
                sid=self.sid,
                from_address=old_address,
                to_address=self.address,
                generation=self.generation,
                replayed=len(self._requests),
                reason=reason,
            )
            # Retire the old generation: a short drain-free close (its
            # requests were replayed; duplicates are spliced away), and
            # its channels leave the pool unless the replacement landed on
            # the very same gang (single-address executors re-lease the
            # pooled transport).
            if old_client is not None:
                try:
                    await old_client.serve_close(old_sid, timeout=5.0)
                except (
                    AgentError, TransportError, asyncio.TimeoutError,
                ) as err:
                    app_log.debug(
                        "post-handoff close of %s failed: %s", old_sid, err
                    )
            shared = {id(c) for c in self._conns}
            leftovers = [c for c in old_conns if id(c) not in shared]
            if leftovers:
                try:
                    await self.executor._discard_workers(leftovers)
                except Exception:  # noqa: BLE001 - teardown is best-effort
                    pass
            self._changed()
            return True
        finally:
            self._in_handoff = False

    # -- supervision / reconnect --------------------------------------------

    async def _supervise(self) -> None:
        """Re-open the session on a fresh gang when its channel dies."""
        while True:
            client = self._client
            if client is None:
                return
            try:
                await client.wait_dead()
            except asyncio.CancelledError:
                raise
            except BaseException as err:  # noqa: BLE001 - AgentError et al.
                death = err
            else:  # pragma: no cover - wait_dead only returns by raising
                death = AgentError("agent channel closed")
            if self._closed:
                return
            if self._client is not client:
                # A warm handoff moved the session while we waited: the
                # death belongs to the RETIRED generation (the preempted
                # gang finally going away), not the live one.
                continue
            if self._in_handoff:
                # The old gang died mid-handoff; let the handoff finish —
                # its replay owns the streams — then watch the new client.
                while self._in_handoff and not self._closed:
                    await asyncio.sleep(0.05)
                if self._client is not client:
                    continue
            obs_events.emit(
                "serve.session_lost",
                sid=self.sid,
                address=self.address,
                error=repr(death),
            )
            if not await self._reconnect(death):
                return

    async def _reconnect(self, death: BaseException) -> bool:
        """Tear down, re-lease, re-open, replay — or fail every stream."""
        self._ready.clear()
        self._changed()
        old_client, old_sid = self._client, self._sid_g
        if old_client is not None:
            old_client.unwatch_serve(old_sid)
        try:
            await self.executor._discard_workers(self._conns)
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass
        fault, fault_label = classify_error(death)
        HEALTH.record_fault(
            self.sid, label=fault_label or fault.name.lower(),
            group=self._health_group,
        )
        failure: BaseException = death
        if fault is FaultClass.TRANSIENT:
            policy = RetryPolicy(
                max_retries=self.retries,
                base_delay=getattr(self.executor, "retry_base_delay", 0.25),
                max_delay=getattr(self.executor, "retry_max_delay", 10.0),
            )
            for attempt in range(self.retries + 1):
                if self._closed:
                    return False
                try:
                    await self._open_generation()
                except asyncio.CancelledError:
                    raise
                except (
                    AgentError, TransportError, ServeError, OSError,
                    ValueError,
                ) as err:
                    failure = err
                    fault, _label = classify_error(err)
                    if fault is not FaultClass.TRANSIENT:
                        break
                    if attempt < self.retries:
                        await asyncio.sleep(policy.delay(attempt))
                else:
                    self.reconnects += 1
                    SERVE_RECONNECTS_TOTAL.inc()
                    obs_events.emit(
                        "serve.session_reopened",
                        sid=self.sid,
                        address=self.address,
                        generation=self.generation,
                        replayed=len(self._requests),
                    )
                    await self._replay_adapters()
                    await self._replay_in_flight()
                    self._ready.set()
                    self._changed()
                    return True
        # Permanent refusal or retry budget spent: the front-end may take
        # the in-flight requests (a replica set drains them onto
        # survivors); otherwise every stream fails with the cause.  New
        # requests are refused either way until the caller closes.
        self._failed = failure
        handled = False
        if self._on_failed is not None:
            try:
                handled = bool(self._on_failed(self, failure))
            except Exception:  # noqa: BLE001 - router hooks never fatal
                app_log.exception("serve on_failed hook failed")
        if not handled:
            for rid, request in list(self._requests.items()):
                self._finish(rid, "error")
                request._fail(ServeError(
                    f"session {self.sid} died and could not be re-opened: "
                    f"{failure}"
                ))
        self._ready.set()
        self._drop_live()
        self._changed()
        return False

    async def _replay_in_flight(self) -> None:
        """Re-send unfinished requests on the fresh generation.

        The new session streams each from idx 0; the splice in
        :meth:`_on_token` drops the already-delivered prefix, so callers
        observe every token exactly once with none lost.
        """
        for request in list(self._requests.values()):
            try:
                await self._send_request(request)
            except BaseException as err:  # noqa: BLE001 - fail just this one
                self._finish(request.rid, "error")
                request._fail(ServeError(
                    f"replay of {request.rid} failed: {err!r}"
                ))

    # -- close --------------------------------------------------------------

    async def close(self, timeout: float = 30.0) -> dict:
        """Drain and close the session; returns the ``serve_closed`` stats.

        The worker finishes every admitted AND queued request before
        acking (their tokens keep streaming during the drain); requests
        that raced a dead channel past the retry budget have already
        failed.  Idempotent.
        """
        if self._closed:
            return {"served": self.served}
        self._closed = True
        if self._supervisor is not None:
            self._supervisor.cancel()
        closed_event: dict = {"served": self.served}
        client, sid_g = self._client, self._sid_g
        if client is not None and self._failed is None:
            try:
                closed_event = await client.serve_close(sid_g, timeout)
            except (AgentError, TransportError, asyncio.TimeoutError) as err:
                app_log.debug("serve_close %s failed: %s", sid_g, err)
            client.unwatch_serve(sid_g)
        for rid, request in list(self._requests.items()):
            self._finish(rid, "error")
            request._fail(ServeError(f"session {self.sid} closed"))
        handles = getattr(self.executor, "_serve_handles", None)
        if handles is not None:
            handles.pop(self.sid, None)
        journal_mod.record("session_closed", sid=self.sid, sync=True)
        self._drop_live()
        obs_events.emit(
            "serve.session_closed",
            sid=self.sid,
            served=int(closed_event.get("served") or 0),
        )
        self._changed()
        return closed_event

    def _drop_live(self) -> None:
        if self._counted_live:
            self._counted_live = False
            SERVE_SESSIONS.dec()
            if self._pool is not None:
                self._pool.release()
        # Stale-series reap: a retired session's gauges must leave the
        # registry with it, or /metrics grows one orphan series pair per
        # session for the process lifetime under session churn.  The
        # worker-occupancy series go too once no other live session
        # shares the worker (its heartbeats stop carrying a serve block
        # the moment the last session closes, freezing stale values).
        # One forced history sample FIRST: a short-lived session could
        # otherwise live and die entirely between two sampler ticks,
        # leaving no trace of its gauges in the /history timeline.
        try:
            from ..obs.history import HISTORY

            HISTORY.sample(force=True)
        except Exception:  # noqa: BLE001 - observability never fatal
            pass
        HEALTH.drop(self.sid)
        SERVE_QUEUE_DEPTH.remove(session=self.sid)
        SERVE_TOKENS_PER_S.remove(session=self.sid)
        SERVE_PREFIX_HITS.remove(session=self.sid)
        SERVE_PREFIX_MISSES.remove(session=self.sid)
        SERVE_PREFILL_POSITIONS.remove(session=self.sid)
        SERVE_SPEC_ACCEPT_RATE.remove(session=self.sid)
        for mode in _SERVING_MODES:
            SERVE_MODE_TOKENS.remove(session=self.sid, mode=mode)
        # Adapter label set is OPEN — reap exactly the series this
        # supervisor created (tracked in _on_stats), plus the per-session
        # attachment gauge, so a churned multi-adapter session leaves no
        # stale adapter series behind.
        SERVE_ADAPTERS.remove(session=self.sid)
        for adapter in self._adapter_series:
            SERVE_ADAPTER_TOKENS.remove(session=self.sid, adapter=adapter)
            SERVE_ADAPTER_REQUESTS_TOTAL.remove(
                session=self.sid, adapter=adapter
            )
        self._adapter_series.clear()
        if self.replica_of is not None:
            SERVE_REPLICA_IN_FLIGHT.remove(
                set=self.replica_of[0], replica=self.replica_of[1]
            )
            SERVE_REPLICA_REQUESTS_TOTAL.remove(
                set=self.replica_of[0], replica=self.replica_of[1]
            )
        handles = getattr(self.executor, "_serve_handles", None) or {}
        if self.address and not any(
            h is not self and getattr(h, "address", "") == self.address
            for h in list(handles.values())
        ):
            for state in ("sessions", "slots", "busy", "queued"):
                SERVE_WORKER_SLOTS.remove(worker=self.address, state=state)

    # -- profiling ----------------------------------------------------------

    async def capture_profile(self, duration_s: float = 2.0) -> dict:
        """Capture a ``jax.profiler`` trace of this session's resident
        runtime while it serves live traffic.

        Records for ``duration_s`` inside the worker process holding the
        model (the pool server, or the native agent's ``--serve-child``
        runner), stages the trace back as a content-addressed artifact and
        digest-verifies it — no launch fallback, no second process.
        Raises :class:`ServeError` when the capture fails (session down,
        another trace already active, jax unavailable on the worker).
        """
        await self._await_ready()
        client, conns = self._client, self._conns
        if client is None or not conns:
            raise ServeError(f"session {self.sid} has no live runtime")
        profile_id = f"{self.sid}-prof{uuid.uuid4().hex[:6]}"
        sid = self._sid_g if client.mode != "pool" else ""
        started = await self.executor._start_resident_profile(
            client, profile_id, sid=sid
        )
        if not started:
            raise ServeError(
                f"profiler start refused on session {self.sid} (busy or "
                "unavailable)"
            )
        info = await self.executor._finish_capture(
            client, conns[0], profile_id, duration_s, sid=sid
        )
        if not info:
            raise ServeError(
                f"profile capture on session {self.sid} produced no "
                "artifact"
            )
        return {"sid": self.sid, "duration_s": float(duration_s), **info}
