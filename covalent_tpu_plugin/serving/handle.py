"""Dispatcher side of the persistent serving tier: the one-session front.

A :class:`ServeHandle` multiplexes any number of concurrent callers onto
ONE resident serving session: the model factory is cloudpickled once,
shipped by CAS digest, and opened on a warm gang's resident runtime
(``serve_open``); every :meth:`ServeHandle.request` is then one
``serve_request`` write on the held-open agent channel, with the response
streamed back incrementally over the telemetry side-band as
``serve.token`` records — time-to-first-token is one decode chunk, not
end-of-batch.

Everything stateful about the session itself — reconnect after channel
death, exactly-once stream replay on the cumulative ``idx`` splice,
backpressure classification, capacity pinning, gauge lifecycle — lives
in :class:`~.supervisor.SessionSupervisor`; the handle is the trivial
router in front of exactly one of them (request-id assignment and
submission).  :class:`~.replicas.ReplicaSet` is the N-session front over
the same supervisor, which is why neither duplicates any replay
machinery.

The public surface (:func:`open_session`, :class:`ServeHandle`,
:class:`ServeRequest`, the error types) is unchanged from PR 9 — a
one-replica deployment reads exactly as before.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any

import cloudpickle

from ..cache import bytes_digest
from ..obs.trace import Span
from .supervisor import (
    ServeError,
    ServeRequest,
    ServeRequestRejected,
    SessionSupervisor,
)

__all__ = [
    "ServeError",
    "ServeRequest",
    "ServeRequestRejected",
    "ServeHandle",
    "open_session",
]


class ServeHandle:
    """One resident serving session and every caller multiplexed onto it.

    Build through :func:`open_session` (or ``Pool.open_session``) — the
    constructor only records configuration; :meth:`_open` does the work.
    All methods must run on the executor's event loop.
    """

    def __init__(
        self,
        executor: Any,
        factory: Any,
        *,
        queue_max: int | None = None,
        default_deadline_s: float | None = None,
        stats_interval_s: float | None = None,
        open_timeout_s: float | None = None,
        retries: int | None = None,
        name: str = "",
        pool: Any = None,
    ) -> None:
        self.executor = executor
        self.factory = factory
        self.sid = name or f"serve-{uuid.uuid4().hex[:10]}"
        self._sup = SessionSupervisor(
            executor,
            sid=self.sid,
            queue_max=queue_max,
            default_deadline_s=default_deadline_s,
            stats_interval_s=stats_interval_s,
            open_timeout_s=open_timeout_s,
            retries=retries,
            pool=pool,
        )
        self._next_rid = 0

    # -- supervisor views (the pre-split attribute surface) -----------------

    @property
    def supervisor(self) -> SessionSupervisor:
        return self._sup

    @property
    def state(self) -> str:
        return self._sup.state

    @property
    def in_flight(self) -> int:
        return self._sup.in_flight

    @property
    def slots(self) -> int:
        return self._sup.slots

    @property
    def generation(self) -> int:
        return self._sup.generation

    @property
    def served(self) -> int:
        return self._sup.served

    @property
    def reconnects(self) -> int:
        return self._sup.reconnects

    @property
    def handoffs(self) -> int:
        return self._sup.handoffs

    async def handoff(self, reason: str = "planned") -> bool:
        """Warm drain-and-reopen onto a fresh gang (planned churn): the
        replacement session opens BEFORE the old one is retired and every
        in-flight stream is spliced exactly-once across the move."""
        return await self._sup.handoff(reason=reason)

    @property
    def opened_at(self) -> float:
        return self._sup.opened_at

    @property
    def stats(self) -> dict[str, Any]:
        return self._sup.stats

    @property
    def address(self) -> str:
        return self._sup.address

    @property
    def queue_max(self) -> int:
        return self._sup.queue_max

    @property
    def default_deadline_s(self) -> float:
        return self._sup.default_deadline_s

    @property
    def retries(self) -> int:
        return self._sup.retries

    def status(self) -> dict[str, Any]:
        """This session's contribution to ``/status`` / ``pool.status()``."""
        return self._sup.status()

    # -- open ---------------------------------------------------------------

    async def _open(self) -> "ServeHandle":
        """First open: stage the factory, lease a gang, start supervision."""
        with Span("serve.open", {"sid": self.sid}):
            payload = await asyncio.to_thread(
                cloudpickle.dumps, self.factory
            )
            await self._sup.open(payload, bytes_digest(payload))
        return self

    # -- requests -----------------------------------------------------------

    async def request(
        self,
        prompt,
        params: dict | None = None,
        deadline_s: float | None = None,
        tenant: str = "",
    ) -> ServeRequest:
        """Submit one request; returns its :class:`ServeRequest` stream.

        Fire-and-stream: this only writes the ``serve_request`` line (plus
        an await of any in-progress reconnect); tokens arrive on the
        side-band.  ``params`` may carry per-request ``max_new_tokens``;
        sampling statics are session-wide (the compiled programs key on
        them).
        """
        self._next_rid += 1
        request = ServeRequest(
            f"{self.sid}-r{self._next_rid}",
            [int(t) for t in prompt],
            params,
            (
                self._sup.default_deadline_s
                if deadline_s is None
                else deadline_s
            ),
            tenant,
        )
        return await self._sup.submit(request)

    # -- multi-adapter registry ---------------------------------------------

    @property
    def adapters(self) -> dict[str, str]:
        """name -> content digest of every adapter attached here."""
        return self._sup.adapters

    async def attach_adapter(
        self,
        name: str,
        payload: Any = None,
        *,
        path: str = "",
        digest: str = "",
        rank: int | None = None,
        alpha: float = 16.0,
        timeout_s: float | None = None,
    ) -> dict:
        """Splice a named LoRA adapter into the RUNNING session (no
        restart, no recompile; a re-attach of an existing name is a hot
        swap — in-flight requests finish on the old generation).  See
        :meth:`~.supervisor.SessionSupervisor.attach_adapter`."""
        return await self._sup.attach_adapter(
            name, payload, path=path, digest=digest, rank=rank,
            alpha=alpha, timeout_s=timeout_s,
        )

    async def detach_adapter(
        self, name: str, timeout_s: float = 30.0
    ) -> dict:
        """Remove a named adapter from the running session."""
        return await self._sup.detach_adapter(name, timeout_s=timeout_s)

    # -- close --------------------------------------------------------------

    async def close(self, timeout: float = 30.0) -> dict:
        """Drain and close the session; returns the ``serve_closed`` stats.

        The worker finishes every admitted AND queued request before
        acking (their tokens keep streaming during the drain).  Idempotent.
        """
        return await self._sup.close(timeout)

    # -- profiling ----------------------------------------------------------

    async def capture_profile(self, duration_s: float = 2.0) -> dict:
        """Capture a ``jax.profiler`` trace of this session's resident
        runtime while it serves live traffic (see
        :meth:`~.supervisor.SessionSupervisor.capture_profile`)."""
        return await self._sup.capture_profile(duration_s)


async def open_session(
    target: Any,
    factory: Any,
    *,
    queue_max: int | None = None,
    default_deadline_s: float | None = None,
    stats_interval_s: float | None = None,
    open_timeout_s: float | None = None,
    retries: int | None = None,
    name: str = "",
) -> ServeHandle:
    """Open a resident serving session; returns the live handle.

    ``target`` is a ``TPUExecutor`` or a fleet ``Pool`` (the session then
    pins one of the pool's capacity slots for its lifetime).  ``factory``
    is a zero-arg callable returning the serving engine (see
    ``models/serve.lm_engine_factory``); it is cloudpickled, shipped by
    CAS digest, and called ONCE inside the resident worker — model load
    and compile happen there, amortized over every request.  Defaults for
    the knobs come from ``COVALENT_TPU_SERVE_{QUEUE_MAX, DEADLINE_S,
    STATS_INTERVAL_S, OPEN_TIMEOUT_S, RETRIES}``.
    """
    pool = None
    executor = target
    if hasattr(target, "spec") and hasattr(target, "executor"):
        pool = target
        executor = target.executor
    handle = ServeHandle(
        executor,
        factory,
        queue_max=queue_max,
        default_deadline_s=default_deadline_s,
        stats_interval_s=stats_interval_s,
        open_timeout_s=open_timeout_s,
        retries=retries,
        name=name,
        pool=pool,
    )
    return await handle._open()
