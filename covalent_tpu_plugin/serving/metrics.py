"""Serving-tier metrics: request outcomes, stream latency, session load.

One module so the handle, the fleet status views, and the bench phase all
move the same series.  Label cardinality is deliberately low: ``outcome``
is a closed set, and per-session gauges key on the HANDLE sid (stable
across reconnect generations), not the per-generation remote session id.
"""

from __future__ import annotations

from ..obs.metrics import REGISTRY

#: Terminal accounting for every request submitted through a handle.
#: ``ok`` — full stream delivered; ``deadline`` — lane reclaimed at its
#: deadline (partial stream, ``error`` marker on the final chunk);
#: ``shed`` — refused at admission (bounded queue full); ``rejected`` —
#: refused for any other reason (unknown session, engine refusal);
#: ``error`` — stream failed (token gap, session death past its retry
#: budget, close with requests in flight).
SERVE_REQUESTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_requests_total",
    "Serving-session requests by terminal outcome",
    ("outcome",),
)

SERVE_TOKENS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_tokens_total",
    "Tokens streamed back to serving-session callers",
)

SERVE_SESSIONS = REGISTRY.gauge(
    "covalent_tpu_serve_sessions",
    "Live serving sessions held open by this dispatcher",
)

SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "covalent_tpu_serve_queue_depth",
    "Worker-side admission queue depth per serving session",
    ("session",),
)

SERVE_TOKENS_PER_S = REGISTRY.gauge(
    "covalent_tpu_serve_tokens_per_s",
    "Worker-reported aggregate decode throughput per serving session",
    ("session",),
)

SERVE_RECONNECTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_reconnects_total",
    "Serving sessions re-opened after a channel/worker death",
)

SERVE_HANDOFFS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_handoffs_total",
    "Warm session handoffs (replacement opened BEFORE the old gang died)",
    ("outcome",),
)

#: Time-to-first-token, submit -> first streamed chunk.  The streaming
#: side-band's whole point: TTFT must sit near one decode chunk, not at
#: end-of-response - the bench phase asserts exactly that.
SERVE_TTFT_SECONDS = REGISTRY.histogram(
    "covalent_tpu_serve_ttft_seconds",
    "Serving-request time to first streamed token",
)

SERVE_REQUEST_SECONDS = REGISTRY.histogram(
    "covalent_tpu_serve_request_seconds",
    "Serving-request full-stream latency (submit -> final chunk)",
)

#: Dispatcher-side view of worker slot occupancy, fed by the heartbeat
#: backhaul (a serving worker's beats carry its ``serve`` block).
SERVE_WORKER_SLOTS = REGISTRY.gauge(
    "covalent_tpu_serve_worker_slots",
    "Serving slot occupancy reported by worker heartbeats",
    ("worker", "state"),
)

# -- replica sets -----------------------------------------------------------
# Per-replica series key on (set, replica) — the replica index is stable
# across reconnect generations, like the session sid — and are removed by
# the supervisor's ``_drop_live`` when the replica retires, so a scaled-
# down set leaves no stale series behind (the same reap contract the
# per-session gauges follow).  ``outcome`` on the router counter is a
# closed set: ``sticky`` (pinned sid honored), ``prefix_affinity``
# (steered to the replica whose engine prefix tree is warm for the
# prompt), ``least_loaded`` (fresh placement),
# ``queued`` (no open replica had headroom — DRR queue),
# ``shed`` (router admission bound hit), ``failover`` (re-routed off a
# dead replica).

SERVE_REPLICAS = REGISTRY.gauge(
    "covalent_tpu_serve_replicas",
    "Replica-set member sessions by state",
    ("set", "state"),
)

SERVE_REPLICA_REQUESTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_replica_requests_total",
    "Requests submitted to each replica of a serving replica set",
    ("set", "replica"),
)

SERVE_REPLICA_IN_FLIGHT = REGISTRY.gauge(
    "covalent_tpu_serve_replica_in_flight",
    "In-flight requests assigned to each replica of a serving replica set",
    ("set", "replica"),
)

SERVE_ROUTER_DECISIONS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_router_decisions_total",
    "Replica-set router placement decisions by outcome",
    ("outcome",),
)

#: The router's own DRR queue depth.  Deliberately NOT the fleet
#: scheduler's covalent_tpu_queue_depth: the underlying FairWorkQueue is
#: shared code, and two queues writing one gauge would overwrite (and on
#: lane retirement, delete) each other's per-tenant series.
SERVE_ROUTER_QUEUE_DEPTH = REGISTRY.gauge(
    "covalent_tpu_serve_router_queue_depth",
    "Requests waiting in a replica-set router's per-tenant DRR queue",
    ("tenant",),
)

#: The router's whole per-request cost: the ``serve_scale`` bench phase
#: asserts its median under 1 ms — scaling out must not move the
#: dispatch tax it removed back into the routing layer.
SERVE_ROUTER_DECISION_SECONDS = REGISTRY.histogram(
    "covalent_tpu_serve_router_decision_seconds",
    "Replica-set router per-request decision latency",
    buckets=(
        0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05, 0.25,
    ),
)

# -- engine prefix counters (per-session, fed by serve.stats) ---------------
# ContinuousEngine.stats counters surfaced as serving metrics: set from
# every worker stats record and reaped by the supervisor's ``_drop_live``
# like serve_tokens_per_s — without these the prefix tree and the prefill
# accounting are engine-local and invisible to /metrics, /history, SLOs.

SERVE_PREFIX_HITS = REGISTRY.gauge(
    "covalent_tpu_serve_prefix_hits",
    "Engine prefix-tree admission hits per serving session",
    ("session",),
)

SERVE_PREFIX_MISSES = REGISTRY.gauge(
    "covalent_tpu_serve_prefix_misses",
    "Engine prefix-tree admission misses per serving session",
    ("session",),
)

SERVE_PREFILL_POSITIONS = REGISTRY.gauge(
    "covalent_tpu_serve_prefill_positions",
    "Prefill positions paid by a serving session's engine "
    "(suffix buckets on prefix hits, full-prompt buckets on misses)",
    ("session",),
)

# -- speculative + quantized decoding ---------------------------------------
# Per-session series fed by the engine's spec/mode counters through the
# worker stats backhaul, and reaped by the supervisor's ``_drop_live``
# with the other per-session gauges (the PR-10 stale-series contract —
# ``mode`` is a CLOSED set (models/quant.py SERVING_MODES), so the reap
# can enumerate it).  The accept rate is draft agreement
# (spec_accepted / spec_proposed), cumulative over the session.

SERVE_SPEC_ACCEPT_RATE = REGISTRY.gauge(
    "covalent_tpu_serve_spec_accept_rate",
    "Speculative-decode draft accept rate per serving session "
    "(accepted / proposed draft tokens, cumulative)",
    ("session",),
)

SERVE_MODE_TOKENS = REGISTRY.gauge(
    "covalent_tpu_serve_mode_tokens",
    "Output tokens per serving session by decode-mode lane group "
    "(fp / int8 / kv_quant / full_quant)",
    ("session", "mode"),
)

# -- multi-adapter serving ---------------------------------------------------
# One engine, N LoRA adapters (PR 20): per-adapter traffic series are
# fed by the engine's adapter_* stats counters through the worker stats
# backhaul.  Unlike the decode-mode set, the ``adapter`` label set is
# OPEN (operators name adapters) — the supervisor therefore tracks which
# (session, adapter) pairs it created and ``_drop_live`` reaps exactly
# those, never enumerating.  Attach latency is dispatcher-measured wall
# time: CAS stage + wire round trip + engine splice.

SERVE_ADAPTERS = REGISTRY.gauge(
    "covalent_tpu_serve_adapters",
    "LoRA adapters currently attached per serving session",
    ("session",),
)

SERVE_ADAPTER_TOKENS = REGISTRY.gauge(
    "covalent_tpu_serve_adapter_tokens",
    "Output tokens per serving session by adapter lane "
    "(cumulative; 'base' is the un-adapted lane)",
    ("session", "adapter"),
)

SERVE_ADAPTER_REQUESTS_TOTAL = REGISTRY.gauge(
    "covalent_tpu_serve_adapter_requests_total",
    "Requests admitted per serving session by adapter "
    "(cumulative engine counter, gauge-backed so the worker restates "
    "it on every stats tick)",
    ("session", "adapter"),
)

SERVE_ADAPTER_ATTACHES_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_adapter_attaches_total",
    "Adapter attach/detach operations by outcome",
    ("op", "outcome"),
)

SERVE_ADAPTER_ATTACH_SECONDS = REGISTRY.histogram(
    "covalent_tpu_serve_adapter_attach_seconds",
    "Live adapter attach wall time: CAS stage -> engine splice ack",
    buckets=(
        0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
        30.0,
    ),
)

# -- disaggregated prefill/decode -------------------------------------------
# The KV transfer plane: prefill replicas package admission prefill as
# content-addressed KV bundles; decode replicas import them and go
# straight to decode.  ``outcome`` is a closed set: ``ok`` (bundle
# fetched, digest-verified), ``digest_mismatch`` (torn/stale transfer —
# degraded to full prefill), ``error`` (prefill tier unreachable or
# refused — degraded), ``fallback`` (no prefill tier routable).  ``path``
# on the request counter: ``disagg`` (KV road taken), ``direct`` (short
# prompt, classic road), ``fallback`` (eligible but degraded).

SERVE_KV_TRANSFERS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_kv_transfers_total",
    "KV bundle transfers between the prefill and decode tiers by outcome",
    ("outcome",),
)

SERVE_KV_TRANSFER_BYTES_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_kv_transfer_bytes_total",
    "Serialized KV bundle bytes shipped from the prefill tier",
)

SERVE_KV_TRANSFER_SECONDS = REGISTRY.histogram(
    "covalent_tpu_serve_kv_transfer_seconds",
    "Prefill-tier round trip: serve_prefill submit -> verified bundle",
    buckets=(
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
        2.5, 5.0,
    ),
)

SERVE_DISAGG_REQUESTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_disagg_requests_total",
    "Requests through a disaggregated set by road taken",
    ("path",),
)

# -- tail-latency hedging ----------------------------------------------------
# The gray-failure defense's request plane: an idempotent request whose
# TTFT exceeds the set's adaptive percentile is speculatively re-issued
# on the next-healthiest replica.  ``outcome`` is a closed set:
# ``launched`` (hedge sent), ``won`` (hedge arm fed the first token —
# the primary was cancelled), ``lost`` (primary answered first — the
# hedge was cancelled), ``budget`` (TTFT fired but the <5% budget was
# spent), ``no_target`` (no healthier routable replica to hedge onto).

SERVE_HEDGES_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_hedges_total",
    "Tail-latency hedge decisions by outcome",
    ("outcome",),
)
