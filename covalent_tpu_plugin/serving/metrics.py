"""Serving-tier metrics: request outcomes, stream latency, session load.

One module so the handle, the fleet status views, and the bench phase all
move the same series.  Label cardinality is deliberately low: ``outcome``
is a closed set, and per-session gauges key on the HANDLE sid (stable
across reconnect generations), not the per-generation remote session id.
"""

from __future__ import annotations

from ..obs.metrics import REGISTRY

#: Terminal accounting for every request submitted through a handle.
#: ``ok`` — full stream delivered; ``deadline`` — lane reclaimed at its
#: deadline (partial stream, ``error`` marker on the final chunk);
#: ``shed`` — refused at admission (bounded queue full); ``rejected`` —
#: refused for any other reason (unknown session, engine refusal);
#: ``error`` — stream failed (token gap, session death past its retry
#: budget, close with requests in flight).
SERVE_REQUESTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_requests_total",
    "Serving-session requests by terminal outcome",
    ("outcome",),
)

SERVE_TOKENS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_tokens_total",
    "Tokens streamed back to serving-session callers",
)

SERVE_SESSIONS = REGISTRY.gauge(
    "covalent_tpu_serve_sessions",
    "Live serving sessions held open by this dispatcher",
)

SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "covalent_tpu_serve_queue_depth",
    "Worker-side admission queue depth per serving session",
    ("session",),
)

SERVE_TOKENS_PER_S = REGISTRY.gauge(
    "covalent_tpu_serve_tokens_per_s",
    "Worker-reported aggregate decode throughput per serving session",
    ("session",),
)

SERVE_RECONNECTS_TOTAL = REGISTRY.counter(
    "covalent_tpu_serve_reconnects_total",
    "Serving sessions re-opened after a channel/worker death",
)

#: Time-to-first-token, submit -> first streamed chunk.  The streaming
#: side-band's whole point: TTFT must sit near one decode chunk, not at
#: end-of-response - the bench phase asserts exactly that.
SERVE_TTFT_SECONDS = REGISTRY.histogram(
    "covalent_tpu_serve_ttft_seconds",
    "Serving-request time to first streamed token",
)

SERVE_REQUEST_SECONDS = REGISTRY.histogram(
    "covalent_tpu_serve_request_seconds",
    "Serving-request full-stream latency (submit -> final chunk)",
)

#: Dispatcher-side view of worker slot occupancy, fed by the heartbeat
#: backhaul (a serving worker's beats carry its ``serve`` block).
SERVE_WORKER_SLOTS = REGISTRY.gauge(
    "covalent_tpu_serve_worker_slots",
    "Serving slot occupancy reported by worker heartbeats",
    ("worker", "state"),
)
