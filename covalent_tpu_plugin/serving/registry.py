"""CAS-keyed adapter registry: named LoRA bundles for live sessions.

Multi-adapter serving (PR 20) splits one model into a resident base and
N cheap rank-r adapters; this module owns the *artifact* side of that
split on the dispatcher:

* **Wire form** — an adapter travels as its ordered ``lora_a``/``lora_b``
  leaf list (:func:`~..models.lora.adapter_leaves` extracts it from a
  training tree), packed by :func:`pack_adapter` into a versioned
  cloudpickle the worker's engine splices directly into its bank.  The
  leaf list (not the params tree) is the portable form: float and
  quantized serving twins of one architecture share it.
* **Identity** — two digests per bundle, deliberately distinct.  The
  *file* digest (sha256 of the pickled bytes) is the CAS key: it names
  the staged artifact and is what the worker verifies before unpickling
  anything.  The *content* digest (:func:`adapter_content_digest`:
  sha256 over each leaf's shape, dtype, and bytes — bit-identical to
  ``models.lora.adapter_digest``, reimplemented here so the dispatcher
  never imports jax) is the adapter's semantic identity: it survives
  re-pickling, names the generation in journal records, and is how a
  disaggregated KV bundle detects a stale adapter after a hot swap.
* **Book-keeping** — :class:`AdapterRegistry` is a local name → record
  book over a CAS directory: ``put`` packs/stages/deduplicates, ``get``
  answers with everything a ``serve_attach`` needs (path + both
  digests).  Supervisors and replica sets consult it; the journal
  persists the per-session attachment view for crash recovery.

Nothing here touches jax: like the rest of the serving tier this runs
in routing processes that must never drag an accelerator runtime in.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Iterable

import cloudpickle

from ..cache import bytes_digest

__all__ = [
    "AdapterRegistry",
    "adapter_content_digest",
    "pack_adapter",
    "unpack_adapter",
]

#: Bundle schema version: the worker refuses versions it does not know
#: instead of guessing at leaf semantics.
BUNDLE_VERSION = 1


def adapter_content_digest(leaves: Iterable[Any]) -> str:
    """Content digest of an ordered adapter leaf list.

    Bit-identical to ``models.lora.adapter_digest`` (sha256 over each
    leaf's shape, dtype, and bytes) so a digest computed here — on the
    dispatcher, from numpy arrays — matches what the worker's engine
    announces for the same adapter after splicing it in.
    """
    import numpy as np

    h = hashlib.sha256()
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(repr((tuple(arr.shape), str(arr.dtype))).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def pack_adapter(
    leaves: Iterable[Any],
    name: str = "",
    rank: int | None = None,
    alpha: float = 16.0,
) -> bytes:
    """Pack an ordered adapter leaf list into its CAS bundle bytes.

    ``rank`` defaults to the trailing dimension of the first leaf —
    ``lora_a`` leaves sort first in the canonical flatten order, and
    their shape is ``(..., features, rank)``.  The content digest is
    computed here and carried INSIDE the bundle, so the worker can
    install without re-hashing and a reader can identify a bundle
    without the leaves' originating tree.
    """
    import numpy as np

    arrs = [np.asarray(leaf) for leaf in leaves]
    if not arrs:
        raise ValueError("adapter bundle needs at least one leaf")
    if rank is None:
        rank = int(arrs[0].shape[-1])
    return cloudpickle.dumps({
        "v": BUNDLE_VERSION,
        "name": str(name),
        "rank": int(rank),
        "alpha": float(alpha),
        "leaves": arrs,
        "digest": adapter_content_digest(arrs),
    })


def unpack_adapter(data: bytes) -> dict:
    """Decode one packed bundle; validates shape and version."""
    obj = cloudpickle.loads(data)
    if not isinstance(obj, dict) or "leaves" not in obj:
        raise ValueError("not an adapter bundle (no leaves)")
    version = int(obj.get("v") or 0)
    if version != BUNDLE_VERSION:
        raise ValueError(
            f"adapter bundle version {version} is not supported "
            f"(expected {BUNDLE_VERSION})"
        )
    return obj


class AdapterRegistry:
    """Local name → adapter-record book over a CAS directory.

    One record per *name*; re-``put`` of a name with different content
    is a generation swap (the old record is replaced, its CAS file left
    for any session still referencing it — CAS files are immutable and
    the cache's usual pruning owns their lifetime).  Thread-safe: the
    serving tier touches this from the event loop and from
    ``asyncio.to_thread`` staging helpers.
    """

    def __init__(self, cache_dir: str) -> None:
        self.cache_dir = str(cache_dir)
        self._dir = os.path.join(self.cache_dir, "cas")
        self._records: dict[str, dict] = {}
        self._lock = threading.Lock()

    # -- write ---------------------------------------------------------------

    def put(
        self,
        name: str,
        payload: Any,
        rank: int | None = None,
        alpha: float = 16.0,
    ) -> dict:
        """Register ``name`` → a packed bundle; returns its record.

        ``payload`` is packed bundle bytes, a leaf list/tuple, or an
        already-decoded bundle dict — anything else refuses.  The bytes
        land in the CAS (digest-named, write-once) and the record holds
        both identities plus the local path a supervisor stages from.
        """
        if isinstance(payload, (bytes, bytearray)):
            data = bytes(payload)
            bundle = unpack_adapter(data)
        elif isinstance(payload, dict):
            bundle = dict(payload)
            data = pack_adapter(
                bundle["leaves"], name=name,
                rank=bundle.get("rank") or rank,
                alpha=float(bundle.get("alpha") or alpha),
            )
            bundle = unpack_adapter(data)
        elif isinstance(payload, (list, tuple)):
            data = pack_adapter(payload, name=name, rank=rank, alpha=alpha)
            bundle = unpack_adapter(data)
        else:
            raise ValueError(
                f"adapter payload must be bundle bytes, a bundle dict, or "
                f"a leaf list, got {type(payload).__name__}"
            )
        digest = bytes_digest(data)
        path = os.path.join(self._dir, f"{digest}.lora")
        if not os.path.exists(path):
            os.makedirs(self._dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}.{os.urandom(4).hex()}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        record = {
            "name": str(name),
            "digest": digest,
            "content": str(bundle.get("digest") or ""),
            "path": path,
            "size": len(data),
            "rank": int(bundle.get("rank") or 0),
            "alpha": float(bundle.get("alpha") or 0.0),
        }
        with self._lock:
            self._records[str(name)] = record
        return dict(record)

    def remove(self, name: str) -> dict | None:
        """Drop a name from the book (CAS file stays; it is immutable
        and may still back a live session's attachment)."""
        with self._lock:
            return self._records.pop(str(name), None)

    # -- read ----------------------------------------------------------------

    def get(self, name: str) -> dict | None:
        with self._lock:
            record = self._records.get(str(name))
        return dict(record) if record is not None else None

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._records)

    def records(self) -> dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._records.items()}

    def digests(self) -> dict[str, str]:
        """name → *content* digest (the semantic identity view the
        scheduler's adapter-affinity rank and /status consume)."""
        with self._lock:
            return {
                k: str(v.get("content") or "")
                for k, v in self._records.items()
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def __contains__(self, name: object) -> bool:
        with self._lock:
            return str(name) in self._records
